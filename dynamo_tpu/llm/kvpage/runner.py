"""PagedEngine: serve contexts far beyond the device KV pool.

The virtual-memory model (docs/long_context.md):

- **Chunked prefill with seal-and-demote.** Each prefill chunk writes its
  KV into device pages leased from the engine's pool; once the chunk's
  dispatch has been issued, full (sealed) blocks beyond the hot-window
  budget are demoted d2h into the host tier (``TieredKvCache``) — pinned,
  because a demoted decode working set is state, not cache — and their
  device pages return to the pool. Device residency therefore stays
  bounded at ``budget`` pages for ANY context length. The d2h gather is
  enqueued against the post-write pool arrays, so JAX sequences it after
  the writing dispatch by data dependency (a one-hop version of the
  cluster write-through's two-step ratchet: here the runner owns the
  issue order, so it demotes the moment the write is in the queue).
- **Batched decode over windowed working sets.** Up to
  ``DYN_KVPAGE_BATCH`` sequences decode CONCURRENTLY, each owning an
  equal share of the device page budget (its lane). One window step runs
  hot-window attention for every lane in a single dispatch (each lane
  reads its own resident slots through the pool), then merges cold
  segments lane-stacked into a shared ``[B, ...]`` staging slot — one
  h2d upload per (layer, segment step) covers every lane, and the
  :class:`~.pager.PageScheduler` round-robins segment assembly across
  lanes so each keeps its own prefetch double-buffer: one lane's page-in
  overlaps the other lanes' attention dispatches. Faults degrade to
  counted synchronous uploads on the faulting lane only. Sampling state
  (PRNG key, penalty counts) is a per-lane row of persistent ``[B]``
  arrays, masked so padded rows never advance — every lane's token
  stream is byte-identical to a batch-1 run and to the dense engine.
- **Prefix reuse for free.** Demoted blocks carry their chained sequence
  hashes, so a repeated long prompt pins matching tier blocks at
  admission and skips recomputing them; at release the pins drop and the
  blocks become ordinary LRU tier content (servable to cluster peers).

Scheduling: ``advance()`` performs one unit of work per engine step —
one prefill chunk (lanes round-robin) or one chained decode window
across every decode-ready lane, prefill FIRST when both kinds of work
exist: a window costs nearly the same at one lane as at full occupancy
(uploads and dispatches are lane-stacked), so filling an admitted lane
before decoding maximizes window occupancy and the newcomer's TTFT,
while the decode stall stays bounded by admission (at most ``batch``
resident prefills). Admission is byte-honest across lanes: every admitted
request's working set is reserved against the host tier up front (the
unpinned remainder counts until the lane has demoted it), so N lanes
cannot jointly over-commit what single-lane admission would refuse.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...llm.kvbm.pool import OutOfBlocks
from ...llm.kvbm.tiers import OutOfTierSpace
from ...obs.flows import record_flow
from ...llm.protocols.common import BackendInput, FinishReason
from ...llm.tokens import TokenSequence, chain_hash, hash_tokens, \
    lora_chain_root
from ...utils.knobs import env_float as _env_float
from ...utils.prometheus import stage_metrics
from .pager import KvPageMiss, PageinPlan, PageScheduler
from .programs import PagedPrograms

log = logging.getLogger("dynamo_tpu.kvpage")


@dataclass
class PagedConfig:
    """Resolved ``DYN_KVPAGE_*`` surface (engine-config fields win over
    env knobs; a zero/unset budget disables the plane entirely)."""

    budget: int                 # device pages the paged lane may lease
    seg_pages: int              # blocks per cold staging segment
    prefetch: int               # segments assembled ahead (0 = sync)
    max_context: int            # paged-lane context ceiling, tokens
    batch: int                  # concurrent paged decode lanes

    @classmethod
    def resolve(cls, cfg) -> Optional["PagedConfig"]:
        budget = cfg.kvpage_budget
        if budget is None:
            budget = int(_env_float("DYN_KVPAGE_DEVICE_BUDGET", 0))
        if budget <= 0:
            return None
        seg = cfg.kvpage_seg_pages or int(
            _env_float("DYN_KVPAGE_SEG_PAGES", 8))
        prefetch = cfg.kvpage_prefetch
        if prefetch is None:
            prefetch = int(_env_float("DYN_KVPAGE_PREFETCH", 2))
        max_ctx = cfg.kvpage_max_context or int(
            _env_float("DYN_KVPAGE_MAX_CONTEXT", 131072))
        batch = cfg.kvpage_batch or int(_env_float("DYN_KVPAGE_BATCH", 1))
        return cls(budget=int(budget), seg_pages=max(1, int(seg)),
                   prefetch=max(0, int(prefetch)),
                   max_context=int(max_ctx), batch=max(1, int(batch)))


@dataclass
class _PagedSeq:
    seq_id: str
    request: BackendInput
    prompt: List[int]
    tokseq: TokenSequence
    lane: int = 0               # row in the batched decode dispatch
    # device pages for blocks [first_res, first_res + len(resident));
    # the resident span is always the contiguous tail of the context
    resident: List[int] = field(default_factory=list)
    first_res: int = 0
    pinned: List[int] = field(default_factory=list)   # demoted block hashes
    reserve_blocks: int = 0     # admission reservation (working set)
    seed: int = 0
    total_len: int = 0          # tokens written to the KV (pool or tier)
    prefill_done: int = 0
    generated: int = 0
    last_token: int = 0
    cum_logprob: float = 0.0
    cancelled: bool = False


class PagedEngine:
    """The paged lane of one :class:`~...engine.engine.EngineCore`.

    Driven from the engine thread: ``advance()`` performs exactly one
    unit of work (one prefill chunk or one chained decode window across
    all decode-ready lanes) so paged and normal traffic interleave at
    engine-step granularity.
    """

    def __init__(self, core, pcfg: PagedConfig):
        from ...engine.engine import StepOutput  # noqa: F401 (typing aid)

        self.core = core
        self.pcfg = pcfg
        cfg = core.cfg
        self.page = cfg.page_size
        m = cfg.model
        self.programs = PagedPrograms(cfg, core.mesh, core._rep_sharding,
                                      core.kv_sharding)
        self.pager = PageScheduler(core.tiered, pcfg.seg_pages,
                                   pcfg.prefetch)
        self.chunk = cfg.prefill_chunk
        self.chunk_pages = -(-self.chunk // self.page)
        # decode chaining: N tokens per host fetch, the dense path's
        # packed multi-step discipline — each sampled token feeds the
        # next forward as a device array, ONE packed fetch per window
        self.decode_chain = max(1, int(_env_float(
            "DYN_KVPAGE_DECODE_STEPS", cfg.decode_steps or 4)))
        # every lane gets an equal share of the device budget; the
        # total leased across lanes never exceeds ``budget``, so the
        # byte-honesty story of the serial lane carries over verbatim
        self.batch = pcfg.batch
        self.lane_budget = pcfg.budget // self.batch
        if self.lane_budget < self.chunk_pages + 2:
            raise ValueError(
                f"kvpage budget of {pcfg.budget} pages split over "
                f"{self.batch} lanes gives {self.lane_budget} pages per "
                f"lane, which cannot hold a prefill chunk "
                f"({self.chunk_pages} pages) plus the hot tail; need "
                f">= {self.batch * (self.chunk_pages + 2)} total")
        from ...models.llama import kv_block_bytes
        self.block_bytes = kv_block_bytes(m, self.page)
        # hot-window residency ceilings: during prefill the in-flight
        # chunk's pages ride inside the lane's budget share
        self.hot_keep = max(1, self.lane_budget - self.chunk_pages - 1)
        self.lanes: List[Optional[_PagedSeq]] = [None] * self.batch
        self.queue: Deque[Tuple[str, BackendInput, int]] = \
            collections.deque()
        self._worker = str(os.getpid())
        # prefill/decode alternation + prefill lane fairness cursors
        self._prefill_rr = 0
        # lane-persistent sampling state: one row per lane. Rows are
        # (re)initialized at lane start; padded rows in a batched head
        # are masked inactive so they never advance (see programs.head)
        vocab = m.vocab_size
        self._keys = jax.random.split(
            jax.random.key(int(cfg.seed)), self.batch)
        self._counts = jnp.zeros((self.batch, vocab), jnp.int32)
        self._temp = np.zeros(self.batch, np.float32)
        self._top_p = np.ones(self.batch, np.float32)
        self._top_k = np.zeros(self.batch, np.int32)
        self._freq = np.zeros(self.batch, np.float32)
        self._pres = np.zeros(self.batch, np.float32)
        # goodput accounting: paged dispatches feed the engine's shared
        # GoodputMeter so MFU/MBU stop under-reporting on long-context
        # traffic. The paged programs compile per (kind, hot-bucket)
        # shape with no instrument_compile wrapper, so first-use shapes
        # are tracked here and their work units excluded — same
        # compile-not-compute convention as the dense path's
        # _take_compiled_flag.
        self._accounted_shapes: set = set()
        # hot-span shape buckets (page multiples, powers of two) keep the
        # attn_hot program count logarithmic in the per-lane budget
        self.s_hot_buckets: List[int] = []
        b = self.page
        while b < self.lane_budget * self.page:
            self.s_hot_buckets.append(b)
            b *= 2
        self.s_hot_buckets.append(self.lane_budget * self.page)

    # ------------------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return any(s is not None for s in self.lanes) or bool(self.queue)

    @property
    def active(self) -> Optional[_PagedSeq]:
        """The first occupied lane (legacy single-lane introspection)."""
        for seq in self.lanes:
            if seq is not None:
                return seq
        return None

    def resident_bytes(self) -> Tuple[float, float]:
        """(device bytes, pinned host bytes) of ALL lanes' working
        sets."""
        dev = host = 0
        for seq in self.lanes:
            if seq is None:
                continue
            dev += len(seq.resident)
            host += len(seq.pinned)
        return (float(dev * self.block_bytes),
                float(host * self.block_bytes))

    def close(self) -> None:
        self.pager.close()

    def cancel(self, seq_id: str) -> None:
        for seq in self.lanes:
            if seq is not None and seq.seq_id == seq_id:
                seq.cancelled = True
                return
        self.queue = collections.deque(
            (s, r, b) for s, r, b in self.queue if s != seq_id)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def _reserved_unpinned(self) -> int:
        """Admitted-but-not-yet-pinned working-set blocks: queued
        requests in full, plus each lane's remaining demotable span.
        This is the ledger that keeps N-lane admission byte-honest —
        what concurrent lanes WILL pin is charged before they pin it."""
        r = sum(b for _, _, b in self.queue)
        for seq in self.lanes:
            if seq is not None:
                r += max(0, seq.reserve_blocks - len(seq.pinned))
        return r

    def try_route(self, seq_id: str, req: BackendInput):
        """Accept the request into the paged lane (None) or explain why
        not (a typed ERROR StepOutput the engine emits as-is)."""
        from ...engine.engine import StepOutput

        prompt_len = len(req.token_ids)

        def err(msg, code, reason):
            return StepOutput(seq_id, 0, 0.0, FinishReason.ERROR,
                              error=msg, error_code=code,
                              error_stage="engine_admission",
                              error_reason=reason)

        if prompt_len >= self.pcfg.max_context:
            return err(
                f"prompt of {prompt_len} tokens exceeds the paged "
                f"context limit of {self.pcfg.max_context} "
                f"(DYN_KVPAGE_MAX_CONTEXT)", 400, "context_exceeded")
        if req.images:
            return err("image requests are not servable on the paged "
                       "long-context lane", 400, "unsupported")
        if self.core.dispatch_hook is not None:
            return err("KV paging does not run on multi-host engines",
                       400, "unsupported")
        max_new = req.stop.max_tokens or (self.pcfg.max_context
                                          - prompt_len)
        blocks = -(-(prompt_len + max_new) // self.page)
        host = self.core.tiered.host
        reserved = self._reserved_unpinned()
        # byte-honest admission: the pinned working set must fit the host
        # tier next to what is already pinned AND what every admitted
        # lane/queued request will still pin, or this one request would
        # evict the pool's (and its neighbors') working sets
        if blocks + len(host.pinned) + reserved + 1 > host.num_blocks:
            return err(
                f"paged working set of {blocks} KV blocks "
                f"({blocks * self.block_bytes / 1e6:.0f} MB) does not fit "
                f"the host tier ({host.num_blocks} blocks, "
                f"{len(host.pinned)} already pinned, {reserved} reserved "
                f"by admitted lanes)", 503,
                "kvpage_capacity")
        self.queue.append((seq_id, req, blocks))
        return None

    # ------------------------------------------------------------------
    # engine-step driver
    # ------------------------------------------------------------------
    def advance(self) -> List:
        """One unit of paged work: start queued sequences into free
        lanes, then one prefill chunk (lanes round-robin) or one chained
        decode window across every decode-ready lane — prefill first
        when both kinds of work exist (see module docstring)."""
        from ...engine.engine import StepOutput

        out: List[StepOutput] = []
        for seq in list(self.lanes):
            if seq is not None and seq.cancelled:
                out.append(StepOutput(seq.seq_id, seq.last_token,
                                      seq.cum_logprob,
                                      FinishReason.CANCELLED))
                self._release(seq)
        for lane in range(self.batch):
            if self.lanes[lane] is None and self.queue:
                seq_id, req, blocks = self.queue.popleft()
                self._start(lane, seq_id, req, blocks)
        prefilling = [s for s in self.lanes
                      if s is not None and s.prefill_done < len(s.prompt)]
        decoding = [(s.lane, s) for s in self.lanes
                    if s is not None and s.prefill_done >= len(s.prompt)]
        # prefill-first: a decode window costs nearly the same at one
        # lane as at full occupancy (staging uploads and dispatches are
        # lane-stacked), so decoding while an admitted lane still
        # prefills squanders the shared slots. Filling the lane first
        # maximizes window occupancy AND its TTFT; the ITL stall for
        # running decodes is bounded by admission (at most ``batch``
        # resident prefills, no queue jump past a busy lane).
        do_prefill = bool(prefilling)
        if do_prefill:
            seq = prefilling[self._prefill_rr % len(prefilling)]
            self._prefill_rr += 1
            try:
                self._prefill_chunk(seq, out)
            except Exception as e:  # noqa: BLE001 - kill THIS request,
                # never the engine (see _fail)
                log.exception("paged sequence %s failed", seq.seq_id)
                self._fail(seq, e, out)
        elif decoding:
            self._decode_window(decoding, out)
        return out

    def _fail(self, seq: _PagedSeq, e: Exception, out: List) -> None:
        """Emit the typed failure for ONE lane and release it: a paged
        failure must kill this request, never the engine — letting it
        escape would hit step()'s catch-all, which errors every DENSE
        sequence and never releases the paged lanes. Capacity pressure
        is a retryable 503; a KvPageMiss (pin discipline violated — a
        data-loss bug, not load) and anything unexpected are 500s with
        distinct reasons so dashboards can tell them apart."""
        from ...engine.engine import StepOutput

        if isinstance(e, (OutOfBlocks, OutOfTierSpace)):
            code, reason = 503, "kvpage_capacity"
        elif isinstance(e, KvPageMiss):
            code, reason = 500, "kvpage_miss"
        else:
            code, reason = 500, "kvpage_internal"
        out.append(StepOutput(
            seq.seq_id, seq.last_token, seq.cum_logprob,
            FinishReason.ERROR,
            error=f"paged serving failed: {e}", error_code=code,
            error_stage="engine", error_reason=reason))
        self._release(seq)

    # ------------------------------------------------------------------
    def _start(self, lane: int, seq_id: str, req: BackendInput,
               blocks: int) -> _PagedSeq:
        prompt = list(req.token_ids)
        lora_id = getattr(req, "lora_id", 0)
        seq = _PagedSeq(seq_id, req, prompt,
                        TokenSequence(self.page, lora_id=lora_id),
                        lane=lane, reserve_blocks=blocks)
        # prefix reuse against the tier: pin matching leading blocks and
        # skip recomputing them — they are cold context from token 0
        page = self.page
        usable = (len(prompt) - 1) // page
        parent = lora_chain_root(lora_id)
        matched = 0
        tiered = self.core.tiered
        for b in range(usable):
            blk = prompt[b * page:(b + 1) * page]
            sh = chain_hash(parent, hash_tokens(blk))
            if not tiered.pin(sh):
                break
            seq.pinned.append(sh)
            parent = sh
            matched += 1
        for t in prompt[:matched * page]:
            seq.tokseq.append(int(t))
        seq.first_res = matched
        seq.total_len = matched * page
        seq.prefill_done = matched * page
        self.core.last_prefix_hit = matched * page
        self.core.prefix_hit_tokens += matched * page
        self.core.prefix_query_tokens += len(prompt)

        # sampling state: this lane's row of the persistent [B] arrays
        sp = req.sampling
        from ...engine.sampling import STATIC_K
        self._temp[lane] = float(sp.temperature or 0.0)
        self._top_p[lane] = float(sp.top_p if sp.top_p is not None
                                  else 1.0)
        self._top_k[lane] = int(min(sp.top_k or 0, STATIC_K))
        self._freq[lane] = float(sp.frequency_penalty or 0.0)
        self._pres[lane] = float(sp.presence_penalty or 0.0)
        seq.seed = int(sp.seed if sp.seed is not None
                       else self.core.cfg.seed)
        self._keys = self._keys.at[lane].set(jax.random.key(seq.seed))
        self._counts = self._counts.at[lane].set(0)
        self.lanes[lane] = seq
        self._set_gauges()
        return seq

    def _release(self, seq: _PagedSeq) -> None:
        for page in seq.resident:
            self.core.pool.blocks.release(page)
        seq.resident = []
        tiered = self.core.tiered
        for h in seq.pinned:
            tiered.unpin(h)
        seq.pinned = []
        seq.reserve_blocks = 0
        if self.lanes[seq.lane] is seq:
            self.lanes[seq.lane] = None
        self.pager.end_lane(seq.lane)
        self._set_gauges()

    def _set_gauges(self) -> None:
        dev, host = self.resident_bytes()
        g = stage_metrics().kvpage_resident_bytes
        g.set("device", self._worker, value=dev)
        g.set("host", self._worker, value=host)

    # ------------------------------------------------------------------
    # residency
    # ------------------------------------------------------------------
    def _slot(self, seq: _PagedSeq, pos: int) -> int:
        """Pool token-slot of position ``pos`` (must be resident)."""
        blk = pos // self.page
        return (seq.resident[blk - seq.first_res] * self.page
                + pos % self.page)

    def _ensure_resident(self, seq: _PagedSeq, upto: int) -> None:
        """Lease device pages so every position < ``upto`` beyond the
        demoted prefix has a slot."""
        need_blocks = -(-upto // self.page)
        while seq.first_res + len(seq.resident) < need_blocks:
            seq.resident.append(self.core.pool.blocks.lease_new())

    def _demote(self, seq: _PagedSeq, keep: int) -> None:
        """Seal-and-demote the oldest resident blocks until at most
        ``keep`` stay resident. Only full (hashed) blocks demote; the
        d2h gather reads the post-write pool arrays, so it is ordered
        after the writing dispatch by data dependency."""
        sealed = len(seq.tokseq.blocks)
        n = 0
        while (len(seq.resident) - n > keep
               and seq.first_res + n < sealed):
            n += 1
        if n <= 0:
            return
        pages = seq.resident[:n]
        hashes = [seq.tokseq.blocks[seq.first_res + i].sequence_hash
                  for i in range(n)]
        t0 = time.perf_counter()
        k, v = self.core.copy_stream.d2h_pages(
            self.core.k_pool, self.core.v_pool, pages, pipeline=n > 4)
        record_flow("kvpage_pageout", n * self.block_bytes,
                    time.perf_counter() - t0, trace_id=seq.seq_id)
        tiered = self.core.tiered
        for i, h in enumerate(hashes):
            tiered.deposit_pinned(h, k[i], v[i])
            seq.pinned.append(h)
        for page in pages:
            self.core.pool.blocks.release(page)
        del seq.resident[:n]
        seq.first_res += n
        stage_metrics().kvpage_demotions.inc(amount=float(n))
        self._set_gauges()

    def _cold_segments(self, seq: _PagedSeq
                       ) -> List[Tuple[int, Tuple[int, ...]]]:
        """The demoted prefix [0, first_res) grouped into staging
        segments of ``seg_pages`` blocks: (start block, block hashes)."""
        hashes = seq.pinned
        sp = self.pcfg.seg_pages
        return [(i, tuple(hashes[i:i + sp]))
                for i in range(0, len(hashes), sp)]

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _bucket_hot(self, n: int) -> int:
        for b in self.s_hot_buckets:
            if n <= b:
                return b
        return self.s_hot_buckets[-1]

    def _account(self, kind: str, S: int, flops: float, bytes_: float,
                 tokens: int, elapsed_s: float) -> None:
        """Feed one paged work unit into the engine's GoodputMeter —
        unless this (kind, hot-bucket) shape just compiled, in which
        case the wall time is XLA, not compute."""
        shape = (kind, S)
        if shape not in self._accounted_shapes:
            self._accounted_shapes.add(shape)
            return
        self.core.goodput.account(flops, bytes_, elapsed_s, tokens)

    def _build_plans(self, parts, positions: np.ndarray
                     ) -> Dict[int, List[List[Tuple[int, Tuple[int, ...]]]]]:
        """Per-row per-layer cold plans for one forward (or one whole
        decode window), installed with the pager per lane. Sliding
        layers drop segments wholly below their window at the FIRST
        query position — later window steps only move the window
        forward, so a clamped-in segment is at worst an all-masked
        exact no-op for them."""
        prg = self.programs
        L = self.core.cfg.model.num_layers
        page = self.page
        plans: Dict[int, List[List[Tuple[int, Tuple[int, ...]]]]] = {}
        for row, seq in parts:
            segs = self._cold_segments(seq)
            if not segs:
                continue
            p0 = int(positions[row, 0])     # first query position
            per_layer = []
            for l in range(L):
                w = prg.windows[l]
                if w is None:
                    per_layer.append(segs)
                else:
                    per_layer.append(
                        [sg for sg in segs
                         if (sg[0] + len(sg[1])) * page - 1 > p0 - w])
            plans[row] = per_layer
            self.pager.begin(
                PageinPlan([[sg[1] for sg in pl] for pl in per_layer]),
                lane=seq.lane)
        return plans

    def _upload_batch(self, parts, plans, B: int, l: int, s: int,
                      cache: Optional[Dict] = None):
        """Take every lane's (layer, step) staging segment and stack
        them into the SHARED [2, B, ...] staging slot (k over v: the
        whole slot is ONE h2d transfer, plus one tiny [B, 2] meta array
        the device rebuilds the validity/position mask from), then
        ENQUEUE its upload; returns the device arrays the batched
        attention dispatch consumes. Lanes with no segment at this step
        ride along masked-invalid (stale/zero slot values are multiplied
        by exactly 0.0 in the partial attend, so sharing the slot is
        exact). Within a decode window the assembled host buffers are
        ``cache``d: cold segments cannot change between the window's
        steps, so only the first step pays the pager takes — later
        steps re-upload the same host staging slots (device staging
        stays double-buffer bounded either way)."""
        key = (l, s)
        assemble_s = 0.0
        if cache is not None and key in cache:
            kv_st, meta_dev = cache[key]
        else:
            sp, page = self.pcfg.seg_pages, self.page
            kv_st = None
            meta = np.zeros((B, 2), np.int32)
            for row, seq in parts:
                pl = plans.get(row)
                if pl is None or s >= len(pl[l]):
                    continue
                start_blk, _hashes = pl[l][s]
                k, v, n = self.pager.take((l, s), lane=seq.lane)
                assemble_s += self.pager.last_assemble_s
                if kv_st is None:
                    kv_st = np.zeros((2, B) + k.shape, k.dtype)
                kv_st[0, row] = k
                kv_st[1, row] = v
                meta[row] = (n, start_blk * page)
            # meta is step-invariant: its device array rides the cache,
            # so later window steps re-upload ONLY the kv slot
            meta_dev = jnp.asarray(meta)
            if cache is not None:
                cache[key] = (kv_st, meta_dev)
        dt = self.core.cfg.model.dtype
        t0 = time.perf_counter()
        kv_dev = jnp.asarray(kv_st, dt)
        # one ledger record per lane-stacked staging upload: the shared
        # slot's bytes once (it covers every lane), priced at assemble
        # (tier->staging, 0 on a window-cache hit) + upload enqueue
        record_flow("kvpage_pagein", kv_st.nbytes,
                    assemble_s + time.perf_counter() - t0)
        return kv_dev, meta_dev

    def _forward(self, parts, B: int, tokens, positions: np.ndarray,
                 write_idx: np.ndarray, read_idx: np.ndarray,
                 read_pos: np.ndarray, read_valid: np.ndarray,
                 plans=None, cache: Optional[Dict] = None) -> jax.Array:
        """The segmented forward over ``parts`` = [(row, seq)]: per
        layer, qkv+write, hot partial attention through the pool (every
        lane in one dispatch), cold segments merged one lane-stacked
        staged upload at a time — the next step's upload enqueued before
        the current step's attention dispatches — then the layer tail.
        Per-layer-class programs come from :attr:`PagedPrograms.
        layer_programs`. ``plans``/``cache`` let a decode window build
        its page-in plan and host staging buffers ONCE and reuse them
        across all chained steps; a plain prefill call plans inline."""
        core = self.core
        prg = self.programs
        L = core.cfg.model.num_layers
        if plans is None:
            plans = self._build_plans(parts, positions)
        x = prg.embed(core.params, jnp.asarray(tokens))
        for l in range(L):
            li = np.int32(l)
            qkv_fn, hot_fn, cold_fn, _w = prg.layer_programs[l]
            q, core.k_pool, core.v_pool = qkv_fn(
                core.params, li, x, positions, core.k_pool, core.v_pool,
                write_idx)
            o, m, d = hot_fn(q, li, core.k_pool, core.v_pool,
                             read_idx, read_pos, read_valid, positions)
            steps = max((len(plans[row][l]) for row in plans), default=0)
            if steps:
                nxt = self._upload_batch(parts, plans, B, l, 0, cache)
                for s in range(steps):
                    cur = nxt
                    nxt = (self._upload_batch(parts, plans, B, l, s + 1,
                                              cache)
                           if s + 1 < steps else None)
                    o, m, d = cold_fn(q, positions, cur[0], cur[1],
                                      o, m, d)
            x = prg.layer_out(core.params, li, x, o, m, d)
        return x

    def _sample_row(self, seq: _PagedSeq, x: jax.Array,
                    last_i: int) -> Tuple[int, float]:
        """Sample ONE lane's token from a B=1 dispatch (the prefill
        tail): the lane's sampling-state rows round-trip through a
        single-row head, so the draw is identical to a batched one."""
        prg = self.programs
        ln = seq.lane
        # the counts row must be a COPY: head donates its counts arg,
        # and a whole-array slice can alias the persistent buffer
        crow_in = jnp.array(self._counts[ln:ln + 1])
        packed, krow, crow = prg.head(
            self.core.params, x, np.asarray([last_i], np.int32),
            self._temp[ln:ln + 1], self._top_p[ln:ln + 1],
            self._top_k[ln:ln + 1], self._keys[ln:ln + 1],
            crow_in, self._freq[ln:ln + 1],
            self._pres[ln:ln + 1], np.ones(1, bool))
        self._keys = self._keys.at[ln].set(krow[0])
        self._counts = self._counts.at[ln].set(crow[0])
        # dynalint: ok(host-sync) THE designed paged-lane fetch: one
        # packed (token, logprob) pair for the prefill-tail sample — stop
        # conditions and the first decode feed depend on it host-side
        arr = np.asarray(packed)
        return int(arr[0, 0]), float(arr[0, 1])

    # ------------------------------------------------------------------
    def _hot_row(self, seq: _PagedSeq, upto: int, padded: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(slots, positions, valid) of static width ``padded`` covering
        the resident span [first_res*page, upto) of one lane."""
        start = seq.first_res * self.page
        n = upto - start
        slots = np.zeros(padded, np.int32)
        pos = np.zeros(padded, np.int32)
        valid = np.zeros(padded, bool)
        t = np.arange(start, upto)
        pages = np.asarray(seq.resident, np.int32)
        slots[:n] = (pages[t // self.page - seq.first_res] * self.page
                     + t % self.page)
        pos[:n] = t
        valid[:n] = True
        return slots, pos, valid

    def _prefill_chunk(self, seq: _PagedSeq, out: List) -> None:
        from ...engine.engine import StepOutput

        t_disp = time.perf_counter()
        C = self.chunk
        prompt = seq.prompt
        start = seq.prefill_done
        count = min(C, len(prompt) - start)
        self._ensure_resident(seq, start + count)
        tokens = np.zeros((1, C), np.int32)
        positions = np.zeros((1, C), np.int32)
        write_idx = np.zeros((1, C), np.int32)    # pad -> scratch page 0
        tokens[0, :count] = prompt[start:start + count]
        positions[0, :count] = np.arange(start, start + count)
        write_idx[0, :count] = [self._slot(seq, p)
                                for p in range(start, start + count)]
        S = self._bucket_hot(start + count - seq.first_res * self.page)
        read_idx = np.zeros((1, S), np.int32)
        read_pos = np.zeros((1, S), np.int32)
        read_valid = np.zeros((1, S), bool)
        read_idx[0], read_pos[0], read_valid[0] = self._hot_row(
            seq, start + count, S)
        x = self._forward([(0, seq)], 1, tokens, positions, write_idx,
                          read_idx, read_pos, read_valid)
        for t in prompt[start:start + count]:
            seq.tokseq.append(int(t))
        seq.total_len = start + count
        seq.prefill_done = start + count
        is_last = seq.prefill_done >= len(prompt)
        # demote beyond the hot window now that the writes are enqueued
        self._demote(seq, self.hot_keep)
        if not is_last:
            from ...utils.roofline import prefill_cost

            fl, by, tk = prefill_cost(self.core.costs, [(start, count)])
            self._account("prefill", S, fl, by, tk,
                          time.perf_counter() - t_disp)
            return
        tok, lp = self._sample_row(seq, x, count - 1)
        from ...utils.roofline import prefill_cost

        fl, by, tk = prefill_cost(self.core.costs, [(start, count)])
        self._account("prefill", S, fl, by, tk,
                      time.perf_counter() - t_disp)
        seq.generated = 1
        seq.last_token = tok
        seq.cum_logprob = lp
        fin = self._finish(seq, tok)
        out.append(StepOutput(seq.seq_id, tok, seq.cum_logprob, fin,
                              prompt_tokens=len(prompt),
                              token_logprob=lp))
        if fin is not None:
            self._release(seq)

    def _window(self, seq: _PagedSeq) -> int:
        """Decode tokens to chain before the next host fetch: bounded by
        the chain knob, the request's remaining token budget and the
        paged context ceiling — overshoot past a mid-window EOS is the
        only speculative work (its writes die with the released pages)."""
        n = self.decode_chain
        if seq.request.stop.max_tokens:
            n = min(n, seq.request.stop.max_tokens - seq.generated)
        n = min(n, self.pcfg.max_context - len(seq.prompt) - seq.generated)
        return max(1, n)

    def _decode_window(self, parts, out: List) -> None:
        """One chained decode window across every decode-ready lane:
        N = min over lanes of their window bound, so no lane oversteps
        its token budget; each window step samples one token PER LANE
        from a single batched dispatch chain, with ONE packed host fetch
        at the end."""
        from ...engine.engine import StepOutput

        t_disp = time.perf_counter()
        N = min(self._window(seq) for _, seq in parts)
        B = self.batch
        # per-lane residency setup: a failure here (device pool pressure)
        # is lane-local — nothing shared has been touched yet, so only
        # the starved lane errors and the window proceeds without it
        ready = []
        for row, seq in parts:
            try:
                self._ensure_resident(seq, seq.total_len + N)
                if len(seq.resident) > self.lane_budget:
                    self._demote(seq, self.lane_budget - 1)
                ready.append((row, seq))
            except Exception as e:  # noqa: BLE001 - typed per-lane error
                log.exception("paged sequence %s failed", seq.seq_id)
                self._fail(seq, e, out)
        if not ready:
            return
        parts = ready
        prg = self.programs
        active = np.zeros(B, bool)
        tokens = np.zeros((B, 1), np.int32)
        for row, seq in parts:
            active[row] = True
            tokens[row, 0] = seq.last_token
        packed_list: List[jax.Array] = []
        S_max = 0
        try:
            # one page-in plan + one set of assembled host staging
            # buffers serves every chained step: cold segments cannot
            # change inside the window (demotion happens at window
            # boundaries), so steps 2..N skip the pager entirely
            pos0 = np.zeros((B, 1), np.int32)
            for row, seq in parts:
                pos0[row, 0] = seq.total_len
            plans = self._build_plans(parts, pos0)
            cache: Dict[Tuple[int, int], Tuple] = {}
            for i in range(N):
                positions = np.zeros((B, 1), np.int32)
                write_idx = np.zeros((B, 1), np.int32)  # pad -> scratch
                S = self.page
                for row, seq in parts:
                    pos = seq.total_len + i
                    positions[row, 0] = pos
                    write_idx[row, 0] = self._slot(seq, pos)
                    S = max(S, pos + 1 - seq.first_res * self.page)
                S = self._bucket_hot(S)
                S_max = max(S_max, S)
                read_idx = np.zeros((B, S), np.int32)
                read_pos = np.zeros((B, S), np.int32)
                read_valid = np.zeros((B, S), bool)
                for row, seq in parts:
                    (read_idx[row], read_pos[row],
                     read_valid[row]) = self._hot_row(
                        seq, seq.total_len + i + 1, S)
                x = self._forward(parts, B, tokens, positions, write_idx,
                                  read_idx, read_pos, read_valid,
                                  plans=plans, cache=cache)
                packed, self._keys, self._counts = prg.head(
                    self.core.params, x, np.zeros(B, np.int32),
                    self._temp, self._top_p, self._top_k, self._keys,
                    self._counts, self._freq, self._pres, active)
                packed_list.append(packed)
                # chain: each lane's sampled token feeds its next forward
                # ON DEVICE — no host round-trip between window steps
                tokens = packed[:, 0:1].astype(jnp.int32)
            # dynalint: ok(host-sync) THE designed paged-lane fetch: one
            # packed (token, logprob) [N, B, 2] batch per chained window,
            # covering every lane at once — stop/stream detection runs
            # host-side on the batch
            arr = np.asarray(jnp.stack(packed_list))
        except Exception as e:  # noqa: BLE001 - window-fatal
            # a failure inside the batched dispatch chain (pager miss,
            # device error) cannot be attributed to one lane once shared
            # sampling state has advanced: the whole window faults and
            # every PARTICIPATING lane gets the typed error. Lanes still
            # prefilling are untouched — their sampling rows are rebuilt
            # below because the donated counts buffer may be gone.
            log.exception("paged decode window failed (%d lanes)",
                          len(parts))
            vocab = self.core.cfg.model.vocab_size
            self._counts = jnp.zeros((B, vocab), jnp.int32)
            self._keys = jax.random.split(
                jax.random.key(int(self.core.cfg.seed)), B)
            for row, seq in parts:
                self._fail(seq, e, out)
            for seq in self.lanes:     # surviving lanes: pre-first-sample
                if seq is not None:
                    self._keys = self._keys.at[seq.lane].set(
                        jax.random.key(seq.seed))
            return
        from ...utils.roofline import decode_cost

        fl = by = tk = 0.0
        for row, seq in parts:
            fin = None
            pos0 = seq.total_len
            for i in range(N):
                seq.tokseq.append(int(seq.last_token))
                seq.total_len = pos0 + i + 1
                tok, lp = int(arr[i, row, 0]), float(arr[i, row, 1])
                f, b, t = decode_cost(self.core.costs, [pos0 + i], 1)
                fl, by, tk = fl + f, by + b, tk + t
                seq.generated += 1
                seq.last_token = tok
                seq.cum_logprob += lp
                fin = self._finish(seq, tok)
                out.append(StepOutput(seq.seq_id, tok, seq.cum_logprob,
                                      fin, token_logprob=lp))
                if fin is not None:
                    # mid-window stop: this lane's tokens past it are
                    # discarded; their page writes/sampler state die with
                    # the release below (other lanes commit all N)
                    break
            if fin is not None:
                self._release(seq)
        self._account("decode", S_max, fl, by, tk,
                      time.perf_counter() - t_disp)

    def _finish(self, seq: _PagedSeq, token: int) -> Optional[FinishReason]:
        req = seq.request
        if not req.stop.ignore_eos:
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            if token in eos and seq.generated >= (req.stop.min_tokens or 0):
                return FinishReason.EOS
        if req.stop.max_tokens and seq.generated >= req.stop.max_tokens:
            return FinishReason.LENGTH
        if len(seq.prompt) + seq.generated >= self.pcfg.max_context:
            return FinishReason.LENGTH
        return None
