"""Jitted programs for paged (working-set-bounded) prefill and decode.

The standard engine runs attention as ONE dispatch over the whole
context — every KV page must be device-resident when it runs. These
programs decompose each layer's attention into *partial* passes with
online-softmax accumulators (the flash-attention recurrence, applied
across dispatches instead of across kernel tiles):

- the ``attn_hot`` program attends over the device-resident tail
  (read through the pool, causally masked, window-masked on sliding
  layers);
- the ``attn_cold`` program attends over one staged segment of demoted
  blocks per lane, uploaded h2d into a shared [B, ...] staging slot (all
  cold positions strictly precede every query, so causality is free;
  sliding layers additionally window-mask against each lane's own
  segment positions);
- :meth:`PagedPrograms.layer_out` normalizes the merged accumulators and
  finishes the layer (o-proj, residual, FFN).

Splitting per (layer, segment) is what makes bounded residency possible:
between partial passes only the tiny per-chunk activations and the f32
(o, m, d) accumulators persist on device, so the cold tail can stream
through a fixed pair of staging slots regardless of context length.
Exactness: softmax reassociation is the only difference from the dense
path — accumulation stays f32 end to end, and the long-context bench
lane pins token-identity against an unpaged run. Batching is exact too:
masked/padded positions contribute exactly ``0.0`` to the f32 sums and
sampling is row-independent, so each lane's token stream is
byte-identical at any batch width (``tests/test_kvpage.py`` pins B=4
against B=1 against the dense engine).

The layer index rides every program as a TRACED scalar (stacked layer
params are gathered with it), so the whole layer stack replays a
constant number of compiled variants, not O(L). Models with per-layer
STATIC structure (sliding-window masks, dual-base rope) compile one
variant per layer *class* instead: the window span and rope-table choice
are closure constants of the class's programs (mirroring the dense
path's ``flash_for`` per-class kernel cache), which is what lifted the
former sliding-window/dual-rope exclusions — Gemma2/3-style models have
exactly two classes, so the program count stays constant.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ...models import llama
from ...models.llama import NEG_INF


def _merge(o0, m0, d0, o1, m1, d1):
    """Online-softmax merge of two partial-attention accumulators.
    Shapes: o [B, Hkv, G, T, Dh] f32; m, d [B, Hkv, G, T] f32."""
    m = jnp.maximum(m0, m1)
    a0 = jnp.exp(m0 - m)
    a1 = jnp.exp(m1 - m)
    return (o0 * a0[..., None] + o1 * a1[..., None],
            m, d0 * a0 + d1 * a1)


def _partial_attend(cfg, q, k, v, mask):
    """Unnormalized attention stats for one KV span.

    q: [B, T, Hq, Dh]; k, v: [B, S, Hkv, Dh]; mask: [B, T, S] bool.
    Returns (o [B,Hkv,G,T,Dh], m [B,Hkv,G,T], d [B,Hkv,G,T]), all f32.
    Scores mirror :func:`llama.attend` (scale then softcap then mask).
    Rows whose mask is all-False yield (0, NEG_INF, 0): an exact no-op
    under :func:`_merge`, which is what makes padded lanes free."""
    Hq = cfg.num_heads
    Hkv = cfg.num_kv_heads
    G = Hq // Hkv
    B, T, _, Dh = q.shape
    qg = q.reshape(B, T, Hkv, G, Dh)
    scores = jnp.einsum("bthgd,bshd->bhgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * cfg.attn_scale
    if cfg.attn_logit_softcap:
        scores = jnp.tanh(scores / cfg.attn_logit_softcap) \
            * cfg.attn_logit_softcap
    mg = mask[:, None, None, :, :]                      # [B,1,1,T,S]
    scores = jnp.where(mg, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                        # [B,Hkv,G,T]
    p = jnp.where(mg, jnp.exp(scores - m[..., None]), 0.0)
    d = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhgts,bshd->bhgtd", p, v.astype(jnp.float32))
    return o, m, d


class PagedPrograms:
    """The compiled-program surface of the paged path, built once per
    engine. All programs take a leading batch dim (1 for prefill-chunk
    dispatches, the lane count for batched decode); per-layer-static
    model structure selects a compiled variant via
    :attr:`layer_programs`."""

    def __init__(self, cfg, mesh, rep_sharding, kv_sharding):
        self.cfg = cfg
        m = cfg.model
        rep, kv = rep_sharding, kv_sharding
        page = cfg.page_size

        # Layer classes: the per-layer STATIC attention structure.
        # (window span, local-rope?) — full-attention layers are
        # (None, False); Gemma2/3 sliding layers carry their window and
        # (gemma3) the local-theta rope table. Each distinct class gets
        # its own compiled qkv/attn_hot/attn_cold variants with the
        # statics baked in as closure constants; the layer index stays
        # traced WITHIN a class.
        classes: List[Tuple[Optional[int], bool]] = []
        layer_cls: List[int] = []
        for l in range(m.num_layers):
            if m.layer_sliding(l):
                key = (int(m.sliding_window),
                       m.rope_local_theta is not None)
            else:
                key = (None, False)
            if key not in classes:
                classes.append(key)
            layer_cls.append(classes.index(key))
        self.classes = classes
        #: per-layer window span (None = full attention), for the
        #: runner's page-in plan clamping
        self.windows: List[Optional[int]] = [
            classes[c][0] for c in layer_cls]

        def embed(params, tokens):
            return llama._embed(params, m, tokens)

        self.embed = jax.jit(embed, out_shardings=rep)

        def make_qkv(local: bool):
            def qkv(params, l, x, positions, k_pool, v_pool, write_idx):
                lp = params["layers"]
                h = llama.rms_norm(x, lp["ln1"][l], m.rms_eps,
                                   m.norm_offset)
                q = jnp.einsum("btd,dhk->bthk", h, lp["wq"][l])
                k = jnp.einsum("btd,dhk->bthk", h, lp["wk"][l])
                v = jnp.einsum("btd,dhk->bthk", h, lp["wv"][l])
                if m.attention_bias:
                    q = q + lp["bq"][l]
                    k = k + lp["bk"][l]
                    v = v + lp["bv"][l]
                if m.qk_norm:
                    q = llama.rms_norm(q, lp["ln_q"][l], m.rms_eps,
                                       m.norm_offset)
                    k = llama.rms_norm(k, lp["ln_k"][l], m.rms_eps,
                                       m.norm_offset)
                cos, sin = llama.rope_tables(m, positions, local=local)
                q = llama.apply_rope(q, cos, sin)
                k = llama.apply_rope(k, cos, sin)
                B, T = positions.shape
                flat_w = write_idx.reshape(-1)
                wp, wo = flat_w // page, flat_w % page
                k_pool = k_pool.at[l, :, wp, wo].set(
                    k.reshape(B * T, *k.shape[2:]))
                v_pool = v_pool.at[l, :, wp, wo].set(
                    v.reshape(B * T, *v.shape[2:]))
                return q, k_pool, v_pool

            return jax.jit(qkv, donate_argnums=(4, 5),
                           out_shardings=(rep, kv, kv))

        def make_attn_hot(window: Optional[int]):
            def attn_hot(q, l, k_pool, v_pool, read_idx, read_pos,
                         read_valid, positions):
                rp, ro = read_idx // page, read_idx % page
                # advanced indices split by the Hkv slice: batch dims in
                # front -> [B, S, Hkv, Dh], each lane reading its own slots
                k_ctx = k_pool[l, :, rp, ro]
                v_ctx = v_pool[l, :, rp, ro]
                mask = (read_valid[:, None, :]
                        & (read_pos[:, None, :] <= positions[:, :, None]))
                if window is not None:
                    # dense-path sliding rule: keys strictly within the
                    # last `window` positions of each query
                    mask = mask & (read_pos[:, None, :]
                                   > positions[:, :, None] - window)
                return _partial_attend(m, q, k_ctx, v_ctx, mask)

            return jax.jit(attn_hot, out_shardings=(rep, rep, rep))

        def make_attn_cold(window: Optional[int]):
            def attn_cold(q, positions, kv_seg, meta, o, m_, d):
                # kv_seg: [2, B, n, Hkv, page, Dh] — one staged segment
                # PER LANE (k stacked over v so the whole slot is ONE
                # h2d transfer). meta: [B, 2] int32 = (valid blocks,
                # first token position) per lane; the validity and
                # position vectors are rebuilt on device from those two
                # scalars — cold segments are contiguous pinned-prefix
                # runs, so a prefix-block count and a start offset carry
                # everything the mask needs. Rows whose lane has no
                # segment at this step ride along with meta (0, 0):
                # all-invalid, an exact no-op under _merge. Cold
                # positions strictly precede every query, so only
                # validity (and, on sliding layers, each lane's own
                # window against the rebuilt positions) masks.
                k_seg, v_seg = kv_seg[0], kv_seg[1]
                B, n = k_seg.shape[0], k_seg.shape[1]
                k_ctx = jnp.transpose(k_seg, (0, 1, 3, 2, 4)).reshape(
                    B, n * page, k_seg.shape[2], k_seg.shape[4])
                v_ctx = jnp.transpose(v_seg, (0, 1, 3, 2, 4)).reshape(
                    B, n * page, v_seg.shape[2], v_seg.shape[4])
                iota = jnp.arange(n * page, dtype=jnp.int32)
                seg_valid = (iota // page)[None, :] < meta[:, 0:1]
                seg_pos = meta[:, 1:2] + iota[None, :]
                T = q.shape[1]
                mask = jnp.broadcast_to(seg_valid[:, None, :],
                                        (B, T, n * page))
                if window is not None:
                    # mirrors ops/attention.py's dense sliding rule
                    # `kp > qp - window` — keep the two in lockstep
                    mask = mask & (seg_pos[:, None, :]
                                   > positions[:, :, None] - window)
                o1, m1, d1 = _partial_attend(m, q, k_ctx, v_ctx, mask)
                return _merge(o, m_, d, o1, m1, d1)

            return jax.jit(attn_cold, donate_argnums=(4, 5, 6),
                           out_shardings=(rep, rep, rep))

        qkv_c = {loc: make_qkv(loc) for loc in {c[1] for c in classes}}
        hot_c = {w: make_attn_hot(w) for w in {c[0] for c in classes}}
        cold_c = {w: make_attn_cold(w) for w in {c[0] for c in classes}}
        #: per-layer (qkv, attn_hot, attn_cold, window) dispatch table —
        #: layers of the same class share the same compiled callables
        self.layer_programs = [
            (qkv_c[classes[c][1]], hot_c[classes[c][0]],
             cold_c[classes[c][0]], classes[c][0])
            for c in layer_cls]

        def layer_out(params, l, x, o, m_, d):
            lp = params["layers"]
            B, Hkv, G, T, Dh = o.shape
            attn = o / jnp.where(d == 0.0, 1.0, d)[..., None]
            attn = jnp.transpose(attn, (0, 3, 1, 2, 4)).reshape(
                B, T, Hkv * G, Dh).astype(x.dtype)
            x = llama._attn_residual(
                x, jnp.einsum("bthk,hkd->btd", attn, lp["wo"][l]), lp, l, m)
            return llama._ffn_block(x, lp, l, m)

        self.layer_out = jax.jit(layer_out, out_shardings=rep)

        def head(params, x, last_i, temp, top_p, top_k, key, counts,
                 freq_pen, pres_pen, active):
            from ...engine.sampling import apply_penalties, sample
            xs = jnp.take_along_axis(
                x, last_i[:, None, None].astype(jnp.int32), axis=1)
            logits = llama._lm_head(xs, params, m)[:, 0]       # [B, V]
            lg = apply_penalties(logits, counts, freq_pen, pres_pen)
            tok, logp, new_key = sample(lg, temp, top_p, top_k, key)
            B = tok.shape[0]
            # inactive rows (padded decode lanes) must not perturb the
            # lane-persistent sampling state: their penalty counts stay
            # put and their PRNG keys do not advance, so a lane's draws
            # are independent of which OTHER lanes shared its windows
            counts = counts.at[jnp.arange(B), tok].add(
                active.astype(jnp.int32))
            new_key = jnp.where(active, new_key, key)
            # token ids < 2^24 are exact in f32: one packed (token,
            # logprob) array = one host fetch per sampled window
            packed = jnp.stack([tok.astype(jnp.float32), logp], -1)
            return packed, new_key, counts

        self.head = jax.jit(head, donate_argnums=(7,),
                            out_shardings=(rep, rep, rep))

    # ------------------------------------------------------------------
    @staticmethod
    def validate(cfg) -> Optional[str]:
        """Why this engine config cannot run the paged path (None = ok).
        Sliding-window and dual-base-rope models compile per layer-class
        variants and ARE servable; what remains excluded is structure the
        segmented forward itself cannot express."""
        m = cfg.model
        if m.num_experts:
            return "MoE models"
        if m.vision is not None:
            return "VLM deployments (image spans need the dense path)"
        if cfg.pp > 1 or cfg.sp > 1:
            return "pp/sp parallel engines"
        return None
