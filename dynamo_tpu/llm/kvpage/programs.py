"""Jitted programs for paged (working-set-bounded) prefill and decode.

The standard engine runs attention as ONE dispatch over the whole
context — every KV page must be device-resident when it runs. These
programs decompose each layer's attention into *partial* passes with
online-softmax accumulators (the flash-attention recurrence, applied
across dispatches instead of across kernel tiles):

- :meth:`PagedPrograms.attn_hot` attends over the device-resident tail
  (read through the pool, causally masked);
- :meth:`PagedPrograms.attn_cold` attends over one staged segment of
  demoted blocks uploaded h2d into a scratch buffer (all cold positions
  strictly precede every query, so only the padding-validity mask
  applies);
- :meth:`PagedPrograms.layer_out` normalizes the merged accumulators and
  finishes the layer (o-proj, residual, FFN).

Splitting per (layer, segment) is what makes bounded residency possible:
between partial passes only the tiny per-chunk activations and the f32
(o, m, d) accumulators persist on device, so the cold tail can stream
through a fixed pair of staging slots regardless of context length.
Exactness: softmax reassociation is the only difference from the dense
path — accumulation stays f32 end to end, and the long-context bench
lane pins token-identity against an unpaged run.

The layer index rides every program as a TRACED scalar (stacked layer
params are gathered with it), so the whole layer stack replays TWO
compiled variants per program (prefill-chunk and decode shapes), not 2*L.
That is also why models with per-layer static structure (sliding-window
layers, dual-base rope) are excluded from paging at config time.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...models import llama
from ...models.llama import NEG_INF


def _merge(o0, m0, d0, o1, m1, d1):
    """Online-softmax merge of two partial-attention accumulators.
    Shapes: o [1, Hkv, G, T, Dh] f32; m, d [1, Hkv, G, T] f32."""
    m = jnp.maximum(m0, m1)
    a0 = jnp.exp(m0 - m)
    a1 = jnp.exp(m1 - m)
    return (o0 * a0[..., None] + o1 * a1[..., None],
            m, d0 * a0 + d1 * a1)


def _partial_attend(cfg, q, k, v, mask):
    """Unnormalized attention stats for one KV span.

    q: [1, T, Hq, Dh]; k, v: [1, S, Hkv, Dh]; mask: [1, T, S] bool.
    Returns (o [1,Hkv,G,T,Dh], m [1,Hkv,G,T], d [1,Hkv,G,T]), all f32.
    Scores mirror :func:`llama.attend` (scale then softcap then mask)."""
    Hq = cfg.num_heads
    Hkv = cfg.num_kv_heads
    G = Hq // Hkv
    B, T, _, Dh = q.shape
    qg = q.reshape(B, T, Hkv, G, Dh)
    scores = jnp.einsum("bthgd,bshd->bhgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * cfg.attn_scale
    if cfg.attn_logit_softcap:
        scores = jnp.tanh(scores / cfg.attn_logit_softcap) \
            * cfg.attn_logit_softcap
    mg = mask[:, None, None, :, :]                      # [B,1,1,T,S]
    scores = jnp.where(mg, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                        # [B,Hkv,G,T]
    p = jnp.where(mg, jnp.exp(scores - m[..., None]), 0.0)
    d = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhgts,bshd->bhgtd", p, v.astype(jnp.float32))
    return o, m, d


class PagedPrograms:
    """The compiled-program surface of the paged path, built once per
    engine. All programs take batch dim 1 (the paged lane runs solo)."""

    def __init__(self, cfg, mesh, rep_sharding, kv_sharding):
        self.cfg = cfg
        m = cfg.model
        rep, kv = rep_sharding, kv_sharding
        page = cfg.page_size

        def embed(params, tokens):
            return llama._embed(params, m, tokens)

        self.embed = jax.jit(embed, out_shardings=rep)

        def qkv(params, l, x, positions, k_pool, v_pool, write_idx):
            lp = params["layers"]
            h = llama.rms_norm(x, lp["ln1"][l], m.rms_eps, m.norm_offset)
            q = jnp.einsum("btd,dhk->bthk", h, lp["wq"][l])
            k = jnp.einsum("btd,dhk->bthk", h, lp["wk"][l])
            v = jnp.einsum("btd,dhk->bthk", h, lp["wv"][l])
            if m.attention_bias:
                q = q + lp["bq"][l]
                k = k + lp["bk"][l]
                v = v + lp["bv"][l]
            if m.qk_norm:
                q = llama.rms_norm(q, lp["ln_q"][l], m.rms_eps,
                                   m.norm_offset)
                k = llama.rms_norm(k, lp["ln_k"][l], m.rms_eps,
                                   m.norm_offset)
            cos, sin = llama.rope_tables(m, positions)
            q = llama.apply_rope(q, cos, sin)
            k = llama.apply_rope(k, cos, sin)
            B, T = positions.shape
            flat_w = write_idx.reshape(-1)
            wp, wo = flat_w // page, flat_w % page
            k_pool = k_pool.at[l, :, wp, wo].set(
                k.reshape(B * T, *k.shape[2:]))
            v_pool = v_pool.at[l, :, wp, wo].set(
                v.reshape(B * T, *v.shape[2:]))
            return q, k_pool, v_pool

        self.qkv = jax.jit(qkv, donate_argnums=(4, 5),
                           out_shardings=(rep, kv, kv))

        def attn_hot(q, l, k_pool, v_pool, read_idx, read_pos, read_valid,
                     positions):
            rp, ro = read_idx // page, read_idx % page
            k_ctx = k_pool[l, :, rp[0], ro[0]][None]    # [1, S, Hkv, Dh]
            v_ctx = v_pool[l, :, rp[0], ro[0]][None]
            mask = (read_valid[:, None, :]
                    & (read_pos[:, None, :] <= positions[:, :, None]))
            return _partial_attend(m, q, k_ctx, v_ctx, mask)

        self.attn_hot = jax.jit(attn_hot, out_shardings=(rep, rep, rep))

        def attn_cold(q, k_seg, v_seg, seg_valid, o, m_, d):
            # k_seg/v_seg: [n, Hkv, page, Dh] staged blocks; every cold
            # position strictly precedes every query position, so only the
            # padding-validity mask applies
            n = k_seg.shape[0]
            k_ctx = jnp.transpose(k_seg, (0, 2, 1, 3)).reshape(
                1, n * page, k_seg.shape[1], k_seg.shape[3])
            v_ctx = jnp.transpose(v_seg, (0, 2, 1, 3)).reshape(
                1, n * page, v_seg.shape[1], v_seg.shape[3])
            T = q.shape[1]
            mask = jnp.broadcast_to(seg_valid[None, None, :],
                                    (1, T, n * page))
            o1, m1, d1 = _partial_attend(m, q, k_ctx, v_ctx, mask)
            return _merge(o, m_, d, o1, m1, d1)

        self.attn_cold = jax.jit(attn_cold, donate_argnums=(4, 5, 6),
                                 out_shardings=(rep, rep, rep))

        def layer_out(params, l, x, o, m_, d):
            lp = params["layers"]
            B, Hkv, G, T, Dh = o.shape
            attn = o / jnp.where(d == 0.0, 1.0, d)[..., None]
            attn = jnp.transpose(attn, (0, 3, 1, 2, 4)).reshape(
                B, T, Hkv * G, Dh).astype(x.dtype)
            x = llama._attn_residual(
                x, jnp.einsum("bthk,hkd->btd", attn, lp["wo"][l]), lp, l, m)
            return llama._ffn_block(x, lp, l, m)

        self.layer_out = jax.jit(layer_out, out_shardings=rep)

        def head(params, x, last_i, temp, top_p, top_k, key, counts,
                 freq_pen, pres_pen):
            from ...engine.sampling import apply_penalties, sample
            xs = jnp.take_along_axis(
                x, last_i[:, None, None].astype(jnp.int32), axis=1)
            logits = llama._lm_head(xs, params, m)[:, 0]       # [1, V]
            lg = apply_penalties(logits, counts, freq_pen, pres_pen)
            tok, logp, new_key = sample(lg, temp, top_p, top_k, key)
            counts = counts.at[jnp.arange(1), tok].add(1)
            # token ids < 2^24 are exact in f32: one packed (token,
            # logprob) array = one host fetch per sampled token
            packed = jnp.stack([tok.astype(jnp.float32), logp], -1)
            return packed, new_key, counts

        self.head = jax.jit(head, donate_argnums=(7,),
                            out_shardings=(rep, rep, rep))

    # ------------------------------------------------------------------
    @staticmethod
    def validate(cfg) -> Optional[str]:
        """Why this engine config cannot run the paged path (None = ok).
        The constraints are exactly the per-layer-static model features
        the traced-layer-index programs cannot express."""
        m = cfg.model
        if m.sliding_window is not None:
            return "sliding-window models (per-layer window pattern)"
        if m.rope_local_theta is not None:
            return "dual-base rope models (per-layer rope tables)"
        if m.num_experts:
            return "MoE models"
        if m.vision is not None:
            return "VLM deployments (image spans need the dense path)"
        if cfg.pp > 1 or cfg.sp > 1:
            return "pp/sp parallel engines"
        return None
