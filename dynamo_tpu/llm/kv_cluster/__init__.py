"""Cluster-wide KV cache sharing (the LMCache direction).

Sealed KV blocks were reusable only within one worker's own
device/host/disk tiers; this plane makes the cache a *cluster* resource:

- every worker publishes a lease-bound **registry record** of the sealed
  block hashes resident in its host/disk tiers (``registry.py``) under the
  ``kv-cluster`` keyspace family — dead owners' records vanish with their
  lease;
- every worker serves a ``kv_fetch`` data-plane endpoint streaming a
  requested prefix's blocks host-tier -> peer with the layer-major
  two-part codec (``fetch.py``), and fetches missing prefixes from the
  donor the router stamped on the request, overlapped against its
  dispatch queue with a bounded race that falls back to local recompute
  on timeout/owner death;
- the KV router scores **cluster** hits (local hit > peer hit > miss,
  weighted by measured transfer cost) and stamps the chosen donor on the
  routed request (``kv_router/scheduler.py`` + ``router.py``).

Grounded in LMCache and PRESERVE (PAPERS.md); see
docs/kv_cache_routing.md "Cluster-wide KV sharing".
"""

from __future__ import annotations

import os

from .fetch import KV_FETCH_ENDPOINT, ClusterFetcher, fetch_prefix
from .registry import (ClusterOverlap, ClusterRecord, KvClusterIndex,
                       KvClusterPublisher, TransferCostModel, cluster_key,
                       cluster_prefix)
from .service import ClusterPrefetchEngine, KvClusterWorker


def enabled() -> bool:
    """``DYN_KV_CLUSTER=1``: workers publish registry records + serve/
    consume ``kv_fetch``; routers score cluster hits and stamp donors.
    Default off — the plane costs one host-tier mirror copy per sealed
    block and one lease-bound store key per worker."""
    return os.environ.get("DYN_KV_CLUSTER", "0").lower() in (
        "1", "true", "yes", "on")


__all__ = [
    "KV_FETCH_ENDPOINT", "ClusterFetcher", "fetch_prefix",
    "ClusterOverlap", "ClusterRecord", "KvClusterIndex",
    "KvClusterPublisher", "TransferCostModel", "cluster_key",
    "cluster_prefix", "ClusterPrefetchEngine", "KvClusterWorker",
    "enabled",
]
