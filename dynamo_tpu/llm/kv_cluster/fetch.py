"""Peer-to-peer prefix fetch: the cluster plane's data path.

Donor side: every worker serves ``kv_fetch`` — given a chained-hash list,
it streams the longest *consecutive* prefix of those blocks resident in
its host/disk tiers, using the same layer-major two-part codec as
prefill->decode KV transfer (``llm/kv_transfer.py``): one JSON meta item
(block count + geometry + served hashes) followed by 2·L binary parts —
layer k then layer v, blocks concatenated along the token axis — so the
receiver can deposit layer l while layer l+1 is in flight. Serving reads
through ``TieredKvCache.peek`` (no LRU perturbation, copies under the
tier lock) on the asyncio thread while the engine thread keeps serving.

Receiver side (:class:`ClusterFetcher`): a routed request arrives stamped
with the donor the router elected (``BackendInput.kv_donor``). Before the
request enters the engine, the worker fetches the prefix blocks it is
missing locally into its OWN host tier, racing client-stop, the request
deadline and ``DYN_KV_CLUSTER_FETCH_TIMEOUT`` — the ``await_remote_kv``
shape. On success, admission's normal host-tier restore uploads the pages
with zero prefill recompute of the shared blocks; on timeout/donor
death/error the request simply prefills locally (counted in
``dyn_kv_cluster_fallbacks_total``), never hangs.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import AsyncIterator, List, Optional, Sequence, Tuple

import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
import numpy as np

from ...obs.flows import record_flow
from ...runtime import deadline as dl
from ...runtime.engine import Context
from ...utils.knobs import env_float
from ...utils.prometheus import stage_metrics
from ...utils.tracing import get_tracer

log = logging.getLogger("dynamo_tpu.kv_cluster")

KV_FETCH_ENDPOINT = "kv_fetch"


def max_fetch_blocks() -> int:
    """``DYN_KV_CLUSTER_MAX_BLOCKS``: cap on blocks per peer fetch
    (0 = unlimited). Bounds both the donor's response and the receiver's
    request — one fetch moves at most this much host memory."""
    return int(env_float("DYN_KV_CLUSTER_MAX_BLOCKS", 0, minimum=0.0))


def make_kv_fetch_handler(tiered, worker_id: int = 0):
    """Donor endpoint handler over a :class:`TieredKvCache`.
    ``worker_id`` is the donor's own lease id — the ledger's src
    endpoint for the bytes this handler puts on the wire."""
    src = f"{worker_id:x}" if worker_id else str(os.getpid())

    async def handler(request, ctx: Context) -> AsyncIterator:
        hashes = [int(h) for h in (request or {}).get("hashes", [])]
        # receiver identity rides the request so the donor's tx flow
        # names the pair it served (absent on old callers -> "q")
        receiver = str((request or {}).get("receiver") or "q")
        cap = max_fetch_blocks()
        if cap:
            hashes = hashes[:cap]
        blocks: List[Tuple[int, np.ndarray, np.ndarray]] = []
        for h in hashes:
            got = tiered.peek(h)
            if got is None:
                break   # consecutive-prefix property: stop at first miss
            blocks.append((h, got[0], got[1]))
        if not blocks:
            yield {"blocks": 0}
            return
        L, H, P, D = blocks[0][1].shape
        dtype = blocks[0][1].dtype
        yield {"blocks": len(blocks), "layers": int(L), "kv_heads": int(H),
               "page": int(P), "head_dim": int(D), "dtype": str(dtype),
               "hashes": [h for h, _, _ in blocks]}
        nbytes = 0
        t0 = time.monotonic()
        for layer in range(L):
            for part_idx in (1, 2):   # k then v, layer-major
                arr = np.concatenate(
                    [b[part_idx][layer] for b in blocks], axis=1)
                part = arr.tobytes()
                nbytes += len(part)
                yield part
        stage = stage_metrics()
        elapsed = time.monotonic() - t0
        stage.kv_transfer.observe("cluster_send", value=elapsed)
        stage.kv_transfer_bytes.inc("cluster_send", amount=nbytes)
        record_flow("kv_fetch_tx", nbytes, elapsed,
                    src=src, dst=receiver)

    return handler


async def fetch_prefix(client, donor_id: int, hashes: Sequence[int],
                       context: Optional[Context] = None,
                       receiver_id: Optional[int] = None
                       ) -> List[Tuple[int, np.ndarray, np.ndarray]]:
    """Pull the consecutive prefix of ``hashes`` from ``donor_id``'s
    tiers. Returns ``[(seq_hash, k, v)]`` ([L,Hkv,page,Dh] each); empty
    when the donor no longer holds the first block.

    Arrivals stream through the shared :class:`~..kv_transfer.
    LayerStream` assembler: each layer part is scattered into the
    per-block output arrays the moment it lands (while layer l+1 is
    still in flight), the codec (order/count) is validated by the one
    implementation both receive paths share, and the observed
    (donor → this worker) bandwidth feeds the router's per-pair
    transfer-cost estimate."""
    from ..kv_transfer import LayerStream

    stage = stage_metrics()
    t0 = time.monotonic()
    meta = None
    stream: Optional[LayerStream] = None
    blocks_k = blocks_v = None
    nbytes = 0
    async with get_tracer().span("kv_cluster.fetch",
                                 donor=f"{donor_id:x}",
                                 blocks_requested=len(hashes)):
        req = {"hashes": list(hashes)}
        if receiver_id:
            req["receiver"] = f"{receiver_id:x}"
        async for item in client.generate(req, context, mode="direct",
                                          instance_id=donor_id):
            if meta is None:
                meta = item
                if not meta.get("blocks"):
                    return []
                n, L = int(meta["blocks"]), int(meta["layers"])
                H, P, D = (int(meta["kv_heads"]), int(meta["page"]),
                           int(meta["head_dim"]))
                dtype = np.dtype(meta["dtype"])
                blocks_k = np.empty((n, L, H, P, D), dtype)
                blocks_v = np.empty((n, L, H, P, D), dtype)

                def sink(layer, ka, va, _n=n, _P=P):
                    # one concatenated [H, n*P, D] layer -> that layer's
                    # slice of every per-block output array
                    for i in range(_n):
                        blocks_k[i, layer] = ka[:, i * _P:(i + 1) * _P]
                        blocks_v[i, layer] = va[:, i * _P:(i + 1) * _P]
                stream = LayerStream(L, sink)
            else:
                stream.feed(np.frombuffer(item, dtype).reshape(
                    H, int(meta["blocks"]) * P, D))
                nbytes += len(item)
    if meta is None:
        return []
    stream.close()   # truncated stream -> typed KvStreamError
    out: List[Tuple[int, np.ndarray, np.ndarray]] = []
    for i, h in enumerate(meta["hashes"][:int(meta["blocks"])]):
        out.append((int(h), blocks_k[i], blocks_v[i]))
    elapsed = time.monotonic() - t0
    stage.kv_transfer.observe("cluster_recv", value=elapsed)
    stage.kv_transfer_bytes.inc("cluster_recv", amount=nbytes)
    stage.kv_cluster_fetch_seconds.observe(value=elapsed)
    # ledger feeds observe_pair_bw itself: cluster-fetch traffic prices
    # the (donor -> receiver) pair exactly like disagg streams do
    record_flow("kv_fetch_rx", nbytes, elapsed, src=f"{donor_id:x}",
                dst=f"{receiver_id:x}" if receiver_id else "0",
                trace_id=context.id if context is not None else None)
    return out


class ClusterFetcher:
    """Receiver-side prefix prefetch for donor-stamped requests."""

    def __init__(self, core, client, worker_id: int,
                 timeout: Optional[float] = None):
        self.core = core
        self.client = client
        self.worker_id = worker_id
        self.timeout = env_float("DYN_KV_CLUSTER_FETCH_TIMEOUT", 5.0,
                                 minimum=0.0) \
            if timeout is None else float(timeout)

    def _missing_hashes(self, request) -> List[int]:
        """The chained hashes of the prefix blocks this worker lacks
        locally (device pool + tiers), up to the router's donor stamp."""
        from ..tokens import compute_seq_hashes

        tiered = self.core.tiered
        page = self.core.pool.page_size
        salt = request.kv_salt or request.lora_id
        # read-only probe: pool.contains + the tier's (locked) membership
        local = self.core.pool.probe_prefix(
            request.token_ids,
            (lambda h: h in tiered) if tiered is not None else None,
            lora_id=salt)
        hashes = compute_seq_hashes(request.token_ids, page, lora_id=salt)
        want = min(int(request.kv_donor_blocks) or len(hashes), len(hashes))
        cap = max_fetch_blocks()
        if cap:
            want = min(want, local // page + cap)
        return hashes[local // page:want]

    async def ensure_prefix(self, request, ctx: Context) -> int:
        """Fetch the stamped donor's prefix blocks into the local host
        tier before the request enters the engine. Returns blocks
        deposited (0 = nothing to do / fell back to local prefill).
        Bounded: races client-stop, the request deadline and the fetch
        timeout; every failure mode degrades to local recompute."""
        donor = int(getattr(request, "kv_donor", 0) or 0)
        if (not donor or donor == self.worker_id
                or self.core.tiered is None):
            return 0
        rem = dl.remaining(ctx.deadline)
        if rem is not None and rem <= 0:
            # already expired: the engine path raises the 504 — spawning
            # a doomed fetch would count phantom cluster fallbacks
            return 0
        missing = self._missing_hashes(request)
        if not missing:
            return 0
        stage = stage_metrics()
        fetch = asyncio.ensure_future(
            fetch_prefix(self.client, donor, missing, ctx.child(),
                         receiver_id=self.worker_id))
        stop = asyncio.ensure_future(ctx.stopped())
        try:
            timeout = self.timeout
            rem = dl.remaining(ctx.deadline)
            if rem is not None and rem < timeout:
                # fetching past the caller's deadline helps nobody; the
                # engine path raises the 504 with its own stage name
                timeout = max(rem, 0.0)
            done, _ = await asyncio.wait(
                {fetch, stop}, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            if stop in done:
                raise asyncio.CancelledError
            if fetch not in done:
                stage.kv_cluster_fallbacks.inc()
                log.warning(
                    "cluster fetch of %d blocks from %x timed out after "
                    "%.2fs; prefilling locally", len(missing), donor,
                    timeout)
                return 0
            try:
                blocks = fetch.result()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - typed fallback path
                stage.kv_cluster_fallbacks.inc()
                log.warning("cluster fetch from %x failed (%s); "
                            "prefilling locally", donor, e)
                return 0
            if not blocks:
                # donor evicted the prefix between routing and fetch
                stage.kv_cluster_fallbacks.inc()
                return 0
            tiered = self.core.tiered
            want = tuple(tiered.host.block_shape)
            got = blocks[0][1].shape
            if got != want or blocks[0][1].dtype != tiered.host.dtype:
                # geometry mismatch (donor runs a different model/TP
                # sharding than the registry claimed): depositing would
                # corrupt the tier — recompute locally instead
                stage.kv_cluster_fallbacks.inc()
                log.warning("cluster fetch from %x: block geometry %s/%s "
                            "!= local %s/%s; prefilling locally", donor,
                            got, blocks[0][1].dtype, want,
                            tiered.host.dtype)
                return 0
            for h, k, v in blocks:
                tiered.offload(h, k, v)
            stage.kv_cluster_fetches.inc()
            return len(blocks)
        finally:
            stop.cancel()
            if not fetch.done():
                fetch.cancel()
            # reap unconsumed failures quietly — a cancelled-and-abandoned
            # fetch, or one that failed in the same wait round client-stop
            # won — so nothing surfaces as a GC'd "exception never
            # retrieved"
            fetch.add_done_callback(
                lambda t: None if t.cancelled() else t.exception())
