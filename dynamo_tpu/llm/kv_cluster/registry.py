"""Global sealed-block registry: who holds which KV prefix, cluster-wide.

Each worker publishes ONE lease-bound record under the ``kv-cluster``
keyspace family (``kv_cluster/{ns}/{component}/{worker_id:x}``): its tier
geometry plus the sealed sequence hashes resident in its host and disk
tiers. Publishing is seal/evict-driven and write-coalesced the same way
stage metrics flow: the tiered cache's ``on_change`` hook marks the
publisher dirty from the engine thread, and the publish loop writes at
most one store put per ``DYN_KV_CLUSTER_PUBLISH_INTERVAL`` — and only
when the record actually changed. Lease binding is the liveness story:
a dead owner's record vanishes with its lease, so readers never chase
KV on a corpse.

Readers (:class:`KvClusterIndex`) watch the prefix and answer "which live
workers hold the first N blocks of this hash chain" — the router's
cluster-hit input. :class:`TransferCostModel` turns the merged
``llm_kv_transfer`` histograms into a peer-block score weight so a cheap
fetch scores close to a local hit and an expensive one close to a miss.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ...utils.knobs import env_float

log = logging.getLogger("dynamo_tpu.kv_cluster")

KV_CLUSTER_PREFIX = "kv_cluster/"


def cluster_prefix(namespace: str) -> str:
    """Watch prefix covering every worker record of a namespace."""
    return f"{KV_CLUSTER_PREFIX}{namespace}/"


def cluster_key(namespace: str, component: str, worker_id: int) -> str:
    """The one record a worker owns (lease-bound; dies with the owner)."""
    return f"{cluster_prefix(namespace)}{component}/{worker_id:x}"


@dataclass
class ClusterRecord:
    """One worker's registry entry: geometry + resident hashes per tier."""

    worker_id: int
    component: str = ""
    #: {"layers", "kv_heads", "page", "head_dim", "dtype"} — what a block
    #: of this owner physically is; fetch receivers validate against it
    geometry: Dict[str, Any] = field(default_factory=dict)
    host: List[int] = field(default_factory=list)
    disk: List[int] = field(default_factory=list)
    seq: int = 0

    def __post_init__(self) -> None:
        self._have = frozenset(self.host) | frozenset(self.disk)
        self._host_set = frozenset(self.host)

    @property
    def block_count(self) -> int:
        return len(self._have)

    def holds(self, seq_hash: int) -> bool:
        return seq_hash in self._have

    def tier_of(self, seq_hash: int) -> Optional[str]:
        if seq_hash in self._host_set:
            return "host"
        if seq_hash in self._have:
            return "disk"
        return None

    def block_bytes(self) -> int:
        """Approximate wire bytes of one block (k + v) from the geometry;
        0 when the geometry is unknown (pre-first-publish or foreign)."""
        g = self.geometry
        try:
            import numpy as np
            elems = (int(g["layers"]) * int(g["kv_heads"]) * int(g["page"])
                     * int(g["head_dim"]))
            return 2 * elems * np.dtype(g["dtype"]).itemsize
        except (KeyError, TypeError, ValueError):
            return 0

    def to_bytes(self) -> bytes:
        return json.dumps({
            "worker_id": self.worker_id, "component": self.component,
            "geometry": self.geometry, "host": self.host,
            "disk": self.disk, "seq": self.seq}).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "ClusterRecord":
        d = json.loads(b.decode())
        return cls(worker_id=int(d["worker_id"]),
                   component=d.get("component", ""),
                   geometry=dict(d.get("geometry") or {}),
                   host=[int(h) for h in d.get("host", [])],
                   disk=[int(h) for h in d.get("disk", [])],
                   seq=int(d.get("seq", 0)))


def tier_geometry(tiered) -> Dict[str, Any]:
    """The record geometry of a :class:`~..kvbm.tiers.TieredKvCache`."""
    import numpy as np
    L, H, P, D = tiered.host.block_shape
    return {"layers": int(L), "kv_heads": int(H), "page": int(P),
            "head_dim": int(D), "dtype": str(np.dtype(tiered.host.dtype))}


class KvClusterPublisher:
    """Worker-side: keep this worker's registry record fresh.

    Seal/evict-driven: the tiered cache's ``on_change`` hook (engine
    thread) marks the publisher dirty; the asyncio loop coalesces writes
    to one put per interval, and only when the record's content changed
    — an idle worker writes nothing. The key rides the worker's liveness
    lease, so no tombstone protocol is needed.
    """

    def __init__(self, store, namespace: str, component: str,
                 worker_id: int, lease: int, tiered,
                 interval: Optional[float] = None):
        self.store = store
        self.namespace = namespace
        self.component = component
        self.worker_id = worker_id
        self.lease = lease
        self.tiered = tiered
        self.interval = env_float("DYN_KV_CLUSTER_PUBLISH_INTERVAL", 1.0,
                                  minimum=0.0) \
            if interval is None else float(interval)
        self._geometry = tier_geometry(tiered)
        self._dirty: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._task: Optional[asyncio.Task] = None
        self._last: Optional[bytes] = None
        self._seq = 0
        self.published = 0

    def _mark_dirty(self) -> None:
        """Engine-thread hook target (tiered.on_change)."""
        loop, ev = self._loop, self._dirty
        if loop is not None and ev is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass   # loop closed mid-shutdown; nothing left to publish

    async def start(self) -> "KvClusterPublisher":
        self._loop = asyncio.get_running_loop()
        self._dirty = asyncio.Event()
        self.tiered.on_change = self._mark_dirty
        # initial record: peers must see this worker exists (possibly with
        # zero blocks) so donor-death detection is watch-driven
        await self.publish(force=True)
        self._task = asyncio.create_task(self._run(),
                                         name="kv-cluster-publish")
        return self

    async def publish(self, force: bool = False) -> str:
        """One publish beat: ``"put"`` or ``"skipped"`` (unchanged)."""
        host, disk = self.tiered.hashes()
        rec = ClusterRecord(self.worker_id, self.component, self._geometry,
                            host, disk, seq=self._seq + 1)
        payload = rec.to_bytes()
        # compare content minus the seq counter: the seq only advances on
        # a real write, so an unchanged tier set stays genuinely silent
        body = (tuple(sorted(host)), tuple(sorted(disk)))
        if not force and self._last == body:
            return "skipped"
        await self.store.put(
            cluster_key(self.namespace, self.component, self.worker_id),
            payload, lease=self.lease)
        self._last = body
        self._seq += 1
        self.published += 1
        return "put"

    async def _run(self) -> None:
        assert self._dirty is not None
        while True:
            if self.interval > 0:
                try:
                    await asyncio.wait_for(self._dirty.wait(),
                                           timeout=self.interval)
                except asyncio.TimeoutError:
                    continue   # nothing sealed/evicted: no write, no work
            else:
                # interval 0 = no coalescing: publish per change, but park
                # on the event while idle (wait_for(timeout=0) would spin)
                await self._dirty.wait()
            self._dirty.clear()
            try:
                await self.publish()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - keep the pump alive
                log.debug("kv-cluster publish deferred (%s); retrying", e)
                self._dirty.set()
                # bound the retry rate even at interval=0: a fast-failing
                # store put must not become a hot RPC loop
                await asyncio.sleep(max(self.interval, 0.5))
                continue
            # coalesce: at most one store write per interval even under a
            # seal storm (prefill bursts seal hundreds of blocks/s)
            await asyncio.sleep(self.interval)

    async def stop(self) -> None:
        self.tiered.on_change = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # best-effort: the lease reaps the key anyway, but a worker that
        # exits while its runtime lives on should vanish promptly
        try:
            await self.store.delete(cluster_key(
                self.namespace, self.component, self.worker_id))
        except Exception:  # noqa: BLE001 - cleanup must never mask exit
            log.debug("kv-cluster key cleanup failed", exc_info=True)


@dataclass
class ClusterOverlap:
    """Cluster-wide prefix availability for one request's hash chain.

    ``owners`` maps worker id -> consecutive prefix blocks that worker
    holds in its host/disk tiers (cluster view; a worker's *device*
    blocks are the indexer's ``OverlapScores``, not this). ``weight`` is
    the score value of one peer block relative to one local block
    (:meth:`TransferCostModel.weight`).

    When the router arms the pair-aware cost model, ``pair_weight`` /
    ``pair_seconds`` are callables ``(src_wid, dst_wid, blocks) ->
    float`` over the measured per-(src,dst) bandwidth: donor election
    then maximizes transfer-cost-weighted *gain* instead of raw block
    count (a near donor with fewer blocks can beat a far donor with
    more), and scoring charges the chosen placement its expected
    transfer seconds.
    """

    owners: Dict[int, int] = field(default_factory=dict)
    weight: float = 0.5
    #: (src_wid, dst_wid, blocks) -> per-block score weight for that pair
    pair_weight: Optional[Any] = None
    #: (src_wid, dst_wid, blocks) -> expected transfer seconds
    pair_seconds: Optional[Any] = None
    #: (src_wid, dst_wid) -> ledger provenance of the bandwidth behind
    #: the charged transfer term ("pair"|"into_dst"|"fleet"|"default")
    pair_source: Optional[Any] = None

    @property
    def blocks(self) -> int:
        """Best consecutive prefix length available anywhere."""
        return max(self.owners.values(), default=0)

    def weight_for(self, src: int, dst: Optional[int], blocks: int) -> float:
        if self.pair_weight is not None and dst is not None:
            return float(self.pair_weight(src, dst, blocks))
        return self.weight

    def seconds_for(self, src: int, dst: Optional[int],
                    blocks: int) -> float:
        if self.pair_seconds is not None and dst is not None:
            return float(self.pair_seconds(src, dst, blocks))
        return 0.0

    def source_for(self, src: int, dst: Optional[int]) -> str:
        """Ledger provenance of the bandwidth the charged transfer term
        was priced from ('' without an armed cost model)."""
        if self.pair_source is not None and dst is not None:
            return str(self.pair_source(src, dst))
        return ""

    def donor_for(self, worker_id: Optional[int], local_blocks: int
                  ) -> Tuple[Optional[int], int]:
        """Best donor for ``worker_id``: the OTHER owner whose extra
        consecutive blocks beyond the worker's local coverage are worth
        the most — raw block count without a cost model, transfer-cost-
        weighted gain (``extra x pair_weight``) with one, so the
        election prices the network pair, not just the prefix length."""
        best, best_n, best_gain = None, 0, 0.0
        for wid, n in self.owners.items():
            if wid == worker_id:
                continue
            extra = n - local_blocks
            if extra <= 0:
                continue
            gain = extra * self.weight_for(wid, worker_id, extra)
            if best is None or gain > best_gain + 1e-12:
                best, best_n, best_gain = wid, n, gain
        return best, best_n


class KvClusterIndex:
    """Router/operator-side registry reader: watches the ``kv-cluster``
    prefix and answers prefix-availability queries. Owner records vanish
    with their lease (store watch delivers the delete), so a dead donor
    disappears from scoring within one watch delivery."""

    def __init__(self):
        self.records: Dict[int, ClusterRecord] = {}
        self._key_owner: Dict[str, int] = {}
        # set only during start(): keys touched by live watch events while
        # the watch-registration RPC was in flight
        self._live_touched: Optional[Set[str]] = None

    async def start(self, store, namespace: str) -> "KvClusterIndex":
        # Live watch events can fire DURING the watch_prefix await, before
        # the (older) snapshot is applied — most dangerously a lease-death
        # delete, which has no later event to correct it. Record which keys
        # the live stream touched and never let the stale snapshot
        # overwrite (or resurrect) them.
        self._live_touched = set()
        snapshot = await store.watch_prefix(cluster_prefix(namespace),
                                            self._on_change)
        touched, self._live_touched = self._live_touched, None
        for key, value in snapshot:
            if key in touched:
                continue
            await self._on_change(key, value, False)
        return self

    async def _on_change(self, key: str, value: Optional[bytes],
                         deleted: bool) -> None:
        if self._live_touched is not None:
            self._live_touched.add(key)
        if deleted:
            wid = self._key_owner.pop(key, None)
            if wid is not None:
                self.records.pop(wid, None)
            return
        try:
            rec = ClusterRecord.from_bytes(value)
        except (ValueError, KeyError, TypeError):
            log.warning("malformed kv-cluster record at %s", key)
            return
        self.records[rec.worker_id] = rec
        self._key_owner[key] = rec.worker_id

    def remove_worker(self, worker_id: int) -> None:
        self.records.pop(worker_id, None)

    def find(self, seq_hashes: Sequence[int], weight: float = 0.5,
             component: Optional[str] = None) -> ClusterOverlap:
        """Per-owner consecutive prefix coverage of a hash chain.
        ``component`` restricts owners to one worker component — a donor
        from another component (disagg prefill pool, another model) is
        unreachable through the receiver's fetch client and must not be
        elected or credited in scoring."""
        out = ClusterOverlap(weight=weight)
        for wid, rec in self.records.items():
            if component is not None and rec.component != component:
                continue
            n = 0
            for h in seq_hashes:
                if not rec.holds(h):
                    break
                n += 1
            if n:
                out.owners[wid] = n
        return out

    def block_bytes(self, worker_id: int) -> int:
        rec = self.records.get(worker_id)
        return rec.block_bytes() if rec is not None else 0

    def any_block_bytes(self) -> int:
        for rec in self.records.values():
            b = rec.block_bytes()
            if b:
                return b
        return 0


class TransferCostModel:
    """KV-movement cost estimates from measured transfer bandwidth —
    fleet-wide AND per-(src,dst) worker pair.

    The router already merges every worker's ``llm_kv_transfer_seconds``
    histogram and ``llm_kv_transfer_bytes_total`` counter;
    :meth:`update_from_states` differentiates them into a fleet-wide
    observed bytes/s, and additionally reads the receiver-side
    ``llm_kv_pair_bw_bytes_per_s`` gauges (EWMA per pair, see
    ``kv_transfer.observe_pair_bw``) so a placement can be priced on the
    SPECIFIC network pair it would move bytes over — NetKV's point:
    decode selection must price the pair, not just the load.

    :meth:`weight` discounts a peer block by the estimated fetch time:
    ``base / (1 + est_seconds)`` — a free fetch is worth
    ``DYN_KV_CLUSTER_PEER_WEIGHT`` of a local block, a one-second fetch
    half that, never zero (a peer hit always beats recompute in score).
    :meth:`estimate_seconds` is the raw expected-transfer-seconds term
    ``score_candidates`` folds into the logit.
    """

    #: assumed bandwidth before any transfer has been measured (loopback
    #: host staging comfortably exceeds this; DCN is in the same decade)
    DEFAULT_BYTES_PER_S = 1e9

    def __init__(self, base_weight: Optional[float] = None):
        self.base = env_float("DYN_KV_CLUSTER_PEER_WEIGHT", 0.5,
                              minimum=0.0) \
            if base_weight is None else float(base_weight)
        self.bytes_per_s: Optional[float] = None
        #: (src_hex, dst_hex) -> observed bytes/s; src ``"q"`` is the
        #: anonymous prefill pool (disagg pushes without a worker id)
        self.pair_bw: Dict[Tuple[str, str], float] = {}

    def update_from_states(self, states) -> None:
        """Fold a ``fetch_stage_states`` result into the bandwidth
        estimates (lifetime totals for the fleet-wide rate, last-EWMA
        gauges for the pairs)."""
        secs = 0.0
        byts = 0.0
        pairs: Dict[Tuple[str, str], float] = {}
        for _component, dump in states:
            h = dump.get("llm_kv_transfer_seconds") or {}
            for val in (h.get("series") or {}).values():
                secs += float(val.get("sum", 0.0))
            c = dump.get("llm_kv_transfer_bytes_total") or {}
            for val in (c.get("series") or {}).values():
                byts += float(val)
            g = dump.get("llm_kv_pair_bw_bytes_per_s") or {}
            for skey, val in (g.get("series") or {}).items():
                labels = skey.split("\x1f")
                if len(labels) == 2 and float(val) > 0:
                    pairs[(labels[0], labels[1])] = float(val)
        if secs > 0 and byts > 0:
            self.bytes_per_s = byts / secs
        if pairs:
            self.pair_bw = pairs

    @staticmethod
    def _hex(wid) -> Optional[str]:
        if wid is None:
            return None
        return wid if isinstance(wid, str) else f"{wid:x}"

    def bandwidth_info(self, src=None, dst=None) -> Tuple[float, str]:
        """Best-informed bytes/s for a (src, dst) movement plus its
        ledger provenance: ``"pair"`` (the exact pair's EWMA — fed by
        every flow kind the byte-flow ledger records over that pair),
        ``"into_dst"`` (mean of observed pairs INTO ``dst``; a disagg
        push's source is the anonymous prefill pool), ``"fleet"`` (the
        fleet-wide differentiated rate) or ``"default"`` (nothing
        measured yet). The provenance string is stamped into the
        router's decision ring so a charged transfer term is auditable
        back to what the ledger had actually seen."""
        s, d = self._hex(src), self._hex(dst)
        if s is not None and d is not None:
            bw = self.pair_bw.get((s, d))
            if bw:
                return bw, "pair"
        if d is not None:
            into = [bw for (_, dk), bw in self.pair_bw.items() if dk == d]
            if into:
                return sum(into) / len(into), "into_dst"
        if self.bytes_per_s:
            return self.bytes_per_s, "fleet"
        return self.DEFAULT_BYTES_PER_S, "default"

    def bandwidth(self, src=None, dst=None) -> float:
        return self.bandwidth_info(src, dst)[0]

    def estimate_seconds(self, blocks: int, block_bytes: int,
                         src=None, dst=None) -> float:
        bw = self.bandwidth(src, dst)
        return (blocks * block_bytes) / bw if bw > 0 else 0.0

    def weight(self, blocks: int, block_bytes: int,
               src=None, dst=None) -> float:
        return self.base / (1.0 + self.estimate_seconds(
            blocks, block_bytes, src=src, dst=dst))
