"""Worker-side wiring of the cluster KV sharing plane.

:meth:`KvClusterWorker.attach` is everything a worker binary (or test)
needs: serve the ``kv_fetch`` donor endpoint over the engine's tiered
cache, start the registry publisher (lease-bound record under the
``kv-cluster`` keyspace family), and build the peer-fetch client +
:class:`~.fetch.ClusterFetcher`. :class:`ClusterPrefetchEngine` wraps any
core engine so donor-stamped requests prefetch their missing prefix into
the host tier before admission — the engine's normal tier restore then
uploads the pages with zero prefill recompute of the shared blocks.
"""

from __future__ import annotations

import logging
from typing import AsyncIterator, Callable, Optional

from ...runtime.engine import AsyncEngine, Context
from ...utils.aiotasks import spawn_blocking
from .fetch import KV_FETCH_ENDPOINT, ClusterFetcher, make_kv_fetch_handler
from .registry import KvClusterPublisher

log = logging.getLogger("dynamo_tpu.kv_cluster")


class ClusterPrefetchEngine(AsyncEngine):
    """Engine decorator: bounded donor prefetch + local-tier h2d
    prefetch before generation.

    The donor fetch overlaps the engine's in-flight dispatch queue
    (other requests keep dispatching while this one's blocks stream in)
    and degrades to plain local prefill on any failure — the inner
    engine never sees the difference beyond a warmer host tier.

    ``prefetcher`` (the engine's ``prefetch_tiers``, when supported)
    then starts the h2d upload of every matched host/disk-tier prefix
    block — including what the donor fetch just deposited — on an
    executor thread WHILE the request sits in the slot-gate queue the
    wrap encloses: by admission, the blocks are device-staged and the
    restore is a d2d scatter instead of a first-prefill-blocking h2d
    (the PRESERVE direction: the router's placement already committed
    this worker, so the movement its hit implies starts immediately).
    """

    def __init__(self, inner: AsyncEngine, fetcher: ClusterFetcher,
                 prefetcher: Optional[Callable] = None):
        self.inner = inner
        self.fetcher = fetcher
        self.prefetcher = prefetcher

    async def generate(self, request, context: Context) -> AsyncIterator:
        await self.fetcher.ensure_prefix(request, context)
        if self.prefetcher is not None:
            # retained: runs concurrently with the inner engine's queue
            # wait; prefetch_tiers owns its own fallback semantics
            spawn_blocking(self.prefetcher, request, name="h2d-prefetch")
        async for item in self.inner.generate(request, context):
            yield item


class KvClusterWorker:
    """One worker's attachment to the cluster sharing plane."""

    def __init__(self, publisher: KvClusterPublisher,
                 fetcher: ClusterFetcher, client):
        self.publisher = publisher
        self.fetcher = fetcher
        self.client = client

    @classmethod
    async def attach(cls, component, drt, namespace: str, core,
                     publish_interval: Optional[float] = None,
                     fetch_timeout: Optional[float] = None
                     ) -> Optional["KvClusterWorker"]:
        """Serve ``kv_fetch``, start the registry publisher, build the
        peer client. Returns None (with a warning) when the engine has no
        host tier — cluster sharing without somewhere to stage blocks is
        meaningless."""
        if core.tiered is None:
            log.warning("kv-cluster enabled but the engine has no host "
                        "tier (host_cache_blocks=0); cluster KV sharing "
                        "disabled on this worker")
            return None
        endpoint = component.endpoint(KV_FETCH_ENDPOINT)
        await endpoint.serve(make_kv_fetch_handler(
            core.tiered, worker_id=drt.worker_id))
        publisher = await KvClusterPublisher(
            drt.store, namespace, component.name, drt.worker_id, drt.lease,
            core.tiered, interval=publish_interval).start()
        client = await endpoint.client().start()
        fetcher = ClusterFetcher(core, client, drt.worker_id,
                                 timeout=fetch_timeout)
        log.info("kv-cluster attached: worker %x publishing + serving %s",
                 drt.worker_id, KV_FETCH_ENDPOINT)
        return cls(publisher, fetcher, client)

    def wrap(self, engine: AsyncEngine,
             prefetcher: Optional[Callable] = None) -> AsyncEngine:
        return ClusterPrefetchEngine(engine, self.fetcher,
                                     prefetcher=prefetcher)

    async def stop(self) -> None:
        await self.publisher.stop()
