"""Bring-your-own Python engines: ``out=pystr:file.py`` / ``out=pytok:file.py``.

The user file defines one coroutine generator::

    async def generate(request, context):
        yield ...

- **pystr** (string level): ``request`` is the fully templated prompt
  string; yields are text chunks streamed straight to the client. The
  framework still does chat templating, SSE framing and usage accounting
  (token counts via the card's tokenizer) around it.
- **pytok** (token level): ``request`` is a ``BackendInput`` (token_ids,
  sampling, stop); yields are token ids (int or list[int]) or complete
  ``EngineOutput`` objects. Detokenization, stop handling and the OpenAI
  layer run on top exactly as for the in-tree engine; ``max_tokens`` is
  enforced regardless of which shape the generator yields.

Reference capability: lib/engines/python (pystr:/pytok: engines loaded from
a user Python file via PyO3); this is the same contract bridged natively.
"""

from __future__ import annotations

import contextlib
import dataclasses
import importlib.util
import os
from typing import AsyncIterator

from ..runtime.engine import AsyncEngine, Context
from .model_card import ModelDeploymentCard
from .preprocessor import Preprocessor
from .protocols.common import BackendInput, EngineOutput, FinishReason
from .protocols.openai import ProtocolError


class PythonEngineError(RuntimeError):
    pass


def _load_generate(path: str):
    if not os.path.isfile(path):
        raise PythonEngineError(f"python engine file not found: {path}")
    spec = importlib.util.spec_from_file_location(
        f"_dynamo_pyengine_{abs(hash(os.path.abspath(path)))}", path)
    if spec is None or spec.loader is None:
        raise PythonEngineError(
            f"{path} is not loadable as a Python module (needs a .py file)")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = getattr(mod, "generate", None)
    if fn is None:
        raise PythonEngineError(
            f"{path} must define 'async def generate(request, context)'")
    return fn


async def _drive(agen, context: Context):
    """Iterate a user async generator with the FnEngine discipline: stop on
    kill, close the generator on any early exit so its cleanup runs now."""
    try:
        async for item in agen:
            if context.is_killed:
                return
            yield item
    finally:
        with contextlib.suppress(Exception):
            await agen.aclose()


class PyTokCoreEngine(AsyncEngine[BackendInput, EngineOutput]):
    """Token-level user engine: BackendInput -> stream of token ids."""

    def __init__(self, path: str):
        self._fn = _load_generate(path)
        self.path = path

    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        emitted = 0
        budget = request.stop.max_tokens
        async with contextlib.aclosing(
                _drive(self._fn(request, context), context)) as agen:
            async for item in agen:
                if context.is_stopped:
                    yield EngineOutput(token_ids=[],
                                       finish_reason=FinishReason.CANCELLED)
                    return
                if isinstance(item, EngineOutput):
                    out = item
                else:
                    ids = [int(item)] if isinstance(item, int) else \
                        [int(t) for t in item]
                    out = EngineOutput(token_ids=ids)
                # the client's max_tokens binds whichever shape the user
                # yields — truncate a multi-token item at the boundary.
                # Copy rather than mutate: a user engine may retain the
                # object it yielded.
                if budget is not None and emitted + len(out.token_ids) >= budget:
                    out = dataclasses.replace(
                        out,
                        token_ids=out.token_ids[:budget - emitted],
                        finish_reason=out.finish_reason or FinishReason.LENGTH)
                emitted += len(out.token_ids)
                yield out
                if out.finish_reason is not None:
                    return
        # generator exhausted — or _drive bailed on kill, which is not a
        # clean completion
        yield EngineOutput(
            token_ids=[],
            finish_reason=(FinishReason.CANCELLED if context.is_killed
                           else FinishReason.STOP))


class _PyStrTextEngine(AsyncEngine):
    """Text-level engine over the user fn: renders the prompt (chat
    template, tool_choice guard) and streams the user's text chunks.
    OpenAI framing is FullEngineAdapter's job — not duplicated here."""

    def __init__(self, fn, card: ModelDeploymentCard, kind: str):
        self._fn = fn
        self.kind = kind
        self._pre = Preprocessor(card)

    def _prompt(self, request) -> str:
        if self.kind == "chat":
            # same tools contract as the in-tree preprocessor: with
            # tool_choice='none' the schemas stay out of the prompt
            tools = (None if getattr(request, "tool_choice", None) == "none"
                     else getattr(request, "tools", None))
            return self._pre.render_chat(request.messages, tools)
        raw = request.prompt
        if not isinstance(raw, str):
            # match preprocess_completion: token-id / batched prompts are
            # rejected, not silently replaced with ""
            raise ProtocolError(
                "pystr engines accept string prompts only")
        return raw

    async def generate(self, request, context: Context):
        prompt = self._prompt(request)
        async with contextlib.aclosing(
                _drive(self._fn(prompt, context), context)) as agen:
            async for text in agen:
                if context.is_stopped:
                    return
                yield str(text)


def build_python_engines(spec: str, card: ModelDeploymentCard):
    """``spec``: 'pystr:path.py' or 'pytok:path.py'. Returns the
    (chat_engine, completion_engine) pair at the OpenAI level."""
    from .pipeline import (
        FullEngineAdapter,
        build_chat_engine,
        build_completion_engine,
    )
    from .tokenizer import load_tokenizer

    kind, _, path = spec.partition(":")
    if not path:
        raise PythonEngineError(f"{kind}: needs a file path ({kind}:file.py)")
    if kind == "pytok":
        core = PyTokCoreEngine(path)
        return (build_chat_engine(card, "core", core),
                build_completion_engine(card, "core", core))
    if kind == "pystr":
        tok = load_tokenizer(card.tokenizer)
        # one module exec shared by both endpoints: a user file that loads
        # a model at module scope must pay that load once
        fn = _load_generate(path)
        return (
            FullEngineAdapter(card.name,
                              _PyStrTextEngine(fn, card, "chat"),
                              "chat", tokenizer=tok),
            FullEngineAdapter(card.name,
                              _PyStrTextEngine(fn, card, "completion"),
                              "completion", tokenizer=tok),
        )
    raise PythonEngineError(f"unknown python engine kind {kind!r}")
