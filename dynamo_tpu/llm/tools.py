"""Tool-calling support: request-side validation of ``tools``/``tool_choice``
and response-side matching of model output into OpenAI ``tool_calls``.

The model signals a tool call by emitting a JSON object (or array) of the
shape ``{"name": ..., "parameters"|"arguments": {...}}`` — the convention the
chat template establishes when it renders the tool list. The matcher parses
the *complete* generated message; arguments are re-serialized to a JSON
string per the OpenAI wire shape.

Reference capability: lib/llm/src/preprocessor/tools.rs:30-115
(ToolCallingMatcher over the same four accepted shapes), tools/request.rs
(ToolChoice), tools/response.rs (ToolCallResponse).
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .protocols.openai import ProtocolError

# internal tool_choice modes
CHOICE_NONE = "none"
CHOICE_AUTO = "auto"
CHOICE_REQUIRED = "required"


def normalize_tools(tools: Any) -> Optional[List[Dict[str, Any]]]:
    """Validate the OpenAI ``tools`` array. Returns None when absent/empty."""
    if tools is None:
        return None
    if not isinstance(tools, list):
        raise ProtocolError("'tools' must be a list")
    if not tools:
        return None
    out = []
    for t in tools:
        if not isinstance(t, dict) or t.get("type") != "function":
            raise ProtocolError("each tool must be {'type': 'function', ...}")
        fn = t.get("function")
        if not isinstance(fn, dict) or not isinstance(fn.get("name"), str):
            raise ProtocolError("tool.function needs a string 'name'")
        out.append(t)
    return out


def normalize_tool_choice(choice: Any,
                          tools: Optional[List[Dict[str, Any]]]
                          ) -> Tuple[str, Optional[str]]:
    """Returns (mode, forced_tool_name). mode is none|auto|required."""
    if choice is None:
        return (CHOICE_AUTO if tools else CHOICE_NONE), None
    if choice in (CHOICE_NONE, CHOICE_AUTO, CHOICE_REQUIRED):
        if choice != CHOICE_NONE and not tools:
            raise ProtocolError(f"tool_choice {choice!r} requires 'tools'")
        return choice, None
    if isinstance(choice, dict) and choice.get("type") == "function":
        name = (choice.get("function") or {}).get("name")
        if not isinstance(name, str):
            raise ProtocolError("tool_choice.function needs a string 'name'")
        if not any((t.get("function") or {}).get("name") == name
                   for t in tools or []):
            raise ProtocolError(f"tool_choice names unknown tool {name!r}")
        return CHOICE_REQUIRED, name
    raise ProtocolError(
        "tool_choice must be 'none'|'auto'|'required' or "
        "{'type':'function','function':{'name':...}}")


def _call_dict(name: str, arguments: Any) -> Dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call-{uuid.uuid4()}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


class ToolCallingMatcher:
    """Parses a complete assistant message into tool calls.

    Accepted shapes (reference tools.rs:53-113): a single object or an array
    of objects carrying ``name`` + ``parameters``/``arguments`` (dict or
    pre-serialized string). Anything unparseable is plain content — unless a
    specific tool (or 'required') was demanded, which is then an error.
    """

    def __init__(self, mode: str, forced_name: Optional[str] = None):
        self.mode = mode
        self.forced_name = forced_name

    def get_calls(self, message: str,
                  complete: bool = True) -> List[Dict[str, Any]]:
        """``complete=False`` marks a cancelled/truncated generation: parsing
        is still attempted (a finished JSON call that ran into max_tokens is
        fine), but the 'required' violation is not raised — the model never
        got the chance to finish its call."""
        if self.mode == CHOICE_NONE:
            return []
        calls = self._parse(message)
        if not calls and self.mode == CHOICE_REQUIRED and complete:
            raise ProtocolError(
                "tool_choice required a tool call but the model produced none")
        if self.forced_name and calls:
            bad = [c for c in calls
                   if c["function"]["name"] != self.forced_name]
            if bad:
                raise ProtocolError(
                    f"model called {bad[0]['function']['name']!r} but "
                    f"tool_choice forced {self.forced_name!r}")
        return calls

    @staticmethod
    def _parse(message: str) -> List[Dict[str, Any]]:
        text = message.strip()
        # tolerate a fenced block around the JSON
        if text.startswith("```"):
            text = text.strip("`")
            if text.startswith("json"):
                text = text[4:]
            text = text.strip()
        try:
            data = json.loads(text)
        except ValueError:
            return []
        items = data if isinstance(data, list) else [data]
        calls = []
        for item in items:
            if not isinstance(item, dict) or not isinstance(item.get("name"), str):
                return []
            args = item.get("parameters", item.get("arguments"))
            if args is None or not isinstance(args, (dict, str)):
                return []
            calls.append(_call_dict(item["name"], args))
        return calls
