"""JSONL record/replay of event streams.

Capture any dict-event stream (KV events, router decisions) to a JSONL file
with timestamps, and replay it later — deterministic router tests and offline
analysis. Reference capability: lib/llm/src/recorder.rs:38-291 + KvRecorder.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Iterator, List, Optional


class Recorder:
    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")
        self.count = 0

    def record(self, event: Dict[str, Any]) -> None:
        self._f.write(json.dumps({"ts": time.time(), "event": event}) + "\n")
        self.count += 1

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Recorder":
        return self

    def __exit__(self, *a) -> None:
        self.close()


def replay(path: str, speed: Optional[float] = None
           ) -> Iterator[Dict[str, Any]]:
    """Yield recorded events; ``speed`` (e.g. 1.0) reproduces original pacing,
    None replays as fast as possible."""
    prev_ts: Optional[float] = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if speed and prev_ts is not None:
                delta = (rec["ts"] - prev_ts) / speed
                if delta > 0:
                    time.sleep(delta)
            prev_ts = rec["ts"]
            yield rec["event"]


class KvRecorder(Recorder):
    """Recorder wired as a KV event publish function."""

    async def publish(self, subject: str, payload: Dict[str, Any]) -> None:
        self.record({"subject": subject, "payload": payload})

    def replay_into(self, apply: Callable[[Dict[str, Any]], None]) -> int:
        n = 0
        for ev in replay(self.path):
            apply(ev["payload"])
            n += 1
        return n
