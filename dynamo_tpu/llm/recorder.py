"""JSONL record/replay of event streams.

Capture any dict-event stream (KV events, router decisions) to a JSONL file
with timestamps, and replay it later — deterministic router tests and offline
analysis. :class:`Recorder` supports pause/resume, predicate filtering, and
auto-stop bounds (max events / max duration); :class:`KvRecorder` taps the
live event plane directly (``attach`` subscribes a component's ``kv_events``
subject) and replays a capture straight into a ``KvIndexer`` — so a recorded
production stream can drive router tests bit-for-bit.

Reference capability: lib/llm/src/recorder.rs:38-291 (Recorder with
pause/resume + event bounds) and KvRecorder (event-plane tap + indexer feed,
recorder.rs KvRecorder::new / send_events).
"""

from __future__ import annotations

import json
import time
from typing import (Any, AsyncIterator, Callable, Dict, Iterator, List,
                    Optional)

EventFilter = Callable[[Dict[str, Any]], bool]


class Recorder:
    """Append-only JSONL event capture.

    - ``filter_fn``: events failing the predicate are counted in
      ``skipped`` and not written.
    - ``max_events`` / ``max_duration_s``: the recorder auto-stops once
      either bound is reached (``stopped`` turns True; further events are
      skipped) — bounded captures on unbounded streams.
    - :meth:`pause` / :meth:`resume`: gate recording without tearing down
      the file or the subscriptions feeding it.
    """

    def __init__(self, path: str, filter_fn: Optional[EventFilter] = None,
                 max_events: Optional[int] = None,
                 max_duration_s: Optional[float] = None):
        self.path = path
        self._f = open(path, "a")
        self.filter_fn = filter_fn
        self.max_events = max_events
        self.max_duration_s = max_duration_s
        self.count = 0
        self.skipped = 0
        self.paused = False
        self.stopped = False
        self._t0 = time.monotonic()

    # ------------------------------------------------------------------
    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def record(self, event: Dict[str, Any]) -> bool:
        """Write one event; returns False when gated (paused/stopped/
        filtered) — the caller's stream keeps flowing either way."""
        if self.stopped or self.paused:
            self.skipped += 1
            return False
        if (self.max_duration_s is not None
                and self.elapsed() >= self.max_duration_s):
            self.stopped = True
            self.skipped += 1
            return False
        if self.filter_fn is not None and not self.filter_fn(event):
            self.skipped += 1
            return False
        self._f.write(json.dumps({"ts": time.time(), "event": event}) + "\n")
        self.count += 1
        if self.max_events is not None and self.count >= self.max_events:
            self.stopped = True
        return True

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        # stop BEFORE closing: a live event-plane tap (attach) has no
        # unsubscribe surface, so record() must gate every later event
        # instead of raising on a closed file
        self.stopped = True
        self._f.close()

    def __enter__(self) -> "Recorder":
        return self

    def __exit__(self, *a) -> None:
        self.close()


def _iter_paced(path: str, speed: Optional[float]) -> Iterator[tuple]:
    """Shared parse-and-pace core of :func:`replay` / :func:`areplay`:
    yields ``(delay_s, event)``, where ``delay_s`` is how long a paced
    replay waits BEFORE delivering the event (0.0 unpaced). The two
    public replays differ ONLY in how they sleep."""
    prev_ts: Optional[float] = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            delay = 0.0
            if speed and prev_ts is not None:
                delay = max(0.0, (rec["ts"] - prev_ts) / speed)
            prev_ts = rec["ts"]
            yield delay, rec["event"]


def replay(path: str, speed: Optional[float] = None
           ) -> Iterator[Dict[str, Any]]:
    """Yield recorded events; ``speed`` (e.g. 1.0) reproduces original pacing,
    None replays as fast as possible.

    Offline/sync use only: pacing blocks in ``time.sleep``. From a running
    event loop (replaying a capture into a live router/indexer) use
    :func:`areplay` — a paced sync replay on the loop would freeze every
    other coroutine for the capture's full duration.
    """
    for delay, event in _iter_paced(path, speed):
        if delay > 0:
            time.sleep(delay)
        yield event


async def areplay(path: str, speed: Optional[float] = None
                  ) -> "AsyncIterator[Dict[str, Any]]":
    """Async :func:`replay`: paces with ``asyncio.sleep`` so a live replay
    shares the loop instead of parking it."""
    import asyncio

    for delay, event in _iter_paced(path, speed):
        # sleep(0) on the unpaced path is a bare yield: replaying a large
        # capture must not park every other coroutine on the loop
        await asyncio.sleep(delay)
        yield event


class KvRecorder(Recorder):
    """Recorder wired to the KV event plane.

    Two ingestion paths:
    - hand :meth:`publish` to a :class:`KvEventPublisher` as its transport
      (records instead of, or alongside, publishing);
    - :meth:`attach` subscribes a live component's ``kv_events`` subject and
      records every RouterEvent payload that flows — the production tap.

    Replay feeds a ``KvIndexer`` (or anything with ``apply_sync``)
    directly, reproducing the radix-tree state the live router had.
    """

    async def publish(self, subject: str, payload: Dict[str, Any]) -> None:
        self.record({"subject": subject, "payload": payload})

    async def attach(self, component, subject: Optional[str] = None
                     ) -> "KvRecorder":
        """Subscribe ``component``'s KV-event subject; every payload is
        recorded (subject to pause/filter/bounds)."""
        from .kv_router.protocols import KV_EVENT_SUBJECT

        subject = subject or KV_EVENT_SUBJECT

        async def on_event(payload: Dict[str, Any]) -> None:
            self.record({"subject": subject, "payload": payload})

        await component.subscribe(subject, on_event)
        return self

    # ------------------------------------------------------------------
    def replay_into(self, apply: Callable[[Dict[str, Any]], None],
                    speed: Optional[float] = None) -> int:
        n = 0
        for ev in replay(self.path, speed=speed):
            apply(ev["payload"])
            n += 1
        return n

    async def replay_into_async(self, apply: Callable[[Dict[str, Any]],
                                                      None],
                                speed: Optional[float] = None) -> int:
        """:meth:`replay_into` for a running event loop: paced replays
        into a LIVE indexer/router must not block its loop."""
        n = 0
        async for ev in areplay(self.path, speed=speed):
            apply(ev["payload"])
            n += 1
        return n

    def replay_into_indexer(self, indexer, speed: Optional[float] = None,
                            worker_ids: Optional[List[int]] = None) -> int:
        """Feed the capture straight into a KvIndexer: each payload parses
        as a RouterEvent and applies in recorded order. ``worker_ids``
        restricts the replay to a subset of workers (per-worker analysis of
        a cluster-wide capture). Returns events applied."""
        from .kv_router.protocols import RouterEvent

        n = 0
        for ev in replay(self.path, speed=speed):
            rev = RouterEvent.from_dict(ev["payload"])
            if worker_ids is not None and rev.worker_id not in worker_ids:
                continue
            indexer.apply_sync(rev)
            n += 1
        return n
