"""Preprocessor: OpenAI request -> BackendInput (template, tokenize, stops).

This is the forward half of the request pipeline. It renders the chat
template (jinja2), tokenizes, assembles sampling/stop conditions, and attaches
requested annotations (``formatted_prompt``, ``token_ids``).

Reference capability: lib/llm/src/preprocessor.rs:63-359 (OpenAIPreprocessor,
prompt templating, stop-condition assembly, annotations).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple, Union

import jinja2

from .model_card import CHATML_TEMPLATE, ModelDeploymentCard
from .protocols.common import (
    BackendInput,
    OutputOptions,
    SamplingOptions,
    StopConditions,
)
from .protocols.openai import ChatCompletionRequest, CompletionRequest, ProtocolError
from .tokenizer import Tokenizer, load_tokenizer

_JINJA_ENV = jinja2.Environment(
    loader=jinja2.BaseLoader(), trim_blocks=False, lstrip_blocks=False,
    # chat templates use tojson and raise_exception
    extensions=[],
)
_JINJA_ENV.globals["raise_exception"] = lambda msg: (_ for _ in ()).throw(
    ProtocolError(f"chat template error: {msg}")
)


def content_text(content: Any) -> str:
    """Message content as text: plain string, OpenAI multipart list of
    {'type':'text','text':...} parts, or None (tool-call messages)."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        return "".join(p.get("text", "") for p in content
                       if isinstance(p, dict))
    return "" if content is None else str(content)


# VLM: image parts are replaced by a sentinel in the rendered prompt, then
# spliced back as placeholder TOKEN ids after segmented tokenization (the
# byte-level sentinel survives any template; token-level splicing is what
# HF processors do too — boi + N soft tokens + eoi per image)
_IMG_SENTINEL = "\x00<dynimg:{k}>\x00"
_IMG_SPLIT = re.compile("\x00<dynimg:(\\d+)>\x00")


def _decode_data_url(url: str):
    """data:image/...;base64,... -> uint8 HWC numpy array."""
    import base64
    import io

    import numpy as np

    if not url.startswith("data:"):
        raise ProtocolError(
            "only data: image URLs are supported (no egress from the "
            "serving host); send base64-embedded images")
    try:
        payload = url.split(",", 1)[1]
        raw = base64.b64decode(payload)
        from PIL import Image

        img = Image.open(io.BytesIO(raw)).convert("RGB")
        return np.asarray(img, np.uint8)
    except ProtocolError:
        raise
    except Exception as e:
        raise ProtocolError(f"could not decode image: {e}") from e


def image_kv_salt(lora_id: int, images: List[Any]) -> int:
    """KV block-hash chain salt for a VLM request: ``lora_id`` folded with a
    digest of the raw decoded pixel content. Computed HERE (frontend) and
    carried on ``BackendInput.kv_salt`` so the KV router's prefix-overlap
    scoring and the engine's published blocks hash under the SAME salt —
    identical (prompt, images) requests match across workers, while the same
    placeholder tokens with different images can never alias."""
    import hashlib

    import numpy as np

    h = hashlib.blake2b(digest_size=8)
    for im in images:
        arr = np.ascontiguousarray(np.asarray(im))
        h.update(arr.tobytes())
    digest = int.from_bytes(h.digest(), "little")
    return (lora_id ^ digest) & ((1 << 63) - 1)


def extract_images(messages: List[Dict[str, Any]]
                   ) -> Tuple[List[Any], List[Dict[str, Any]]]:
    """Pull image_url parts out of OpenAI multipart messages; each becomes
    a decoded pixel array plus an in-text sentinel marking its position."""
    images: List[Any] = []
    out = []
    for m in messages:
        c = m.get("content")
        if isinstance(c, list) and any(
                isinstance(p, dict) and p.get("type") == "image_url"
                for p in c):
            parts = []
            for p in c:
                if isinstance(p, dict) and p.get("type") == "image_url":
                    url = (p.get("image_url") or {}).get("url", "")
                    images.append(_decode_data_url(url))
                    parts.append({"type": "text",
                                  "text": _IMG_SENTINEL.format(
                                      k=len(images) - 1)})
                else:
                    parts.append(p)
            m = {**m, "content": parts}
        out.append(m)
    return images, out


@dataclass
class PreprocessedRequest:
    backend_input: BackendInput
    formatted_prompt: Optional[str]
    annotations: Dict[str, Any]


class Preprocessor:
    """Stateless per-model preprocessor bound to a card + tokenizer."""

    def __init__(self, card: ModelDeploymentCard, tokenizer: Optional[Tokenizer] = None):
        self.card = card
        self.tokenizer = tokenizer or load_tokenizer(card.tokenizer)
        src = card.chat_template or CHATML_TEMPLATE
        self._template = _JINJA_ENV.from_string(src)

    # ------------------------------------------------------------------
    def render_chat(self, messages: List[Dict[str, Any]],
                    tools: Optional[List[Dict[str, Any]]] = None) -> str:
        # normalize OpenAI multipart content ([{'type':'text','text':...}])
        # and None (tool-call messages) to plain strings: chat templates
        # concatenate content directly
        msgs = [{**m, "content": content_text(m.get("content"))}
                for m in messages]
        try:
            return self._template.render(
                messages=msgs,
                tools=tools,
                add_generation_prompt=True,
                bos_token="",
                eos_token="",
            )
        except jinja2.TemplateError as e:
            raise ProtocolError(f"chat template failed: {e}") from e

    # ------------------------------------------------------------------
    def preprocess_chat(self, req: ChatCompletionRequest) -> PreprocessedRequest:
        images: List[Any] = []
        messages = req.messages
        if not bool(req.ext.get("use_raw_prompt")):
            images, messages = extract_images(messages)
        if bool(req.ext.get("use_raw_prompt")) and req.messages:
            # raw-prompt escape hatch: single user message passed through untemplated
            prompt = "".join(str(m.get("content", "")) for m in req.messages)
        else:
            # tool_choice='none' disables the matcher, so the tool list must
            # stay out of the prompt too — otherwise the template invites
            # tool-call JSON that would stream back as plain content
            tools = None if req.tool_choice == "none" else req.tools
            prompt = self.render_chat(messages, tools)
        if images:
            token_ids = self._encode_with_images(prompt, len(images))
        else:
            token_ids = self.tokenizer.encode(prompt)
        bi = self._assemble(
            token_ids,
            model=req.model,
            max_tokens=req.max_tokens,
            stop=req.stop,
            temperature=req.temperature,
            top_p=req.top_p,
            top_k=req.top_k,
            n=req.n,
            seed=req.seed,
            frequency_penalty=req.frequency_penalty,
            presence_penalty=req.presence_penalty,
            min_tokens=req.min_tokens,
            ignore_eos=req.ignore_eos,
            logprobs=(req.top_logprobs if req.top_logprobs is not None else 0)
            if req.logprobs else None,
        )
        if images:
            bi.images = images
            bi.kv_salt = image_kv_salt(bi.lora_id, images)
        if req.ext.get("no_spec"):
            # per-request speculative-decoding opt-out — also how the
            # frontend's brownout level >= 3 sheds spec's extra programs
            bi.no_spec = True
        annotations = self._annotations(req.ext, prompt, token_ids)
        bi.annotations = annotations
        return PreprocessedRequest(bi, prompt, annotations)

    def _encode_with_images(self, prompt: str, n_images: int) -> List[int]:
        """Segmented tokenization around image sentinels: text segments
        encode normally; each sentinel becomes [boi] + mm_tokens x
        [image_token_id] + [eoi] from the card's model config."""
        mc = self.card.model_config or {}
        # hub Gemma3 configs spell these *_index (image_token_index,
        # boi/eoi_token_index); newer transformers re-exports *_id — accept
        # both, or every real image request is rejected below
        img_id = mc.get("image_token_id", mc.get("image_token_index"))
        if img_id is None:
            raise ProtocolError(
                "this model takes no image input (no image_token_id in "
                "its config)")
        mm_tokens = int(mc.get("mm_tokens_per_image", 256))
        boi = mc.get("boi_token_id", mc.get("boi_token_index"))
        eoi = mc.get("eoi_token_id", mc.get("eoi_token_index"))
        ids: List[int] = []
        pieces = _IMG_SPLIT.split(prompt)
        # split() yields [text, idx, text, idx, ..., text]
        for i, piece in enumerate(pieces):
            if i % 2 == 0:
                if piece:
                    ids.extend(self.tokenizer.encode(piece))
            else:
                if int(piece) >= n_images:
                    raise ProtocolError("image sentinel out of range")
                if boi is not None:
                    ids.append(int(boi))
                ids.extend([int(img_id)] * mm_tokens)
                if eoi is not None:
                    ids.append(int(eoi))
        return ids

    def preprocess_completion(self, req: CompletionRequest) -> PreprocessedRequest:
        prompt: Optional[str]
        raw_prompt = req.prompt
        if (isinstance(raw_prompt, list) and len(raw_prompt) == 1
                and isinstance(raw_prompt[0], str)):
            raw_prompt = raw_prompt[0]  # single-element batch == plain string
        if isinstance(raw_prompt, list) and not raw_prompt:
            raise ProtocolError("prompt must not be empty")
        if isinstance(raw_prompt, list) and all(isinstance(x, str) for x in raw_prompt):
            raise ProtocolError(
                "multi-prompt batch completions are not supported yet; send one "
                "request per prompt")
        if isinstance(raw_prompt, str):
            prompt = raw_prompt
            token_ids = self.tokenizer.encode(prompt)
        elif isinstance(raw_prompt, list) and all(isinstance(x, int) for x in raw_prompt):
            prompt = None
            token_ids = list(raw_prompt)
            if any(t < 0 or t >= 1 << 32 for t in token_ids):
                raise ProtocolError("token ids must be in [0, 2^32)")
        else:
            raise ProtocolError("prompt must be a string or a list of token ids")
        bi = self._assemble(
            token_ids,
            model=req.model,
            max_tokens=req.max_tokens,
            stop=req.stop,
            temperature=req.temperature,
            top_p=req.top_p,
            top_k=req.top_k,
            n=req.n,
            seed=req.seed,
            frequency_penalty=req.frequency_penalty,
            presence_penalty=req.presence_penalty,
            min_tokens=req.min_tokens,
            ignore_eos=req.ignore_eos,
            logprobs=req.logprobs,
            echo=req.echo,
        )
        if req.ext.get("no_spec"):
            bi.no_spec = True   # see preprocess_chat
        annotations = self._annotations(req.ext, prompt, token_ids)
        bi.annotations = annotations
        return PreprocessedRequest(bi, prompt, annotations)

    # ------------------------------------------------------------------
    def _assemble(self, token_ids: List[int], *, model: str,
                  max_tokens: Optional[int], stop: List[str],
                  temperature: Optional[float], top_p: Optional[float],
                  top_k: Optional[int], n: int, seed: Optional[int],
                  frequency_penalty: Optional[float] = None,
                  presence_penalty: Optional[float] = None,
                  min_tokens: Optional[int] = None, ignore_eos: bool = False,
                  logprobs: Optional[int] = None, echo: bool = False) -> BackendInput:
        ctx = self.card.context_length
        if len(token_ids) >= ctx:
            raise ProtocolError(
                f"prompt of {len(token_ids)} tokens exceeds the model context "
                f"length of {ctx}"
            )
        budget = ctx - len(token_ids)
        mt = min(max_tokens, budget) if max_tokens is not None else budget
        if max_tokens is not None and max_tokens < 1:
            raise ProtocolError("max_tokens must be >= 1")
        return BackendInput(
            token_ids=token_ids,
            sampling=SamplingOptions(
                temperature=temperature,
                top_p=top_p,
                top_k=top_k,
                frequency_penalty=frequency_penalty,
                presence_penalty=presence_penalty,
                seed=seed,
                n=n,
            ),
            stop=StopConditions(
                max_tokens=mt,
                stop=list(stop),
                min_tokens=min_tokens,
                ignore_eos=ignore_eos,
            ),
            output=OutputOptions(logprobs=logprobs, echo=echo),
            eos_token_ids=list(self.card.eos_token_ids),
            model=model,
            mdc_sum=self.card.mdc_sum,
        )

    @staticmethod
    def _annotations(ext: Dict[str, Any], prompt: Optional[str],
                     token_ids: List[int]) -> Dict[str, Any]:
        want = set(ext.get("annotations", []) or [])
        out: Dict[str, Any] = {}
        if "formatted_prompt" in want and prompt is not None:
            out["formatted_prompt"] = prompt
        if "token_ids" in want:
            out["token_ids"] = token_ids
        return out
