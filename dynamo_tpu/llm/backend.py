"""Backend postprocessor: token stream -> text stream.

Wraps a core (token-in/token-out) engine and performs incremental
detokenization, hidden-stop-token jailing, stop-sequence truncation and
length/EOS finishing — producing clean text deltas for the delta generators.

Reference capability: lib/llm/src/backend.rs:63-479 (Backend.generate, Decoder
step loop, stop jail).
"""

from __future__ import annotations

import contextlib
from typing import AsyncIterator

from ..runtime.engine import AsyncEngine, Context, EngineError
from .protocols.common import BackendInput, EngineOutput, FinishReason
from .tokenizer import DecodeStream, StopSequenceDecoder, Tokenizer


class Backend(AsyncEngine[BackendInput, EngineOutput]):
    """Postprocessing stage layered over a core engine.

    The inner engine streams ``EngineOutput`` with ``token_ids`` only; this
    stage fills in ``text`` and rewrites ``finish_reason`` when a client stop
    sequence fires before the engine's own finish.
    """

    def __init__(self, engine: AsyncEngine[BackendInput, EngineOutput],
                 tokenizer: Tokenizer):
        self.engine = engine
        self.tokenizer = tokenizer

    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        decode = DecodeStream(self.tokenizer, request.token_ids)
        # min_tokens suppresses stop-sequence scanning entirely until the
        # minimum is generated (a stop string spanning the boundary is
        # deliberately not matched, mirroring common engine semantics).
        stops = StopSequenceDecoder(request.stop.stop)
        emitted = 0
        min_tokens = request.stop.min_tokens or 0

        # aclosing: an early return (stop sequence, client stop) must close
        # the core engine's generator NOW — its finally blocks release
        # engine-side resources (slot cancel bookkeeping, user-engine
        # cleanup) and deferring them to GC leaves those held
        async with contextlib.aclosing(
                self.engine.generate(request, context)) as stream:
            async for out in stream:
                if out.finish_reason is FinishReason.ERROR:
                    # surface the cause as a typed error: over the wire it
                    # becomes an error frame, at the HTTP edge an SSE error
                    # event — never a silently terminated stream. The
                    # engine's code/stage/reason ride along so an
                    # over-length rejection maps to a 400 body naming the
                    # limit, not a generic 500
                    raise EngineError(out.error or "engine error",
                                      out.error_code or 500,
                                      stage=out.error_stage,
                                      reason=out.error_reason)
                text_parts = []
                finish = out.finish_reason
                for tid in out.token_ids:
                    emitted += 1
                    piece = decode.step(tid)
                    if not piece:
                        continue
                    if emitted <= min_tokens:
                        text_parts.append(piece)
                        continue
                    visible, hit_stop = stops.feed(piece)
                    if visible:
                        text_parts.append(visible)
                    if hit_stop:
                        finish = FinishReason.STOP
                        break
                if finish is not None and finish is not FinishReason.STOP:
                    # engine finished without a client stop: flush held-back text
                    tail = decode.flush()
                    if tail:
                        visible, hit_stop = stops.feed(tail)
                        if visible:
                            text_parts.append(visible)
                        if hit_stop:
                            finish = FinishReason.STOP
                    if finish is not FinishReason.STOP:
                        jail = stops.flush()
                        if jail:
                            text_parts.append(jail)
                text = "".join(text_parts)
                # always yield (even with empty text) so downstream usage
                # accounting sees every generated token id
                if text or finish is not None or out.token_ids:
                    yield EngineOutput(
                        token_ids=out.token_ids,
                        text=text,
                        cum_log_prob=out.cum_log_prob,
                        logprobs=out.logprobs,
                        finish_reason=finish,
                        kv_prefix_hit_tokens=out.kv_prefix_hit_tokens,
                        index=out.index,
                    )
                if finish is not None:
                    if finish is FinishReason.STOP:
                        context.stop_generating()
                    return
        # stream ended without an explicit finish (e.g. cancelled upstream)
        tail = decode.flush() + stops.flush()
        yield EngineOutput(token_ids=[], text=tail,
                          finish_reason=FinishReason.CANCELLED)
