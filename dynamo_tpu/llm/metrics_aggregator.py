"""Cluster metrics aggregator: worker capacity + KV hit rate -> Prometheus.

Subscribes the namespace ``kv-hit-rate`` event plane (emitted by the KV
router per routed request) and periodically scrapes every worker's
ForwardPassMetrics snapshot from the ``metrics/`` store prefix, exposing the
reference's cluster gauges:

- ``llm_kv_blocks_active`` / ``llm_kv_blocks_total``      (per worker)
- ``llm_requests_active_slots`` / ``llm_requests_total_slots`` (per worker)
- ``llm_requests_waiting``                                (per worker)
- ``llm_load_avg`` / ``llm_load_std``                     (per component)
- ``llm_kv_hit_rate_percent``                             (cumulative)

Reference capability: components/metrics/src/main.rs:115-241 (the metrics
binary's event subscription + service scrape + prometheus export) and
lib/llm/src/kv_router/scoring.rs (load_avg/load_std over active slots).
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
from typing import Dict, List, Optional, Sequence

from ..runtime.component import DistributedRuntime
from ..utils.prometheus import Registry, render_states
from .kv_router.protocols import ForwardPassMetrics, KVHitRateEvent

log = logging.getLogger("dynamo_tpu.metrics")

METRICS_PREFIX = "metrics/"
STAGE_PREFIX = "metrics_stage/"


def metrics_key(namespace: str, component: str, worker_id: int) -> str:
    """Store key a worker refreshes its ForwardPassMetrics under (lease-
    bound, so dead workers' snapshots vanish with their lease)."""
    return f"{METRICS_PREFIX}{namespace}/{component}/{worker_id:x}"


def stage_key(namespace: str, component: str, worker_id: int) -> str:
    """Store key a worker refreshes its per-stage latency histogram dump
    under (utils.prometheus.StageMetrics state; lease-bound like above)."""
    return f"{STAGE_PREFIX}{namespace}/{component}/{worker_id:x}"


async def publish_stage_metrics(store, namespace: str, component: str,
                                worker_id: int, lease: int,
                                extra_metrics: Optional[Dict] = None) -> None:
    """One refresh of this process's stage-histogram dump (workers call
    this from their metrics loop). ``extra_metrics`` merges additional
    registry ``state_dump()``s into the payload — the HTTP frontend ships
    its request counters (`dyn_http_*`) this way so availability SLOs can
    be evaluated cluster-wide."""
    from ..utils.prometheus import stage_metrics

    metrics = stage_metrics().registry.state_dump()
    if extra_metrics:
        metrics.update(extra_metrics)
    payload = json.dumps({
        "component": component,
        "metrics": metrics,
    }).encode()
    await store.put(stage_key(namespace, component, worker_id), payload,
                    lease=lease)


async def clear_worker_keys(store, namespace: str, component: str,
                            worker_id: int) -> None:
    """Drop a worker's published metric snapshots at deregistration.

    The keys are lease-bound, so a DEAD worker's snapshots vanish on their
    own — but a worker that exits while its runtime (and lease) live on
    (shared-runtime embedding, model remove/re-add) would otherwise keep
    exporting ghost occupancy/MFU until the process dies. Best-effort: a
    store mid-outage just leaves the lease TTL to do the same job later."""
    for key in (metrics_key(namespace, component, worker_id),
                stage_key(namespace, component, worker_id)):
        try:
            await store.delete(key)
        except Exception:  # noqa: BLE001 - cleanup must never mask exit
            log.debug("metrics key cleanup failed for %s", key)


async def fetch_worker_metrics(store, namespace: str, component: str
                               ) -> Dict[int, "ForwardPassMetrics"]:
    """One component's live ForwardPassMetrics snapshots, keyed by worker
    id — the aggregator's scrape unit, shared with the planner's signal
    collector (which reads the same prefix without a DistributedRuntime)."""
    prefix = f"{METRICS_PREFIX}{namespace}/{component}/"
    workers: Dict[int, ForwardPassMetrics] = {}
    for key, value in await store.get_prefix(prefix):
        try:
            wid = int(key.rsplit("/", 1)[1], 16)
            workers[wid] = ForwardPassMetrics.from_dict(
                json.loads(value.decode()))
        except Exception:
            log.warning("malformed metrics at %s", key)
    return workers


async def fetch_stage_states(store, namespace: Optional[str] = None,
                             exclude_worker: Optional[int] = None
                             ) -> List[tuple]:
    """All published stage dumps as ``(component, state_dump)`` pairs, ready
    for :func:`dynamo_tpu.utils.prometheus.render_states`.
    ``exclude_worker`` skips one publisher's dump — a frontend that both
    publishes and scrapes must not merge its own counters twice."""
    prefix = STAGE_PREFIX + (f"{namespace}/" if namespace else "")
    states: List[tuple] = []
    for key, value in await store.get_prefix(prefix):
        if exclude_worker is not None and key.rsplit("/", 1)[-1] == \
                f"{exclude_worker:x}":
            continue
        try:
            d = json.loads(value.decode())
            states.append((d.get("component")
                           or key[len(STAGE_PREFIX):].split("/")[1],
                           d["metrics"]))
        except Exception:
            log.warning("malformed stage metrics at %s", key)
    return states


class ClusterMetricsAggregator:
    """Aggregates per-worker snapshots and router hit-rate events."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 components: Sequence[str], scrape_interval: float = 1.0):
        self.drt = drt
        self.namespace = namespace
        self.components = list(components)
        self.scrape_interval = scrape_interval
        self._task: Optional[asyncio.Task] = None

        self.registry = Registry()
        g = self.registry.gauge
        self.g_kv_active = g("llm_kv_blocks_active",
                             "KV blocks in use on a worker",
                             ("component", "worker_id"))
        self.g_kv_total = g("llm_kv_blocks_total",
                            "KV block capacity of a worker",
                            ("component", "worker_id"))
        self.g_slots_active = g("llm_requests_active_slots",
                                "Active request slots on a worker",
                                ("component", "worker_id"))
        self.g_slots_total = g("llm_requests_total_slots",
                               "Total request slots of a worker",
                               ("component", "worker_id"))
        self.g_waiting = g("llm_requests_waiting",
                           "Requests queued on a worker",
                           ("component", "worker_id"))
        self.g_load_avg = g("llm_load_avg",
                            "Mean active slots across workers",
                            ("component",))
        self.g_load_std = g("llm_load_std",
                            "Stddev of active slots across workers",
                            ("component",))
        self.g_hit_rate = g("llm_kv_hit_rate_percent",
                            "Cumulative prefix-cache hit rate "
                            "(overlap blocks / isl blocks)", ())
        self._isl_blocks = 0
        self._overlap_blocks = 0
        # last scrape snapshot, for tests/introspection
        self.workers: Dict[str, Dict[int, ForwardPassMetrics]] = {}
        # last stage-histogram scrape: (component, state_dump) pairs folded
        # into render() via render_states
        self.stage_states: List[tuple] = []

    # ------------------------------------------------------------------
    async def start(self) -> "ClusterMetricsAggregator":
        ns = self.drt.namespace(self.namespace)

        async def on_hit_rate(payload: Dict) -> None:
            ev = KVHitRateEvent.from_dict(payload)
            self._isl_blocks += ev.isl_blocks
            self._overlap_blocks += ev.overlap_blocks
            if self._isl_blocks:
                self.g_hit_rate.set(
                    value=100.0 * self._overlap_blocks / self._isl_blocks)

        await ns.subscribe("kv-hit-rate", on_hit_rate)
        self._task = asyncio.create_task(self._scrape_loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()

    # ------------------------------------------------------------------
    async def scrape_once(self) -> None:
        for comp in self.components:
            workers = await fetch_worker_metrics(self.drt.store,
                                                 self.namespace, comp)
            self.workers[comp] = workers
            self._export(comp, workers)
        self.stage_states = await fetch_stage_states(self.drt.store,
                                                     self.namespace)

    def _export(self, comp: str,
                workers: Dict[int, ForwardPassMetrics]) -> None:
        for g in (self.g_kv_active, self.g_kv_total, self.g_slots_active,
                  self.g_slots_total, self.g_waiting):
            g.clear_label(0, comp)
        loads: List[float] = []
        for wid, m in workers.items():
            w = f"{wid:x}"
            self.g_kv_active.set(comp, w, value=m.kv_active_blocks)
            self.g_kv_total.set(comp, w, value=m.kv_total_blocks)
            self.g_slots_active.set(comp, w, value=m.request_active_slots)
            self.g_slots_total.set(comp, w, value=m.request_total_slots)
            self.g_waiting.set(comp, w, value=m.num_requests_waiting)
            loads.append(m.request_active_slots)
        if loads:
            avg = sum(loads) / len(loads)
            var = sum((x - avg) ** 2 for x in loads) / len(loads)
            self.g_load_avg.set(comp, value=avg)
            self.g_load_std.set(comp, value=math.sqrt(var))
        else:
            # no workers left: the series must vanish, not freeze
            self.g_load_avg.clear_label(0, comp)
            self.g_load_std.clear_label(0, comp)

    async def _scrape_loop(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("cluster metrics scrape failed")
            await asyncio.sleep(self.scrape_interval)

    # ------------------------------------------------------------------
    def render(self) -> str:
        return self.registry.render() + render_states(self.stage_states)
