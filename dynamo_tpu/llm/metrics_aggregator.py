"""Cluster metrics aggregator: worker capacity + KV hit rate -> Prometheus.

Subscribes the namespace ``kv-hit-rate`` event plane (emitted by the KV
router per routed request) and periodically scrapes every worker's
ForwardPassMetrics snapshot from the ``metrics/`` store prefix, exposing the
reference's cluster gauges:

- ``llm_kv_blocks_active`` / ``llm_kv_blocks_total``      (per worker)
- ``llm_requests_active_slots`` / ``llm_requests_total_slots`` (per worker)
- ``llm_requests_waiting``                                (per worker)
- ``llm_load_avg`` / ``llm_load_std``                     (per component)
- ``llm_kv_hit_rate_percent``                             (cumulative)

Reference capability: components/metrics/src/main.rs:115-241 (the metrics
binary's event subscription + service scrape + prometheus export) and
lib/llm/src/kv_router/scoring.rs (load_avg/load_std over active slots).
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import re
import time
from typing import Dict, List, Optional, Sequence

from ..runtime.component import DistributedRuntime
from ..utils.prometheus import Registry, diff_states, render_states
from .kv_router.protocols import ForwardPassMetrics, KVHitRateEvent

log = logging.getLogger("dynamo_tpu.metrics")

METRICS_PREFIX = "metrics/"
STAGE_PREFIX = "metrics_stage/"
#: the store server's own telemetry dump (runtime/store_server.py writes
#: it into its KV under the ``metrics-store`` keyspace family); fetched
#: alongside every namespace's worker dumps so the store shows up on the
#: same merge path as any component
STORE_STAGE_PREFIX = "metrics_stage/_store/"

#: publisher self-accounting excluded from delta change-detection (its
#: own counters change on every push — including them would turn every
#: idle interval into a delta); full snapshots still carry them
_SELF_METRICS = ("dyn_metrics_pushes_total",)


def metrics_key(namespace: str, component: str, worker_id: int) -> str:
    """Store key a worker refreshes its ForwardPassMetrics under (lease-
    bound, so dead workers' snapshots vanish with their lease)."""
    return f"{METRICS_PREFIX}{namespace}/{component}/{worker_id:x}"


def stage_slices() -> int:
    """``DYN_STAGE_SLICES``: worker-stable sub-prefix slices of the
    stage keyspace (``worker_id mod slices``). Regional aggregators
    rendezvous-own SLICES and read only theirs per tick — a region tick
    is O(owned slice), not O(fleet). Must agree fleet-wide (publishers
    and aggregators hash with the same modulus)."""
    from ..utils.knobs import env_float

    return max(1, int(env_float("DYN_STAGE_SLICES", 16, minimum=1.0)))


def stage_slice_of(worker_id: int) -> int:
    return worker_id % stage_slices()


def stage_slice_prefix(namespace: str, slice_idx: int) -> str:
    """Every stage dump of one slice — the aggregator's per-tick read
    unit."""
    return f"{STAGE_PREFIX}{namespace}/s{slice_idx:02x}/"


def stage_key(namespace: str, component: str, worker_id: int) -> str:
    """Store key a worker refreshes its per-stage latency histogram dump
    under (utils.prometheus.StageMetrics state; lease-bound like above).
    The ``s{slice:02x}`` segment is a pure function of the worker id, so
    the key stays stable across aggregator membership churn while
    letting an owner scan just its slices."""
    return (f"{STAGE_PREFIX}{namespace}/s{stage_slice_of(worker_id):02x}/"
            f"{component}/{worker_id:x}")


_SLICE_SEG = re.compile(r"^s[0-9a-f]{2,}$")   # :02x pads, never truncates


def split_stage_key(rest: str) -> tuple:
    """``(component, widhex)`` from the post-``{ns}/`` remainder of a
    stage BASE key. Tolerates the pre-slice legacy layout (no ``sNN``
    segment) so FLAT readers and the ``_store`` dump keep parsing —
    note the regional aggregator's owned-slice scan reads only sliced
    keys by construction: the slice layout (like ``DYN_STAGE_SLICES``
    itself) is a fleet-wide flag day, publishers and aggregators
    upgrade together."""
    parts = rest.split("/")
    if len(parts) >= 3 and _SLICE_SEG.match(parts[0]):
        return parts[1], parts[2]
    return parts[0], (parts[1] if len(parts) > 1 else "")


def stage_delta_key(namespace: str, component: str, worker_id: int) -> str:
    """Sibling key carrying the coalesced since-last-full delta batch
    (see :class:`StagePublisher`); lease-bound like the full snapshot."""
    return stage_key(namespace, component, worker_id) + "/delta"


def stage_base_key(key: str) -> str:
    """The full-snapshot key a stage-KV key belongs to (its own key, or
    the ``/delta``-stripped sibling)."""
    return key[:-len("/delta")] if key.endswith("/delta") else key


def merge_stage_items(items) -> Dict[str, tuple]:
    """Group raw stage-KV ``(key, value)`` pairs by publisher and apply
    the delta overlay: ``{base_key: (full_doc, merged_metrics)}``.

    THE one implementation of the full+delta read protocol (see
    :class:`StagePublisher`) — :func:`fetch_stage_states` and the
    planner's ``SignalCollector`` both read through it. A delta overlays
    its full iff its ``base_seq`` matches the full's ``seq`` (stale
    deltas from before a newer full are dropped, never mis-merged);
    legacy seq-less fulls pass through unchanged; malformed payloads are
    logged and skipped."""
    fulls: Dict[str, Dict] = {}
    deltas: Dict[str, Dict] = {}
    for key, value in items:
        try:
            d = json.loads(value.decode())
        except Exception:
            log.warning("malformed stage metrics at %s", key)
            continue
        (deltas if key.endswith("/delta") else fulls)[
            stage_base_key(key)] = d
    out: Dict[str, tuple] = {}
    for key, d in fulls.items():
        metrics = d.get("metrics") or {}
        delta = deltas.get(key)
        if delta and d.get("seq") is not None \
                and delta.get("base_seq") == d.get("seq"):
            metrics = {**metrics, **(delta.get("metrics") or {})}
        out[key] = (d, metrics)
    return out


class StagePublisher:
    """Delta-batched stage-metrics publishing: O(1) store writes per
    worker per interval, O(changed) bytes instead of O(metrics).

    Protocol (stateless-reader safe):

    - every ``full_every``-th push writes the **full** registry image to
      ``stage_key`` as ``{"component", "seq", "metrics"}``;
    - pushes in between write ONE **cumulative delta** — every metric
      whose state changed since the last full — to ``stage_delta_key`` as
      ``{"component", "base_seq", "metrics"}``. Cumulative (not chained)
      means a reader needs only the (full, delta) pair it can always
      fetch in one ``get_prefix``: overlay delta iff ``base_seq`` matches
      the full's ``seq`` (a stale delta from before a newer full is
      ignored, never mis-merged);
    - an interval where nothing changed writes **nothing**.

    Pushes are additionally rate-limited to one store write per
    ``DYN_METRICS_PUSH_INTERVAL`` seconds (0 = every call), so a worker
    with a fast metrics loop still costs the store one write per
    interval. Outcomes are counted in ``dyn_metrics_pushes_total{kind}``.
    """

    def __init__(self, store, namespace: str, component: str,
                 worker_id: int, lease: int,
                 dump_fn=None, push_interval: Optional[float] = None,
                 full_every: Optional[int] = None):
        self.store = store
        self.namespace = namespace
        self.component = component
        self.worker_id = worker_id
        self.lease = lease
        self._dump_fn = dump_fn
        # the publishing identity IS the flow ledger's local endpoint:
        # a worker's host/dev link labels adopt its hex id the moment it
        # starts publishing (before that: pid)
        from ..obs.flows import set_local_worker

        set_local_worker(worker_id)
        if push_interval is None:
            try:
                push_interval = float(
                    os.environ.get("DYN_METRICS_PUSH_INTERVAL", "0") or 0)
            except ValueError:
                push_interval = 0.0
        self.push_interval = max(push_interval, 0.0)
        if full_every is None:
            try:
                full_every = int(
                    os.environ.get("DYN_METRICS_FULL_EVERY", "10") or 10)
            except ValueError:
                full_every = 10
        self.full_every = max(full_every, 1)
        self._last_full: Optional[Dict[str, Dict]] = None
        self._last_delta: Optional[Dict[str, Dict]] = None
        self._seq = 0             # seq of the last full snapshot
        self._pushes_since_full = 0
        self._last_push_t = 0.0

    def _dump(self) -> Dict[str, Dict]:
        if self._dump_fn is not None:
            return self._dump_fn()
        from ..utils.prometheus import stage_metrics

        return stage_metrics().registry.state_dump()

    async def publish(self, extra_metrics: Optional[Dict] = None,
                      force_full: bool = False) -> str:
        """One publish beat; returns what happened: ``"full"``,
        ``"delta"``, ``"skipped"`` (no change — no write) or
        ``"throttled"`` (inside the push interval — no work done)."""
        from ..utils.prometheus import stage_metrics

        now = time.monotonic()
        if self._last_full is not None and self.push_interval > 0 \
                and now - self._last_push_t < self.push_interval:
            return "throttled"
        cur = self._dump()
        if extra_metrics:
            cur = {**cur, **extra_metrics}
        if self._last_full is None or force_full \
                or self._pushes_since_full >= self.full_every - 1:
            self._seq += 1
            payload = json.dumps({"component": self.component,
                                  "seq": self._seq,
                                  "metrics": cur}).encode()
            await self.store.put(
                stage_key(self.namespace, self.component, self.worker_id),
                payload, lease=self.lease)
            self._last_full = cur
            self._last_delta = None
            self._pushes_since_full = 0
            self._last_push_t = now
            stage_metrics().metrics_pushes.inc("full")
            return "full"
        delta = diff_states(self._last_full, cur, ignore=_SELF_METRICS)
        # skip only when the delta key's content would be unchanged: an
        # EMPTY delta after a non-empty one must still be written, or a
        # metric that reverted to its full-snapshot value (e.g. a queue
        # depth back to 0) would keep reading as the stale delta value
        if delta == (self._last_delta or {}):
            stage_metrics().metrics_pushes.inc("skipped")
            return "skipped"
        # only WRITES advance the full rollover — an idle worker must
        # stay genuinely silent, not re-publish an unchanged full every
        # full_every beats
        self._pushes_since_full += 1
        payload = json.dumps({"component": self.component,
                              "base_seq": self._seq,
                              "metrics": delta}).encode()
        await self.store.put(
            stage_delta_key(self.namespace, self.component,
                            self.worker_id),
            payload, lease=self.lease)
        self._last_delta = delta
        self._last_push_t = now
        stage_metrics().metrics_pushes.inc("delta")
        return "delta"


async def publish_stage_metrics(store, namespace: str, component: str,
                                worker_id: int, lease: int,
                                extra_metrics: Optional[Dict] = None) -> None:
    """One full-snapshot refresh of this process's stage-histogram dump.
    Long-running workers should hold a :class:`StagePublisher` instead
    (delta batching); this one-shot form is kept for callers that publish
    once or rarely. ``extra_metrics`` merges additional registry
    ``state_dump()``s into the payload — the HTTP frontend ships its
    request counters (`dyn_http_*`) this way so availability SLOs can be
    evaluated cluster-wide."""
    from ..utils.prometheus import stage_metrics

    metrics = stage_metrics().registry.state_dump()
    if extra_metrics:
        metrics.update(extra_metrics)
    payload = json.dumps({
        "component": component,
        "metrics": metrics,
    }).encode()
    await store.put(stage_key(namespace, component, worker_id), payload,
                    lease=lease)


async def clear_worker_keys(store, namespace: str, component: str,
                            worker_id: int) -> None:
    """Drop a worker's published metric snapshots at deregistration.

    The keys are lease-bound, so a DEAD worker's snapshots vanish on their
    own — but a worker that exits while its runtime (and lease) live on
    (shared-runtime embedding, model remove/re-add) would otherwise keep
    exporting ghost occupancy/MFU until the process dies. Best-effort: a
    store mid-outage just leaves the lease TTL to do the same job later."""
    for key in (metrics_key(namespace, component, worker_id),
                stage_key(namespace, component, worker_id),
                stage_delta_key(namespace, component, worker_id)):
        try:
            await store.delete(key)
        except Exception:  # noqa: BLE001 - cleanup must never mask exit
            log.debug("metrics key cleanup failed for %s", key)


async def fetch_worker_metrics(store, namespace: str, component: str
                               ) -> Dict[int, "ForwardPassMetrics"]:
    """One component's live ForwardPassMetrics snapshots, keyed by worker
    id — the aggregator's scrape unit, shared with the planner's signal
    collector (which reads the same prefix without a DistributedRuntime)."""
    prefix = f"{METRICS_PREFIX}{namespace}/{component}/"
    workers: Dict[int, ForwardPassMetrics] = {}
    for key, value in await store.get_prefix(prefix):
        try:
            wid = int(key.rsplit("/", 1)[1], 16)
            workers[wid] = ForwardPassMetrics.from_dict(
                json.loads(value.decode()))
        except Exception:
            log.warning("malformed metrics at %s", key)
    return workers


async def _store_dump_items(store) -> List[tuple]:
    """The store server(s)' self-telemetry items. On a sharded store
    every shard publishes its own dump under the SAME key in its own
    KV — read each shard's copy and suffix the key with the shard name
    so the per-publisher grouping in :func:`merge_stage_items` keeps
    them distinct (a routed read would surface only the shard that owns
    the ``metrics-store`` family and silently hide the rest)."""
    if hasattr(store, "get_prefix_on"):
        items: List[tuple] = []
        for i, name in enumerate(store.shard_names):
            try:
                for key, value in await store.get_prefix_on(
                        i, STORE_STAGE_PREFIX):
                    items.append((f"{key}#{name}", value))
            except Exception:  # noqa: BLE001 - a dead shard's dump is
                # simply absent; its families already raise typed errors
                log.debug("store dump unreadable on shard %s", name)
        return items
    return list(await store.get_prefix(STORE_STAGE_PREFIX))


async def fetch_stage_states_ex(store, namespace: Optional[str] = None,
                                exclude_worker: Optional[int] = None
                                ) -> tuple:
    """``(states, region_read)``: the stage states plus the
    :class:`~dynamo_tpu.runtime.scale.regions.RegionStates` that served
    them (None on the flat path) — dyntop renders the region metadata,
    everyone else uses :func:`fetch_stage_states`.

    Delta-aware: a worker's ``.../delta`` batch (see
    :class:`StagePublisher`) is overlaid onto its full snapshot when the
    delta's ``base_seq`` matches the snapshot's ``seq`` — stale deltas
    (from before a newer full) are dropped, and legacy seq-less full
    dumps pass through unchanged. A namespace-scoped fetch also includes
    the store server's own telemetry dump (``metrics_stage/_store/``),
    so the coordination plane itself renders on every merge surface.
    ``exclude_worker`` skips one publisher's dump — a frontend that both
    publishes and scrapes must not merge its own counters twice.

    **Region-aware**: when regional aggregators are live for the
    namespace (runtime/scale/regions.py) the states come from their R
    pre-merged region records instead of the N per-worker dumps — same
    ``(component, state_dump)`` shape, O(regions) read+merge cost. The
    flat scrape remains the fallback (no aggregator, stale records) and
    the only path for ``exclude_worker`` reads: a region record is
    already merged, one publisher cannot be subtracted from it."""
    if namespace and exclude_worker is None:
        from ..runtime.scale.regions import fetch_region_states

        regional = await fetch_region_states(store, namespace)
        if regional is not None:
            states = list(regional.states)
            for _key, (doc, metrics) in merge_stage_items(
                    await _store_dump_items(store)).items():
                states.append((doc.get("component") or "store", metrics))
            return states, regional
    prefix = STAGE_PREFIX + (f"{namespace}/" if namespace else "")
    items = list(await store.get_prefix(prefix))
    if namespace:
        items.extend(await _store_dump_items(store))
    if exclude_worker is not None:
        items = [(k, v) for k, v in items
                 if stage_base_key(k).rsplit("/", 1)[-1]
                 != f"{exclude_worker:x}"]
    return [(doc.get("component")
             or split_stage_key(
                 key[len(STAGE_PREFIX):].split("/", 1)[-1])[0],
             metrics)
            for key, (doc, metrics) in merge_stage_items(items).items()], \
        None


async def fetch_stage_states(store, namespace: Optional[str] = None,
                             exclude_worker: Optional[int] = None
                             ) -> List[tuple]:
    """All published stage dumps as ``(component, state_dump)`` pairs
    (see :func:`fetch_stage_states_ex` for the full contract — this is
    the states-only view every merge surface reads)."""
    states, _regional = await fetch_stage_states_ex(store, namespace,
                                                    exclude_worker)
    return states


class ClusterMetricsAggregator:
    """Aggregates per-worker snapshots and router hit-rate events."""

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 components: Sequence[str], scrape_interval: float = 1.0):
        self.drt = drt
        self.namespace = namespace
        self.components = list(components)
        self.scrape_interval = scrape_interval
        self._task: Optional[asyncio.Task] = None

        self.registry = Registry()
        g = self.registry.gauge
        self.g_kv_active = g("llm_kv_blocks_active",
                             "KV blocks in use on a worker",
                             ("component", "worker_id"))
        self.g_kv_total = g("llm_kv_blocks_total",
                            "KV block capacity of a worker",
                            ("component", "worker_id"))
        self.g_slots_active = g("llm_requests_active_slots",
                                "Active request slots on a worker",
                                ("component", "worker_id"))
        self.g_slots_total = g("llm_requests_total_slots",
                               "Total request slots of a worker",
                               ("component", "worker_id"))
        self.g_waiting = g("llm_requests_waiting",
                           "Requests queued on a worker",
                           ("component", "worker_id"))
        self.g_load_avg = g("llm_load_avg",
                            "Mean active slots across workers",
                            ("component",))
        self.g_load_std = g("llm_load_std",
                            "Stddev of active slots across workers",
                            ("component",))
        self.g_hit_rate = g("llm_kv_hit_rate_percent",
                            "Cumulative prefix-cache hit rate "
                            "(overlap blocks / isl blocks)", ())
        self._isl_blocks = 0
        self._overlap_blocks = 0
        # last scrape snapshot, for tests/introspection
        self.workers: Dict[str, Dict[int, ForwardPassMetrics]] = {}
        # last stage-histogram scrape: (component, state_dump) pairs folded
        # into render() via render_states
        self.stage_states: List[tuple] = []

    # ------------------------------------------------------------------
    async def start(self) -> "ClusterMetricsAggregator":
        ns = self.drt.namespace(self.namespace)

        async def on_hit_rate(payload: Dict) -> None:
            ev = KVHitRateEvent.from_dict(payload)
            self._isl_blocks += ev.isl_blocks
            self._overlap_blocks += ev.overlap_blocks
            if self._isl_blocks:
                self.g_hit_rate.set(
                    value=100.0 * self._overlap_blocks / self._isl_blocks)

        await ns.subscribe("kv-hit-rate", on_hit_rate)
        self._task = asyncio.create_task(self._scrape_loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()

    # ------------------------------------------------------------------
    async def scrape_once(self) -> None:
        for comp in self.components:
            workers = await fetch_worker_metrics(self.drt.store,
                                                 self.namespace, comp)
            self.workers[comp] = workers
            self._export(comp, workers)
        self.stage_states = await fetch_stage_states(self.drt.store,
                                                     self.namespace)

    def _export(self, comp: str,
                workers: Dict[int, ForwardPassMetrics]) -> None:
        for g in (self.g_kv_active, self.g_kv_total, self.g_slots_active,
                  self.g_slots_total, self.g_waiting):
            g.clear_label(0, comp)
        loads: List[float] = []
        for wid, m in workers.items():
            w = f"{wid:x}"
            self.g_kv_active.set(comp, w, value=m.kv_active_blocks)
            self.g_kv_total.set(comp, w, value=m.kv_total_blocks)
            self.g_slots_active.set(comp, w, value=m.request_active_slots)
            self.g_slots_total.set(comp, w, value=m.request_total_slots)
            self.g_waiting.set(comp, w, value=m.num_requests_waiting)
            loads.append(m.request_active_slots)
        if loads:
            avg = sum(loads) / len(loads)
            var = sum((x - avg) ** 2 for x in loads) / len(loads)
            self.g_load_avg.set(comp, value=avg)
            self.g_load_std.set(comp, value=math.sqrt(var))
        else:
            # no workers left: the series must vanish, not freeze
            self.g_load_avg.clear_label(0, comp)
            self.g_load_std.clear_label(0, comp)

    async def _scrape_loop(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("cluster metrics scrape failed")
            await asyncio.sleep(self.scrape_interval)

    # ------------------------------------------------------------------
    def render(self) -> str:
        return self.registry.render() + render_states(self.stage_states)
