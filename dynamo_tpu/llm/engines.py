"""Test/fixture engines: echo backends that need no model at all.

``EchoCoreEngine`` is a token-level core engine (BackendInput -> EngineOutput)
that replays the prompt tokens at a fixed rate; ``echo_full`` operates at the
OpenAI level. These are first-class backends — every input mode and the whole
pipeline can run against them with no TPU and no weights, exactly how the
reference uses its echo engines as the main fake backend
(reference: lib/llm/src/engines.rs:64-178, env DYN_TOKEN_ECHO_DELAY_MS).
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator

from ..runtime.engine import AsyncEngine, Context
from .protocols.common import BackendInput, EngineOutput, FinishReason

ECHO_DELAY_ENV = "DYN_TOKEN_ECHO_DELAY_MS"


def _delay_s() -> float:
    return float(os.environ.get(ECHO_DELAY_ENV, "10")) / 1000.0


class EchoCoreEngine(AsyncEngine[BackendInput, EngineOutput]):
    """Echoes the prompt's token ids back one at a time (rate-limited)."""

    def __init__(self, delay_s: float | None = None):
        self._delay = delay_s

    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        delay = self._delay if self._delay is not None else _delay_s()
        # mid-stream resume (llm/resume.py): the request's tail carries the
        # resume_pos tokens a dead instance already emitted. The echo
        # source is the ORIGINAL prompt (strip that tail), and emission
        # continues from position resume_pos — never re-emitting — so a
        # resumed echo stream is byte-identical to an unkilled one.
        pos = int(request.resume_pos or 0)
        src = request.token_ids[:len(request.token_ids) - pos] if pos \
            else request.token_ids
        budget = request.stop.max_tokens
        if budget is None:
            budget = len(src)
        n = min(pos + budget, len(src))
        if n <= pos:
            yield EngineOutput(token_ids=[], finish_reason=FinishReason.LENGTH)
            return
        for i in range(pos, n):
            if context.is_stopped:
                yield EngineOutput(token_ids=[], finish_reason=FinishReason.CANCELLED)
                return
            if delay:
                await asyncio.sleep(delay)
            last = i == n - 1
            yield EngineOutput(
                token_ids=[src[i]],
                finish_reason=FinishReason.LENGTH if last else None,
            )


class EchoFullEngine(AsyncEngine):
    """OpenAI-level echo: streams the last user message back as chunks."""

    def __init__(self, delay_s: float | None = None, chunk_chars: int = 4):
        self._delay = delay_s
        self._chunk = chunk_chars

    async def generate(self, request, context: Context):
        delay = self._delay if self._delay is not None else _delay_s()
        if hasattr(request, "messages"):
            text = str(request.messages[-1].get("content", ""))
        else:
            text = request.prompt if isinstance(request.prompt, str) else ""
        for i in range(0, len(text), self._chunk):
            if context.is_stopped:
                return
            if delay:
                await asyncio.sleep(delay)
            yield text[i : i + self._chunk]
