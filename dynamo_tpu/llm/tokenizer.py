"""Tokenizer abstraction: encode/decode + streaming incremental detokenization.

Backends:
- :class:`HfTokenizer` — wraps a HuggingFace ``tokenizers``/``transformers``
  tokenizer loaded from a local directory (tokenizer.json / tokenizer_config).
- :class:`ByteTokenizer` — self-contained byte-level tokenizer (vocab = 256
  bytes + specials). Lets the whole stack run hermetically with no downloaded
  artifacts; also the fixture tokenizer for tests.

Streaming pieces:
- :class:`DecodeStream` — incremental detokenization that never emits a torn
  multi-byte codepoint (prefix/read-offset algorithm).
- :class:`StopSequenceDecoder` — the "jail": holds back text that might be the
  start of a stop sequence until disambiguated, truncates at the match.

Reference capability: lib/llm/src/tokenizers.rs:39-236 (Encoder/Decoder,
DecodeStream, StopSequenceDecoder) and backend.rs stop handling.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Protocol, Sequence, Tuple


class Tokenizer(Protocol):
    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    @property
    def eos_token_ids(self) -> List[int]: ...
    @property
    def bos_token_id(self) -> Optional[int]: ...
    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """Byte-level tokenizer: token i (< 256) is byte i; then BOS/EOS/PAD."""

    BOS = 256
    EOS = 257
    PAD = 258

    def __init__(self, add_bos: bool = False):
        self.add_bos = add_bos

    def encode(self, text: str) -> List[int]:
        ids = list(text.encode("utf-8"))
        if self.add_bos:
            ids = [self.BOS] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    @property
    def eos_token_ids(self) -> List[int]:
        return [self.EOS]

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.BOS

    @property
    def vocab_size(self) -> int:
        return 259


class HfTokenizer:
    """HuggingFace tokenizer loaded from a *local* path (offline-only)."""

    def __init__(self, path: str):
        tok_json = os.path.join(path, "tokenizer.json")
        if os.path.exists(tok_json):
            from tokenizers import Tokenizer as _RustTok

            self._tok = _RustTok.from_file(tok_json)
            self._fast = True
        else:  # pragma: no cover - slow tokenizer fallback
            from transformers import AutoTokenizer

            self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
            self._fast = False
        self._eos_ids, self._bos_id = _special_ids_from_config(path, self)

    def encode(self, text: str) -> List[int]:
        if self._fast:
            return list(self._tok.encode(text, add_special_tokens=False).ids)
        return list(self._tok.encode(text, add_special_tokens=False))

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=False)

    def token_to_id(self, token: str) -> Optional[int]:
        if self._fast:
            return self._tok.token_to_id(token)
        return self._tok.convert_tokens_to_ids(token)

    @property
    def eos_token_ids(self) -> List[int]:
        return self._eos_ids

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos_id

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size() if self._fast else len(self._tok)


def _special_ids_from_config(path: str, tok: "HfTokenizer") -> Tuple[List[int], Optional[int]]:
    eos_ids: List[int] = []
    bos_id: Optional[int] = None
    # generation_config.json may carry a list of eos ids; tokenizer_config the names
    gc = os.path.join(path, "generation_config.json")
    if os.path.exists(gc):
        with open(gc) as f:
            g = json.load(f)
        e = g.get("eos_token_id")
        if isinstance(e, list):
            eos_ids = [int(x) for x in e]
        elif e is not None:
            eos_ids = [int(e)]
        if g.get("bos_token_id") is not None:
            bos_id = int(g["bos_token_id"])
    tc = os.path.join(path, "tokenizer_config.json")
    if os.path.exists(tc):
        with open(tc) as f:
            c = json.load(f)

        def _name(v):
            return v.get("content") if isinstance(v, dict) else v

        if not eos_ids and c.get("eos_token"):
            i = tok.token_to_id(_name(c["eos_token"]))
            if i is not None:
                eos_ids = [i]
        if bos_id is None and c.get("bos_token"):
            i = tok.token_to_id(_name(c["bos_token"]))
            if i is not None:
                bos_id = i
    return eos_ids, bos_id


def load_tokenizer(path_or_kind: str) -> Tokenizer:
    """``"byte"`` → ByteTokenizer; ``"gguf-sp:<file.gguf>"`` → the native
    SentencePiece tokenizer built from the GGUF's embedded SPM vocab;
    ``"gguf-bpe:<file.gguf>"`` → the native byte-level BPE tokenizer from
    the GGUF's tokens+merges; otherwise a local HF tokenizer directory."""
    if path_or_kind == "byte":
        return ByteTokenizer()
    if path_or_kind.startswith("gguf-sp:"):
        from .sp_tokenizer import SpTokenizer

        return SpTokenizer.from_gguf(path_or_kind[len("gguf-sp:"):])
    if path_or_kind.startswith("gguf-bpe:"):
        from .bpe_tokenizer import BpeTokenizer

        return BpeTokenizer.from_gguf(path_or_kind[len("gguf-bpe:"):])
    return HfTokenizer(path_or_kind)


class DecodeStream:
    """Incremental detokenization over a growing token list.

    Uses the prefix/read-offset algorithm: only emit text once the decoded
    suffix no longer ends in a replacement character (i.e. no torn UTF-8), so
    streamed chunks concatenate to exactly ``decode(all_tokens)``.
    """

    # How many trailing prompt tokens to keep as detokenization context (some
    # tokenizers render a token differently at sequence start vs mid-sequence).
    _CTX = 6

    def __init__(self, tokenizer: Tokenizer, prompt_ids: Sequence[int] = ()):
        self._tok = tokenizer
        self._ids: List[int] = list(prompt_ids[-self._CTX:])
        self._prefix_offset = len(self._ids)
        self._read_offset = len(self._ids)

    def step(self, token_id: int) -> str:
        """Feed one token; return newly-finalized text ('' if held back)."""
        self._ids.append(int(token_id))
        prefix = self._tok.decode(self._ids[self._prefix_offset : self._read_offset])
        full = self._tok.decode(self._ids[self._prefix_offset :])
        if full.endswith("�"):
            return ""  # torn multibyte char: wait for more tokens
        new = full[len(prefix) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return new

    def flush(self) -> str:
        """End-of-stream: release any text still held back (even if it ends in
        a torn codepoint, rendered as U+FFFD) so that the concatenation of all
        ``step()`` results plus ``flush()`` equals ``decode(all_tokens)``."""
        prefix = self._tok.decode(self._ids[self._prefix_offset : self._read_offset])
        full = self._tok.decode(self._ids[self._prefix_offset :])
        self._prefix_offset = self._read_offset = len(self._ids)
        return full[len(prefix) :]

    @property
    def token_ids(self) -> List[int]:
        return self._ids


class StopSequenceDecoder:
    """Holds back ("jails") emitted text that could be the start of a stop
    sequence; truncates the stream at a full match.

    ``feed(text) -> (visible_text, stopped)``; call ``flush()`` at end of
    stream to release any jailed text that never completed a stop sequence.
    """

    def __init__(self, stop_sequences: Sequence[str]):
        self._stops = [s for s in stop_sequences if s]
        self._jail = ""
        self.stopped = False

    def feed(self, text: str) -> Tuple[str, bool]:
        if self.stopped:
            return "", True
        if not self._stops:
            return text, False
        buf = self._jail + text
        # full match => truncate at earliest occurrence
        cut = -1
        for s in self._stops:
            i = buf.find(s)
            if i != -1 and (cut == -1 or i < cut):
                cut = i
        if cut != -1:
            self.stopped = True
            self._jail = ""
            return buf[:cut], True
        # partial match at the tail => jail it
        hold = 0
        for s in self._stops:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._jail = buf[-hold:]
            return buf[:-hold], False
        self._jail = ""
        return buf, False

    def flush(self) -> str:
        out, self._jail = self._jail, ""
        return out
