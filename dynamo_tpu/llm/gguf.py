"""GGUF model file support: metadata, config, tokenizer and tensor loading.

Parses the GGUF v2/v3 container format (llama.cpp's model distribution
format): header, string-keyed typed metadata, and the tensor directory. A
llama-family GGUF (llama/mistral/qwen2) maps onto :class:`~dynamo_tpu.
models.llama.LlamaConfig` and the stacked param pytree the engine serves;
F32/F16/BF16 tensors load directly; Q8_0/Q4_0/Q5_0/Q5_1 block-quantized and
Q4_K/Q5_K/Q6_K super-block-quantized tensors (the formats stock *_K_M
exports ship) dequantize at load.

Reference capability: lib/llm/src/gguf/{content,gguf_metadata,
gguf_tokenizer}.rs (~950 LoC: metadata parse, tokenizer build, model
config) — the reference loads GGUF for mistralrs/llamacpp engines and model
cards.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types (gguf spec)
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 \
    = range(13)

_SCALAR_FMT = {_U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
               _I32: "<i", _F32: "<f", _BOOL: "<?", _U64: "<Q", _I64: "<q",
               _F64: "<d"}

# tensor ggml dtypes
_GGML_F32, _GGML_F16 = 0, 1
_GGML_Q4_0, _GGML_Q8_0, _GGML_BF16 = 2, 8, 16
_GGML_Q5_0, _GGML_Q5_1 = 6, 7
_GGML_Q4_K, _GGML_Q5_K, _GGML_Q6_K = 12, 13, 14
_GGML_NAMES = {0: "F32", 1: "F16", 2: "Q4_0", 3: "Q4_1", 6: "Q5_0",
               7: "Q5_1", 8: "Q8_0", 9: "Q8_1", 10: "Q2_K", 11: "Q3_K",
               12: "Q4_K", 13: "Q5_K", 14: "Q6_K", 16: "BF16"}
_QBLOCK = 32   # values per quant block (Q4_0 / Q8_0)
_QK_K = 256    # values per K-quant super-block


def _dequant_q8_0(raw: bytes, count: int) -> np.ndarray:
    """Q8_0: per 32-value block, one f16 scale + 32 int8 -> w = d * q."""
    nb = count // _QBLOCK
    rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"),
                                             ("q", "i1", (_QBLOCK,))]),
                        count=nb)
    return (rec["d"].astype(np.float32)[:, None]
            * rec["q"].astype(np.float32)).reshape(count)


def _dequant_q4_0(raw: bytes, count: int) -> np.ndarray:
    """Q4_0: per 32-value block, one f16 scale + 16 bytes of nibbles ->
    w = d * (q - 8); low nibbles are values 0..15, high nibbles 16..31."""
    nb = count // _QBLOCK
    rec = np.frombuffer(raw, dtype=np.dtype([("d", "<f2"),
                                             ("q", "u1", (_QBLOCK // 2,))]),
                        count=nb)
    lo = (rec["q"] & 0x0F).astype(np.int8) - 8
    hi = (rec["q"] >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (rec["d"].astype(np.float32)[:, None] * vals).reshape(count)


def _q5_bits(qh: np.ndarray) -> np.ndarray:
    """[nb, 4] uint8 -> [nb, 32] the per-value 5th bit (llama.cpp order:
    bit i of the packed u32 belongs to value i; values 0..15 are low
    nibbles, 16..31 high nibbles)."""
    bits32 = qh.view(np.uint32).reshape(-1, 1)          # [nb, 1] LE
    idx = np.arange(32, dtype=np.uint32)[None, :]
    return ((bits32 >> idx) & 1).astype(np.uint8)        # [nb, 32]


def _dequant_q5_0(raw: bytes, count: int) -> np.ndarray:
    """Q5_0: f16 scale + 32 high bits + 16 nibble bytes; w = d*(q-16)."""
    nb = count // _QBLOCK
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("qh", "u1", (4,)), ("q", "u1", (_QBLOCK // 2,))]),
        count=nb)
    h = _q5_bits(rec["qh"])
    lo = (rec["q"] & 0x0F) | (h[:, :16] << 4)
    hi = (rec["q"] >> 4) | (h[:, 16:] << 4)
    vals = np.concatenate([lo, hi], axis=1).astype(np.float32) - 16.0
    return (rec["d"].astype(np.float32)[:, None] * vals).reshape(count)


def _dequant_q5_1(raw: bytes, count: int) -> np.ndarray:
    """Q5_1: f16 scale + f16 min + 32 high bits + nibbles; w = d*q + m."""
    nb = count // _QBLOCK
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("m", "<f2"), ("qh", "u1", (4,)),
         ("q", "u1", (_QBLOCK // 2,))]), count=nb)
    h = _q5_bits(rec["qh"])
    lo = (rec["q"] & 0x0F) | (h[:, :16] << 4)
    hi = (rec["q"] >> 4) | (h[:, 16:] << 4)
    vals = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (rec["d"].astype(np.float32)[:, None] * vals
            + rec["m"].astype(np.float32)[:, None]).reshape(count)


def _kquant_scale_min(scales: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte 6-bit scale/min table of Q4_K/Q5_K super-blocks.
    scales: [nb, 12] uint8 -> (sc [nb, 8], mn [nb, 8]) float32."""
    s = scales.astype(np.uint16)
    sc = np.empty(s.shape[:-1] + (8,), np.uint16)
    mn = np.empty_like(sc)
    sc[..., :4] = s[..., 0:4] & 63
    mn[..., :4] = s[..., 4:8] & 63
    sc[..., 4:] = (s[..., 8:12] & 0x0F) | ((s[..., 0:4] >> 6) << 4)
    mn[..., 4:] = (s[..., 8:12] >> 4) | ((s[..., 4:8] >> 6) << 4)
    return sc.astype(np.float32), mn.astype(np.float32)


def _dequant_q4_k(raw: bytes, count: int) -> np.ndarray:
    """Q4_K: 256-value super-blocks of 8 sub-blocks; w = d*sc*q - dmin*m,
    q in 0..15. Layout per 64 values: 32 bytes, low nibbles -> sub-block
    2j, high nibbles -> sub-block 2j+1 (llama.cpp dequantize_row_q4_K)."""
    nb = count // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
         ("qs", "u1", (128,))]), count=nb)
    sc, mn = _kquant_scale_min(rec["scales"])
    d = rec["d"].astype(np.float32)[:, None] * sc       # [nb, 8]
    m = rec["dmin"].astype(np.float32)[:, None] * mn
    qs = rec["qs"].reshape(nb, 4, 32)                   # 4 groups of 64
    lo = (qs & 0x0F).astype(np.float32)                 # sub-block 2j
    hi = (qs >> 4).astype(np.float32)                   # sub-block 2j+1
    q = np.stack([lo, hi], axis=2).reshape(nb, 8, 32)
    out = d[:, :, None] * q - m[:, :, None]
    return out.reshape(count)


def _dequant_q5_k(raw: bytes, count: int) -> np.ndarray:
    """Q5_K: Q4_K's scale scheme + one high bit per value from qh."""
    nb = count // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
         ("qh", "u1", (32,)), ("qs", "u1", (128,))]), count=nb)
    sc, mn = _kquant_scale_min(rec["scales"])
    d = rec["d"].astype(np.float32)[:, None] * sc
    m = rec["dmin"].astype(np.float32)[:, None] * mn
    qs = rec["qs"].reshape(nb, 4, 32)
    qh = rec["qh"][:, None, :]                          # [nb, 1, 32]
    group = np.arange(4)[None, :, None]
    lo = (qs & 0x0F) + (((qh >> (2 * group)) & 1) << 4)       # u1 bit
    hi = (qs >> 4) + (((qh >> (2 * group + 1)) & 1) << 4)     # u2 bit
    q = np.stack([lo, hi], axis=2).reshape(nb, 8, 32).astype(np.float32)
    out = d[:, :, None] * q - m[:, :, None]
    return out.reshape(count)


def _dequant_q6_k(raw: bytes, count: int) -> np.ndarray:
    """Q6_K: 256-value super-blocks, 16 int8 scales, 6-bit values
    (4 low bits in ql, 2 high bits in qh); w = d * sc * (q - 32)."""
    nb = count // _QK_K
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("ql", "u1", (128,)), ("qh", "u1", (64,)),
         ("scales", "i1", (16,)), ("d", "<f2")]), count=nb)
    d = rec["d"].astype(np.float32)                 # [nb]
    sc = rec["scales"].astype(np.float32).reshape(nb, 2, 8)  # per 128-half
    ql = rec["ql"].reshape(nb, 2, 64)               # 64 bytes per half
    qh = rec["qh"].reshape(nb, 2, 32)               # 32 bytes per half
    l = np.arange(32)
    out = np.empty((nb, 2, 4, 32), np.float32)      # [nb, half, quarter, l]
    for quarter in range(4):
        src = ql[:, :, 32 * (quarter & 1):32 * (quarter & 1) + 32]
        nib = (src & 0x0F) if quarter < 2 else (src >> 4)
        q = (nib | (((qh >> (2 * quarter)) & 3) << 4)).astype(np.int32) - 32
        scale = sc[:, :, 2 * quarter + l // 16]     # [nb, 2, 32]
        out[:, :, quarter, :] = d[:, None, None] * scale * q
    return out.reshape(count)


_KQUANT_BYTES = {_GGML_Q4_K: 144, _GGML_Q5_K: 176, _GGML_Q6_K: 210}
_KQUANT_FNS = {_GGML_Q4_K: _dequant_q4_k, _GGML_Q5_K: _dequant_q5_k,
               _GGML_Q6_K: _dequant_q6_k}
# 32-value block formats: ggml type -> (bytes per block, dequant fn)
_QBLOCK_FMT = {
    _GGML_Q8_0: (2 + _QBLOCK, _dequant_q8_0),
    _GGML_Q4_0: (2 + _QBLOCK // 2, _dequant_q4_0),
    _GGML_Q5_0: (2 + 4 + _QBLOCK // 2, _dequant_q5_0),
    _GGML_Q5_1: (4 + 4 + _QBLOCK // 2, _dequant_q5_1),
}


@dataclass
class GGUFTensorInfo:
    name: str
    shape: Tuple[int, ...]      # logical shape, row-major (numpy order)
    ggml_type: int
    offset: int                 # within the data section


@dataclass
class GGUFFile:
    version: int
    metadata: Dict[str, Any]
    tensors: Dict[str, GGUFTensorInfo]
    data_start: int
    path: str
    _fh: Optional[BinaryIO] = field(default=None, repr=False, compare=False)

    # ------------------------------------------------------------------
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "")

    def llama_config(self):
        """Map llama-family metadata onto LlamaConfig. Covers the
        llama-shaped architectures GGUF ships (llama/mistral identical;
        qwen2 adds qkv bias)."""
        from ..models.llama import LlamaConfig

        md = self.metadata
        arch = self.architecture()
        if arch not in ("llama", "mistral", "qwen2", "gemma", "gemma2",
                        "gemma3"):
            raise ValueError(f"not a llama-family GGUF: {arch!r}")

        def g(key, default=None):
            return md.get(f"{arch}.{key}", default)

        n_heads = int(g("attention.head_count"))
        emb = int(g("embedding_length"))
        vocab = md.get("tokenizer.ggml.tokens")
        vocab_size = (int(md[f"{arch}.vocab_size"])
                      if f"{arch}.vocab_size" in md
                      else len(vocab) if vocab else 32000)
        gemma2 = arch == "gemma2"
        gemma3 = arch == "gemma3"
        gemma_any = arch in ("gemma", "gemma2", "gemma3")
        # rope scaling: gemma3 4b/12b/27b and linear-scaled llamas carry
        # {arch}.rope.scaling.{type,factor}; ignoring them would run rope at
        # unscaled (e.g. 8x-too-fast) frequencies — silently wrong logits at
        # every position. llama3-style NTK scaling is exported by llama.cpp
        # as a rope_freqs.weight tensor of per-frequency divisors instead.
        rope_scaling = None
        scale_type = g("rope.scaling.type")
        if scale_type in (None, "", "none"):
            pass
        elif scale_type == "linear":
            rope_scaling = {"rope_type": "linear",
                            "factor": float(g("rope.scaling.factor", 1.0))}
        else:
            # yarn etc.: refusing beats serving wrong positions for every
            # token (ref lib/llm/src/gguf/* takes the same bail-hard stance
            # on unknown tokenizer models)
            raise NotImplementedError(
                f"GGUF rope scaling type {scale_type!r} is not supported "
                f"(linear and llama3-style rope_freqs factors are); "
                f"serving without it would be silently wrong")
        for tname in ("rope_freqs.weight", "rope_factors_long.weight"):
            if tname in self.tensors:
                if tname != "rope_freqs.weight":
                    raise NotImplementedError(
                        f"GGUF per-position rope factor tensor {tname!r} "
                        f"(longrope) is not supported")
                factors = self.load_tensor(tname).astype(float).ravel()
                if rope_scaling is not None:
                    # ggml applies freq_scale (linear) AND freq_factors
                    # together (ggml_rope_ext); fold the linear factor into
                    # the per-frequency divisors rather than dropping it
                    factors = factors * rope_scaling["factor"]
                rope_scaling = {"rope_type": "ggml_factors",
                                "factors": factors.tolist()}
        return LlamaConfig(
            tie_embeddings="output.weight" not in self.tensors,
            attention_bias="blk.0.attn_q.bias" in self.tensors,
            hidden_act="gelu_tanh" if gemma_any else "silu",
            # llama.cpp's gemma converter bakes the +1 into norm weights at
            # export, so GGUF files store the EFFECTIVE scale — applying the
            # offset again would compute 2+w
            norm_offset=False,
            embed_scale=gemma_any,
            sandwich_norms=gemma2 or gemma3,
            qk_norm=gemma3,
            sliding_pattern=(6 if gemma3 else 2),
            rope_local_theta=(float(g("rope.local.freq_base", 10000.0))
                              if gemma3 else None),
            attn_logit_softcap=(float(g("attn_logit_softcapping", 50.0))
                                if gemma2 else None),
            final_logit_softcap=(float(g("final_logit_softcapping", 30.0))
                                 if gemma2 else None),
            sliding_window=(int(g("attention.sliding_window",
                                  1024 if gemma3 else 4096))
                            if gemma2 or gemma3 else None),
            # attention scale: rsqrt(head_dim) for gemma2 2b/9b, but 27b
            # uses rsqrt(hidden/heads)=rsqrt(144). GGUF metadata carries no
            # scale key, so mirror llama.cpp's rule: the 27b variant (its
            # unique 46-layer stack) gets hidden/heads; honor an explicit
            # key when an exporter provides one. Serving 27b at the 2b/9b
            # scale would be ~6% off on every attention score — silently.
            # the 27B variants scale by rsqrt(hidden/heads), not
            # rsqrt(head_dim): gemma2-27b = 46 layers, gemma3-27b = 62
            # (llama.cpp hardcodes the same rule; GGUF carries no key)
            query_pre_attn_scalar=(
                float(md[f"{arch}.attention.query_pre_attn_scalar"])
                if f"{arch}.attention.query_pre_attn_scalar" in md
                else float(emb) / n_heads
                if ((gemma2 and int(g("block_count")) == 46)
                    or (gemma3 and int(g("block_count")) == 62))
                else None),
            rope_scaling=rope_scaling,
            vocab_size=vocab_size,
            hidden_size=emb,
            num_layers=int(g("block_count")),
            num_heads=n_heads,
            num_kv_heads=int(g("attention.head_count_kv", n_heads)),
            head_dim=int(g("attention.key_length", emb // n_heads)),
            intermediate_size=int(g("feed_forward_length")),
            rope_theta=float(g("rope.freq_base", 10000.0)),
            rms_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
            max_position=int(g("context_length", 8192)),
        )

    def tokenizer_vocab(self) -> Optional[List[str]]:
        return self.metadata.get("tokenizer.ggml.tokens")

    def load_tensor(self, name: str) -> np.ndarray:
        info = self.tensors[name]
        count = int(np.prod(info.shape)) if info.shape else 1
        if info.ggml_type in _QBLOCK_FMT:
            # block-quantized weights dequantize to f32 at load (the engine
            # casts to its compute dtype; on-device quantized matmuls are a
            # separate optimization, this is the loading capability)
            bpb, deq_fn = _QBLOCK_FMT[info.ggml_type]
            raw = self._read(self.data_start + info.offset,
                             count // _QBLOCK * bpb)
            return deq_fn(raw, count).reshape(info.shape)
        if info.ggml_type in _KQUANT_FNS:
            raw = self._read(self.data_start + info.offset,
                             count // _QK_K * _KQUANT_BYTES[info.ggml_type])
            return _KQUANT_FNS[info.ggml_type](raw, count) \
                .reshape(info.shape)
        if info.ggml_type == _GGML_BF16:
            import ml_dtypes

            raw = self._read(self.data_start + info.offset, count * 2)
            return np.frombuffer(raw, dtype=ml_dtypes.bfloat16) \
                .reshape(info.shape)
        if info.ggml_type not in (_GGML_F32, _GGML_F16):
            tname = _GGML_NAMES.get(info.ggml_type, str(info.ggml_type))
            raise NotImplementedError(
                f"tensor {name!r} uses unsupported ggml type {tname}; "
                f"F32/F16/BF16/Q8_0/Q4_0/Q5_0/Q5_1/Q4_K/Q5_K/Q6_K are loadable "
                f"(dequantize or re-export the model)")
        dtype = np.float32 if info.ggml_type == _GGML_F32 else np.float16
        raw = self._read(self.data_start + info.offset,
                         count * dtype().itemsize)
        return np.frombuffer(raw, dtype=dtype).reshape(info.shape)

    def _read(self, offset: int, size: int) -> bytes:
        # one persistent handle: bulk loads touch every tensor and a
        # 70B-class model would otherwise pay hundreds of open/close cycles
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "rb")
        self._fh.seek(offset)
        return self._fh.read(size)

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self._fh.close()


# ---------------------------------------------------------------------------
# parse
# ---------------------------------------------------------------------------

def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        fmt = _SCALAR_FMT[vtype]
        (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
        return v
    if vtype == _STR:
        return _read_str(f)
    if vtype == _ARR:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(count)]
    raise ValueError(f"unknown gguf metadata type {vtype}")


def read_gguf(path: str) -> GGUFFile:
    """Parse header + metadata + tensor directory (tensors load lazily)."""
    with open(path, "rb") as f:
        magic, version = struct.unpack("<II", f.read(8))
        if magic != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        if version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {version}")
        n_tensors, n_kv = struct.unpack("<QQ", f.read(16))

        metadata: Dict[str, Any] = {}
        for _ in range(n_kv):
            key = _read_str(f)
            (vtype,) = struct.unpack("<I", f.read(4))
            metadata[key] = _read_value(f, vtype)

        tensors: Dict[str, GGUFTensorInfo] = {}
        for _ in range(n_tensors):
            name = _read_str(f)
            (ndim,) = struct.unpack("<I", f.read(4))
            dims = struct.unpack(f"<{ndim}Q", f.read(8 * ndim))
            (ggml_type,) = struct.unpack("<I", f.read(4))
            (offset,) = struct.unpack("<Q", f.read(8))
            # gguf stores dims innermost-first; numpy wants outermost-first
            tensors[name] = GGUFTensorInfo(name, tuple(reversed(dims)),
                                           ggml_type, offset)

        align = int(metadata.get("general.alignment", 32))
        pos = f.tell()
        data_start = (pos + align - 1) // align * align
    return GGUFFile(version, metadata, tensors, data_start, path)


# ---------------------------------------------------------------------------
# llama param mapping (gguf tensor names -> our stacked pytree)
# ---------------------------------------------------------------------------

def load_llama_params_gguf(path: str, cfg=None,
                           shardings: Optional[Dict[str, Any]] = None,
                           dtype=None) -> Tuple[Any, Dict[str, Any]]:
    """Load a llama GGUF into (config, stacked param pytree). With
    ``shardings`` each tensor is placed straight into its NamedSharding."""
    import jax
    import jax.numpy as jnp

    g = read_gguf(path)
    if cfg is None:
        cfg = g.llama_config()
    dt = np.dtype(jnp.bfloat16 if dtype is None else dtype)
    L, D, Hq, Hkv, Dh = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                         cfg.num_kv_heads, cfg.head_dim)

    def t(name):
        return g.load_tensor(name)

    def stack(fmt, transform):
        return np.stack([transform(t(fmt.format(i))) for i in range(L)])

    params: Dict[str, Any] = {
        "embed": t("token_embd.weight").astype(dt),
        "layers": {
            "ln1": stack("blk.{}.attn_norm.weight",
                         lambda w: w.astype(np.float32)),
            "ln2": stack("blk.{}.ffn_norm.weight",
                         lambda w: w.astype(np.float32)),
            "wq": stack("blk.{}.attn_q.weight",
                        lambda w: w.astype(dt).T.reshape(D, Hq, Dh)),
            "wk": stack("blk.{}.attn_k.weight",
                        lambda w: w.astype(dt).T.reshape(D, Hkv, Dh)),
            "wv": stack("blk.{}.attn_v.weight",
                        lambda w: w.astype(dt).T.reshape(D, Hkv, Dh)),
            "wo": stack("blk.{}.attn_output.weight",
                        lambda w: w.astype(dt).T.reshape(Hq, Dh, D)),
            "wg": stack("blk.{}.ffn_gate.weight", lambda w: w.astype(dt).T),
            "wu": stack("blk.{}.ffn_up.weight", lambda w: w.astype(dt).T),
            "wd": stack("blk.{}.ffn_down.weight", lambda w: w.astype(dt).T),
        },
        "final_norm": t("output_norm.weight").astype(np.float32),
    }
    if cfg.sandwich_norms:
        # gemma2/3 GGUF tensor names: post_attention_norm / post_ffw_norm
        # (ffn_norm above is the PRE-ffw norm in this layout)
        params["layers"]["ln1_post"] = stack(
            "blk.{}.post_attention_norm.weight",
            lambda w: w.astype(np.float32))
        params["layers"]["ln2_post"] = stack(
            "blk.{}.post_ffw_norm.weight", lambda w: w.astype(np.float32))
    if cfg.qk_norm:
        params["layers"]["ln_q"] = stack(
            "blk.{}.attn_q_norm.weight", lambda w: w.astype(np.float32))
        params["layers"]["ln_k"] = stack(
            "blk.{}.attn_k_norm.weight", lambda w: w.astype(np.float32))
    if cfg.attention_bias:
        params["layers"]["bq"] = stack(
            "blk.{}.attn_q.bias", lambda w: w.astype(dt).reshape(Hq, Dh))
        params["layers"]["bk"] = stack(
            "blk.{}.attn_k.bias", lambda w: w.astype(dt).reshape(Hkv, Dh))
        params["layers"]["bv"] = stack(
            "blk.{}.attn_v.bias", lambda w: w.astype(dt).reshape(Hkv, Dh))
    if "output.weight" in g.tensors:
        params["lm_head"] = t("output.weight").astype(dt).T
    g.close()
    if shardings is not None:
        from ..engine.engine import global_put

        params = jax.tree.map(lambda a, s: global_put(a, s),
                              params, shardings)
    return cfg, params


def write_gguf(path: str, metadata: Dict[str, Any],
               tensors: Dict[str, np.ndarray]) -> None:
    """Minimal GGUF v3 writer (F32 tensors) — test fixture / export path."""
    def pstr(s: str) -> bytes:
        b = s.encode()
        return struct.pack("<Q", len(b)) + b

    def pval(v) -> bytes:
        if isinstance(v, bool):
            return struct.pack("<I", _BOOL) + struct.pack("<?", v)
        if isinstance(v, int):
            return struct.pack("<I", _I64) + struct.pack("<q", v)
        if isinstance(v, float):
            return struct.pack("<I", _F64) + struct.pack("<d", v)
        if isinstance(v, str):
            return struct.pack("<I", _STR) + pstr(v)
        if isinstance(v, list):
            if v and isinstance(v[0], str):
                body = b"".join(pstr(x) for x in v)
                return (struct.pack("<I", _ARR) + struct.pack("<I", _STR)
                        + struct.pack("<Q", len(v)) + body)
            body = b"".join(struct.pack("<q", int(x)) for x in v)
            return (struct.pack("<I", _ARR) + struct.pack("<I", _I64)
                    + struct.pack("<Q", len(v)) + body)
        raise TypeError(f"unsupported metadata value {type(v)}")

    align = 32
    out = bytearray()
    out += struct.pack("<II", GGUF_MAGIC, 3)
    out += struct.pack("<QQ", len(tensors), len(metadata) + 1)
    out += pstr("general.alignment") + struct.pack("<I", _I64) \
        + struct.pack("<q", align)
    for k, v in metadata.items():
        out += pstr(k) + pval(v)

    data = bytearray()
    infos = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        off = len(data)
        data += arr.tobytes()
        pad = (-len(data)) % align
        data += b"\x00" * pad
        infos.append((name, arr.shape, off))
    for name, shape, off in infos:
        out += pstr(name)
        out += struct.pack("<I", len(shape))
        for d in reversed(shape):          # gguf dims innermost-first
            out += struct.pack("<Q", d)
        out += struct.pack("<I", _GGML_F32)
        out += struct.pack("<Q", off)
    pad = (-len(out)) % align
    out += b"\x00" * pad
    with open(path, "wb") as f:
        f.write(out + data)
