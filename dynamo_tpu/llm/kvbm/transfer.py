"""Layer-pipelined page copies between the device KV pool and host memory.

D2H: one async gather per layer is dispatched up front; the host then
converts layer by layer while the device keeps executing the remaining
gathers — transfer of layer l overlaps compute of layer l+1, the same
pipelining the reference gets from its per-layer CUDA copy kernel on a
dedicated stream. H2D: per-layer donated scatters queue on the device and
return immediately.

Reference capability: block_copy.cu + CopyStream layer triggering
(lib/llm/src/kernels/block_copy.cu:25-80, lib/llm/src/kv/layer.rs:619-1132),
re-expressed as jitted XLA gathers/scatters because on TPU the runtime's
async dispatch queue *is* the copy stream.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class CopyStream:
    """Jitted page gather/scatter helpers over pools shaped
    [L, Hkv, n_pages, page, Dh] (host blocks stay [L, Hkv, page, Dh])."""

    def __init__(self):
        self._gather_layer = jax.jit(
            lambda pool, l, pages: jnp.swapaxes(pool[l][:, pages], 0, 1))
        # [l, :, pages] batches the scalar l with pages -> indexed shape
        # [n, Hkv, page, Dh], matching the host block layout directly
        self._scatter_layer = jax.jit(
            lambda pool, l, pages, vals: pool.at[l, :, pages].set(vals),
            donate_argnums=0)
        self._gather_all = jax.jit(
            lambda pool, pages: jnp.transpose(pool[:, :, pages],
                                              (2, 0, 1, 3, 4)))
        # device-resident [n, L, Hkv, page, Dh] blocks -> pool pages, one
        # dispatch per pool: the h2d happened earlier (prefetch staging),
        # this is the d2d consume on admission's critical path
        self._scatter_blocks = jax.jit(
            lambda pool, pages, vals: pool.at[:, :, pages].set(
                jnp.moveaxis(vals, 0, 2)), donate_argnums=0)
        # weight-mobility h2d: overwrite a contiguous layer-group slab of a
        # stacked [L, ...] param leaf in place (donated — the swap reuses
        # the engine's existing device buffers instead of doubling HBM).
        # One program per (leaf shape, group size); NOT routed through
        # instrument_compile on purpose: swap-path helper compiles must not
        # perturb the dyn_compiled_programs flatness contract.
        self._scatter_slab = jax.jit(
            lambda buf, start, vals: jax.lax.dynamic_update_slice(
                buf, vals, (start,) + (0,) * (vals.ndim - 1)),
            donate_argnums=0)

    def h2d_param_slab(self, buf, start: int, vals):
        """Scatter an already-on-device layer-group chunk ``vals``
        ([G, ...]) into the stacked param leaf ``buf`` ([L, ...]) at layer
        ``start``, donating the old buffer. Returns the new leaf."""
        return self._scatter_slab(buf, np.int32(start), vals)

    # ------------------------------------------------------------------
    def d2h_pages(self, k_pool, v_pool, pages: Sequence[int],
                  pipeline: bool = False) -> Tuple[np.ndarray, np.ndarray]:
        """Copy pages out to host. Returns (k, v) [n, L, Hkv, page, Dh].

        ``pipeline=True`` dispatches one gather per layer so host conversion
        of layer l overlaps device execution of layer l+1 — worth it for
        bulk multi-page transfers (disagg); small transfers use one
        dispatch per pool."""
        idx = jnp.asarray(list(pages), jnp.int32)
        if not pipeline:
            # dynalint: ok(host-sync) the d2h page copy IS the transfer:
            # tier offload / pager demotion ships blocks host-staged,
            # batched per eviction flush or demotion, never per token
            return (np.asarray(self._gather_all(k_pool, idx)),
                    # dynalint: ok(host-sync) second half of the same copy
                    np.asarray(self._gather_all(v_pool, idx)))
        L = k_pool.shape[0]
        # dispatch every layer's gather before converting any (async queue)
        k_parts = [self._gather_layer(k_pool, l, idx) for l in range(L)]
        v_parts = [self._gather_layer(v_pool, l, idx) for l in range(L)]
        k = np.stack([np.asarray(p) for p in k_parts], axis=1)
        v = np.stack([np.asarray(p) for p in v_parts], axis=1)
        return k, v

    def h2d_pages(self, k_pool, v_pool, pages: Sequence[int],
                  k: np.ndarray, v: np.ndarray):
        """Upload [n, L, Hkv, page, Dh] host blocks into device pages,
        queueing one donated scatter per layer. Returns the new pools."""
        idx = jnp.asarray(list(pages), jnp.int32)
        L = k_pool.shape[0]
        dt = k_pool.dtype
        for l in range(L):
            k_pool = self._scatter_layer(k_pool, l, idx,
                                         jnp.asarray(k[:, l], dt))
            v_pool = self._scatter_layer(v_pool, l, idx,
                                         jnp.asarray(v[:, l], dt))
        return k_pool, v_pool

    def scatter_blocks(self, k_pool, v_pool, pages: Sequence[int],
                       k_blocks: Sequence, v_blocks: Sequence):
        """Scatter already-on-device [L, Hkv, page, Dh] blocks (the h2d
        prefetch staging buffer) into pool pages — pure device-to-device,
        so a prefetched tier hit costs admission no host transfer at all.
        Returns the new pools."""
        idx = jnp.asarray(list(pages), jnp.int32)
        k_pool = self._scatter_blocks(k_pool, idx, jnp.stack(k_blocks))
        v_pool = self._scatter_blocks(v_pool, idx, jnp.stack(v_blocks))
        return k_pool, v_pool
