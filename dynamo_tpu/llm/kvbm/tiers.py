"""Host-DRAM and disk KV cache tiers.

TPU VMs carry large host DRAM; offloaded KV pages park there (and optionally
spill to an mmap'd file) keyed by chained sequence hash, so a later request
with the same prefix re-uploads instead of recomputing. Capacity is
fixed-slot: each tier is one preallocated array of block slots + an LRU map,
so steady-state serving does zero host allocation.

Cross-thread contract: the engine thread owns all tier mutation on the
serving path (offload at eviction flush, lookup at admission), but the
cluster-sharing plane (``llm/kv_cluster/``) reads AND deposits blocks from
the asyncio thread — peer fetches land fetched prefixes here, and the
``kv_fetch`` endpoint serves blocks out. :class:`TieredKvCache` therefore
guards every access with one internal lock; ``peek`` reads a block without
perturbing LRU order (safe for probes and peer serving), and ``hashes``
snapshots the resident hash sets for the cluster registry publisher.

Reference capability: the multi-tier KV manager design HBM->CPU->SSD
(docs/kv_cache_manager.md:5-15,39-71, lib/llm/src/kv/storage.rs pinned/system
tiers) — host-staged rather than GPUDirect, which is the TPU reality.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ...utils.prometheus import stage_metrics

log = logging.getLogger("dynamo_tpu.kvbm")


class OutOfTierSpace(RuntimeError):
    """A pinned insert found no evictable slot (every resident block is
    pinned) — the paging working set outgrew the tier."""


class _SlotCache:
    """Fixed-capacity LRU of KV blocks in one preallocated array pair.

    ``pinned`` hashes are excluded from LRU eviction: the KV-paging plane
    pins a long sequence's demoted working set so a cluster-traffic burst
    cannot silently drop blocks a live decode still has to read back.
    """

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...],
                 dtype, k_store: np.ndarray, v_store: np.ndarray):
        self.num_blocks = num_blocks
        self.block_shape = block_shape
        self.dtype = dtype
        self._k = k_store
        self._v = v_store
        self._slot_of: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()          # seq_hash -> slot, LRU order
        self._free = list(range(num_blocks - 1, -1, -1))
        self.pinned: set = set()

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._slot_of

    def _victim(self) -> Optional[int]:
        """Oldest resident hash that is not pinned (None = all pinned)."""
        for h in self._slot_of:                # iterates LRU -> MRU
            if h not in self.pinned:
                return h
        return None

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray,
            required: bool = False
            ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Insert a block. Returns the evicted (hash, k, v) if the cache was
        full (caller may cascade it to the next tier), else None.

        When full and every resident block is pinned, the incoming block is
        DROPPED (cache semantics; the caller's data was best-effort) unless
        ``required=True`` — then :class:`OutOfTierSpace` is raised, because
        the caller (the paging plane depositing a pinned block) cannot
        tolerate silent loss."""
        evicted = None
        if seq_hash in self._slot_of:
            self._slot_of.move_to_end(seq_hash)
            slot = self._slot_of[seq_hash]
        elif self._free:
            slot = self._free.pop()
            self._slot_of[seq_hash] = slot
        else:
            old_hash = self._victim()
            if old_hash is None:
                if required:
                    raise OutOfTierSpace(
                        f"all {self.num_blocks} tier blocks are pinned; "
                        f"cannot insert block {seq_hash:x}")
                log.warning("KV tier full of pinned blocks; dropping "
                            "offloaded block %x", seq_hash)
                return None
            slot = self._slot_of.pop(old_hash)
            evicted = (old_hash, self._k[slot].copy(), self._v[slot].copy())
            self._slot_of[seq_hash] = slot
        self._k[slot] = k
        self._v[slot] = v
        return evicted

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        slot = self._slot_of.get(seq_hash)
        if slot is None:
            return None
        self._slot_of.move_to_end(seq_hash)
        return self._k[slot], self._v[slot]

    def peek(self, seq_hash: int
             ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Read WITHOUT touching LRU order (probes, peer serving)."""
        slot = self._slot_of.get(seq_hash)
        if slot is None:
            return None
        return self._k[slot], self._v[slot]

    def peek_layer(self, seq_hash: int, layer: int
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """One layer's [Hkv, page, Dh] slice, no LRU touch — the paging
        plane streams cold blocks layer-at-a-time, and copying the whole
        [L, ...] block per layer would multiply the memcpy by L."""
        slot = self._slot_of.get(seq_hash)
        if slot is None:
            return None
        return self._k[slot][layer], self._v[slot][layer]

    def pop(self, seq_hash: int) -> None:
        slot = self._slot_of.pop(seq_hash, None)
        if slot is not None:
            self.pinned.discard(seq_hash)
            self._free.append(slot)


class HostKvTier(_SlotCache):
    """Host-DRAM tier: [n_blocks, L, Hkv, page, Dh] preallocated numpy."""

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...], dtype):
        shape = (num_blocks, *block_shape)
        super().__init__(num_blocks, block_shape, dtype,
                         np.zeros(shape, dtype), np.zeros(shape, dtype))


class DiskKvTier(_SlotCache):
    """mmap-backed spill tier (the reference's SSD tier)."""

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...], dtype,
                 path: str):
        shape = (num_blocks, *block_shape)
        self.path = path
        k = np.memmap(path + ".k", dtype=dtype, mode="w+", shape=shape)
        v = np.memmap(path + ".v", dtype=dtype, mode="w+", shape=shape)
        super().__init__(num_blocks, block_shape, dtype, k, v)
        self._closed = False

    def close(self) -> None:
        """Flush and remove the spill files. ``mode="w+"`` memmaps are
        scratch state: a worker that exits without this leaks two
        block-pool-sized files in the spill directory per engine."""
        if self._closed:
            return
        self._closed = True
        for arr in (self._k, self._v):
            try:
                arr.flush()
            except (OSError, ValueError):
                log.warning("disk tier flush failed for %s", self.path,
                            exc_info=True)
        # drop the memmap references before unlinking so the interpreter
        # can release the mappings promptly
        self._k = self._v = None
        self._slot_of.clear()
        self._free.clear()
        for suffix in (".k", ".v"):
            try:
                os.unlink(self.path + suffix)
            except FileNotFoundError:
                pass
            except OSError:
                log.warning("could not remove KV spill file %s%s",
                            self.path, suffix, exc_info=True)


class TieredKvCache:
    """Host tier with optional disk spill, one lookup/offload surface.

    ``offload`` inserts at the host tier and cascades host-LRU evictions to
    disk; ``lookup`` checks host then disk (promoting disk hits back to
    host). All arrays are [L, Hkv, page, Dh] per block. Thread-safe: every
    method takes the internal lock, so the engine thread and the cluster
    data plane (peer fetch deposit/serve on the asyncio thread) can share
    one instance. ``on_change`` fires (outside the lock) whenever the
    resident hash sets changed — the cluster registry publisher's dirty
    signal.
    """

    def __init__(self, host: HostKvTier, disk: Optional[DiskKvTier] = None):
        self.host = host
        self.disk = disk
        self.hits = 0
        self.misses = 0
        # one lock shared by the engine thread and the asyncio data plane
        self._lock = threading.RLock()
        self.on_change: Optional[Callable[[], None]] = None
        self._worker = str(os.getpid())

    def __contains__(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self.host or (
                self.disk is not None and seq_hash in self.disk)

    def _set_block_gauges(self) -> None:
        g = stage_metrics().kv_tier_blocks
        g.set("host", self._worker, value=float(len(self.host)))
        if self.disk is not None:
            g.set("disk", self._worker, value=float(len(self.disk)))

    def offload(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            self._offload_locked(seq_hash, k, v)
        self._fire_change()

    def _offload_locked(self, seq_hash: int, k: np.ndarray,
                        v: np.ndarray) -> None:
        """Insert + cascade under the already-held lock, WITHOUT firing
        ``on_change`` — public entry points fire exactly once after the
        lock drops (a callback that needs the lock must not deadlock)."""
        spilled = self.host.put(seq_hash, k, v)
        if spilled is not None and self.disk is not None:
            self.disk.put(*spilled)
        self._set_block_gauges()

    def lookup(self, seq_hash: int
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        stage = stage_metrics()
        promoted = False
        with self._lock:
            got = self.host.get(seq_hash)
            tier = "host" if got is not None else None
            if got is None and self.disk is not None:
                got = self.disk.get(seq_hash)
                if got is not None:   # promote to host (may spill another)
                    tier = "disk"
                    k, v = got[0].copy(), got[1].copy()
                    got = (k, v)
                    if seq_hash in self.disk.pinned:
                        # a pin must never be separated from its data:
                        # promote only if the host can take it as pinned,
                        # else serve from disk and leave it there
                        try:
                            spilled = self.host.put(seq_hash, k, v,
                                                    required=True)
                        except OutOfTierSpace:
                            spilled = None
                        else:
                            if spilled is not None:
                                self.disk.put(*spilled)
                            self.disk.pop(seq_hash)
                            self.host.pinned.add(seq_hash)
                            self._set_block_gauges()
                            promoted = True
                    else:
                        self.disk.pop(seq_hash)
                        self._offload_locked(seq_hash, k, v)
                        promoted = True
            if got is None:
                self.misses += 1
                stage.kv_tier_misses.inc()
            else:
                self.hits += 1
                stage.kv_tier_hits.inc(tier)
        if promoted:
            self._fire_change()
        return got

    def peek(self, seq_hash: int
             ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copy a resident block without promoting/LRU-touching it — what
        the ``kv_fetch`` donor endpoint serves peers from. Returns fresh
        copies (the slot may be recycled the moment the lock drops)."""
        with self._lock:
            got = self.host.peek(seq_hash)
            if got is None and self.disk is not None:
                got = self.disk.peek(seq_hash)
            if got is None:
                return None
            return got[0].copy(), got[1].copy()

    def peek_layer(self, seq_hash: int, layer: int
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copy ONE layer's [Hkv, page, Dh] slice of a resident block, no
        LRU touch — the KV-paging plane's page-in read (streaming cold
        blocks layer-at-a-time must not thrash the reuse order that serves
        admission restores)."""
        with self._lock:
            got = self.host.peek_layer(seq_hash, layer)
            if got is None and self.disk is not None:
                got = self.disk.peek_layer(seq_hash, layer)
            if got is None:
                return None
            return got[0].copy(), got[1].copy()

    # ------------------------------------------------------------------
    # pinning (KV-paging working set)
    # ------------------------------------------------------------------
    def pin(self, seq_hash: int) -> bool:
        """Exclude a resident block from LRU eviction (False = not
        resident anywhere). Pins survive disk->host promotion."""
        with self._lock:
            if seq_hash in self.host:
                self.host.pinned.add(seq_hash)
                return True
            if self.disk is not None and seq_hash in self.disk:
                self.disk.pinned.add(seq_hash)
                return True
            return False

    def unpin(self, seq_hash: int) -> None:
        with self._lock:
            self.host.pinned.discard(seq_hash)
            if self.disk is not None:
                self.disk.pinned.discard(seq_hash)

    def pinned_count(self) -> int:
        with self._lock:
            return len(self.host.pinned) + (
                len(self.disk.pinned) if self.disk is not None else 0)

    def deposit_pinned(self, seq_hash: int, k: np.ndarray,
                       v: np.ndarray) -> None:
        """Insert a block that MUST stick: pinned on arrival, and the
        insert raises :class:`OutOfTierSpace` instead of dropping when the
        host tier is wall-to-wall pinned (a demoted decode working set is
        state, not cache). Host-LRU spill of unpinned neighbors cascades
        to disk as usual."""
        with self._lock:
            self.host.pinned.add(seq_hash)
            try:
                spilled = self.host.put(seq_hash, k, v, required=True)
            except OutOfTierSpace:
                self.host.pinned.discard(seq_hash)
                raise
            if spilled is not None and self.disk is not None:
                self.disk.put(*spilled)
            self._set_block_gauges()
        self._fire_change()

    def hashes(self) -> Tuple[List[int], List[int]]:
        """Snapshot of the resident (host, disk) sequence hashes — the
        cluster registry publisher's record body."""
        with self._lock:
            return (list(self.host._slot_of),
                    list(self.disk._slot_of) if self.disk is not None
                    else [])

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "host_blocks": len(self.host),
                "disk_blocks": len(self.disk) if self.disk is not None
                else 0,
                "pinned_blocks": len(self.host.pinned) + (
                    len(self.disk.pinned) if self.disk is not None else 0),
                "hits": self.hits,
                "misses": self.misses,
            }

    def clear(self) -> None:
        """Drop every resident block (host and disk) and all pins. The
        model-swap cutover calls this: block hashes are content-only
        (tokens + lora salt, no model identity), so KV computed under the
        outgoing model would silently alias same-token prefixes of the
        incoming one if left resident."""
        with self._lock:
            for tier in (self.host, self.disk):
                if tier is None:
                    continue
                for h in list(tier._slot_of):
                    tier.pop(h)
            self._set_block_gauges()
        self._fire_change()

    def close(self) -> None:
        """Release the disk tier's spill files (engine shutdown)."""
        with self._lock:
            if self.disk is not None:
                self.disk.close()
                self.disk = None

    def _fire_change(self) -> None:
        cb = self.on_change
        if cb is not None:
            cb()
