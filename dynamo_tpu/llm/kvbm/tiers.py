"""Host-DRAM and disk KV cache tiers.

TPU VMs carry large host DRAM; offloaded KV pages park there (and optionally
spill to an mmap'd file) keyed by chained sequence hash, so a later request
with the same prefix re-uploads instead of recomputing. Capacity is
fixed-slot: each tier is one preallocated array of block slots + an LRU map,
so steady-state serving does zero host allocation.

Reference capability: the multi-tier KV manager design HBM->CPU->SSD
(docs/kv_cache_manager.md:5-15,39-71, lib/llm/src/kv/storage.rs pinned/system
tiers) — host-staged rather than GPUDirect, which is the TPU reality.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

import numpy as np


class _SlotCache:
    """Fixed-capacity LRU of KV blocks in one preallocated array pair."""

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...],
                 dtype, k_store: np.ndarray, v_store: np.ndarray):
        self.num_blocks = num_blocks
        self.block_shape = block_shape
        self.dtype = dtype
        self._k = k_store
        self._v = v_store
        self._slot_of: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()          # seq_hash -> slot, LRU order
        self._free = list(range(num_blocks - 1, -1, -1))

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._slot_of

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray
            ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Insert a block. Returns the evicted (hash, k, v) if the cache was
        full (caller may cascade it to the next tier), else None."""
        evicted = None
        if seq_hash in self._slot_of:
            self._slot_of.move_to_end(seq_hash)
            slot = self._slot_of[seq_hash]
        elif self._free:
            slot = self._free.pop()
            self._slot_of[seq_hash] = slot
        else:
            old_hash, slot = self._slot_of.popitem(last=False)  # LRU out
            evicted = (old_hash, self._k[slot].copy(), self._v[slot].copy())
            self._slot_of[seq_hash] = slot
        self._k[slot] = k
        self._v[slot] = v
        return evicted

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        slot = self._slot_of.get(seq_hash)
        if slot is None:
            return None
        self._slot_of.move_to_end(seq_hash)
        return self._k[slot], self._v[slot]

    def pop(self, seq_hash: int) -> None:
        slot = self._slot_of.pop(seq_hash, None)
        if slot is not None:
            self._free.append(slot)


class HostKvTier(_SlotCache):
    """Host-DRAM tier: [n_blocks, L, Hkv, page, Dh] preallocated numpy."""

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...], dtype):
        shape = (num_blocks, *block_shape)
        super().__init__(num_blocks, block_shape, dtype,
                         np.zeros(shape, dtype), np.zeros(shape, dtype))


class DiskKvTier(_SlotCache):
    """mmap-backed spill tier (the reference's SSD tier)."""

    def __init__(self, num_blocks: int, block_shape: Tuple[int, ...], dtype,
                 path: str):
        shape = (num_blocks, *block_shape)
        k = np.memmap(path + ".k", dtype=dtype, mode="w+", shape=shape)
        v = np.memmap(path + ".v", dtype=dtype, mode="w+", shape=shape)
        super().__init__(num_blocks, block_shape, dtype, k, v)


class TieredKvCache:
    """Host tier with optional disk spill, one lookup/offload surface.

    ``offload`` inserts at the host tier and cascades host-LRU evictions to
    disk; ``lookup`` checks host then disk (promoting disk hits back to
    host). All arrays are [L, Hkv, page, Dh] per block.
    """

    def __init__(self, host: HostKvTier, disk: Optional[DiskKvTier] = None):
        self.host = host
        self.disk = disk
        self.hits = 0
        self.misses = 0

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self.host or (
            self.disk is not None and seq_hash in self.disk)

    def offload(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        spilled = self.host.put(seq_hash, k, v)
        if spilled is not None and self.disk is not None:
            self.disk.put(*spilled)

    def lookup(self, seq_hash: int
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        got = self.host.get(seq_hash)
        if got is None and self.disk is not None:
            got = self.disk.get(seq_hash)
            if got is not None:       # promote to host (may spill another)
                k, v = got[0].copy(), got[1].copy()
                self.disk.pop(seq_hash)
                self.offload(seq_hash, k, v)
                got = (k, v)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def stats(self) -> Dict[str, int]:
        return {
            "host_blocks": len(self.host),
            "disk_blocks": len(self.disk) if self.disk is not None else 0,
            "hits": self.hits,
            "misses": self.misses,
        }
