"""KV block manager: device reuse pool, tiered host/disk cache, transfers."""
