"""Device KV block pool: allocation, sequence-hash reuse, LRU eviction.

The engine's KV pages live in one flat device array; this pool owns the
*states* of those pages:

- ``free``      — unclaimed, contents meaningless.
- ``leased``    — held by >= 1 live sequence (refcounted; a full, sealed
                  block may be shared read-only by several sequences that
                  matched the same prefix).
- ``reusable``  — no live owner, but holds a sealed block addressed by its
                  chained sequence hash; claimable by prefix match, evicted
                  (lowest priority, then least recently used) when the free
                  list runs dry. Eviction fires ``on_evict`` first so a
                  tiered cache can offload the page to host DRAM.

Reference capability: the AvailableBlocks reuse actor + RAII block pool +
reserved-block registry (lib/llm/src/kv/reuse.rs:50-150,
lib/runtime/src/utils/pool.rs:111-241, lib/llm/src/kv/reserved.rs:15-60) —
re-designed as a single synchronous state machine because the JAX engine
drives all KV bookkeeping from one engine thread (no actor mailboxes needed).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


class OutOfBlocks(RuntimeError):
    pass


@dataclass
class _Block:
    page: int
    state: str = "free"                  # free | leased | reusable
    seq_hash: Optional[int] = None       # set once sealed
    registered: bool = False             # seq_hash -> page map entry is ours
    refs: int = 0
    priority: int = 0
    last_used: int = 0                   # logical clock (deterministic LRU)


class DeviceBlockPool:
    """Page-granularity state machine over the engine's device KV pool.

    Page 0 is reserved as the scratch page (masked lanes write there).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is scratch)")
        self.num_pages = num_pages
        self._blocks: Dict[int, _Block] = {
            p: _Block(p) for p in range(1, num_pages)}
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._by_hash: Dict[int, int] = {}      # seq_hash -> page
        self._clock = 0
        # (priority, last_used, page) lazy-deleted eviction heap
        self._evict_heap: List[Tuple[int, int, int]] = []
        # incremental count of state == "reusable" blocks: allocatable is
        # probed per page-allocation, an O(num_pages) scan there is the
        # scheduler's hottest host cost
        self._n_reusable = 0
        # offload hook: called with (seq_hash, page) BEFORE the page is
        # recycled; the tiered cache copies it out to host DRAM here
        self.on_evict: Optional[Callable[[int, int], None]] = None

    # ------------------------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def reusable_count(self) -> int:
        return self._n_reusable

    @property
    def allocatable(self) -> int:
        """Pages a new lease could obtain (free + evictable)."""
        return self.free_count + self.reusable_count

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------------
    def lease_new(self) -> int:
        """Claim a page for writing (refs=1). Evicts LRU reusable on
        pressure; raises OutOfBlocks when nothing is left."""
        if self._free:
            page = self._free.pop()
        else:
            page = self._evict_one()
        b = self._blocks[page]
        b.state = "leased"
        b.seq_hash = None
        b.registered = False
        b.refs = 1
        b.last_used = self._tick()
        return page

    def _evict_one(self) -> int:
        while self._evict_heap:
            prio, ts, page = heapq.heappop(self._evict_heap)
            b = self._blocks[page]
            if b.state != "reusable" or (b.priority, b.last_used) != (prio, ts):
                continue  # stale heap entry
            if self.on_evict is not None and b.seq_hash is not None:
                self.on_evict(b.seq_hash, page)
            # decrement only after the offload hook: a hook exception must
            # leave the counter consistent with the unchanged state
            self._n_reusable -= 1
            self._unregister(b)
            return page
        raise OutOfBlocks("no free or reusable pages left")

    def _unregister(self, b: _Block) -> None:
        if b.registered and self._by_hash.get(b.seq_hash) == b.page:
            del self._by_hash[b.seq_hash]
        b.registered = False
        b.seq_hash = None

    # ------------------------------------------------------------------
    def seal(self, page: int, seq_hash: int, priority: int = 0) -> bool:
        """Mark a leased page as holding the full block ``seq_hash``; it
        becomes discoverable for prefix matching (first page wins if the
        same content is sealed twice). Returns True iff this page newly
        registered the hash — the signal to publish a router "stored" event
        (exactly one stored per registered block balances the one "removed"
        fired at eviction)."""
        b = self._blocks[page]
        assert b.state == "leased", f"seal on {b.state} page {page}"
        b.seq_hash = seq_hash
        b.priority = priority
        if seq_hash not in self._by_hash:
            self._by_hash[seq_hash] = page
            b.registered = True
            return True
        return False

    def contains(self, seq_hash: int) -> bool:
        """Non-claiming membership probe (disagg router's prefix-hit input)."""
        return seq_hash in self._by_hash

    def match(self, seq_hash: int) -> Optional[int]:
        """Claim the sealed block for ``seq_hash`` if present: a reusable
        block is re-leased; a live shared block gains a reference."""
        page = self._by_hash.get(seq_hash)
        if page is None:
            return None
        b = self._blocks[page]
        b.last_used = self._tick()
        if b.state == "reusable":
            b.state = "leased"
            self._n_reusable -= 1
            b.refs = 1
        else:
            b.refs += 1
        return page

    def release(self, page: int) -> None:
        """Drop one reference. At zero refs a sealed+registered block parks
        as reusable; anything else returns to the free list."""
        b = self._blocks[page]
        assert b.state == "leased" and b.refs > 0, \
            f"release on {b.state}/{b.refs} page {page}"
        b.refs -= 1
        if b.refs:
            return
        if b.seq_hash is not None and b.registered:
            b.state = "reusable"
            self._n_reusable += 1
            b.last_used = self._tick()
            heapq.heappush(self._evict_heap, (b.priority, b.last_used, b.page))
        else:
            b.state = "free"
            self._unregister(b)
            self._free.append(page)

    # ------------------------------------------------------------------
    def flush_reusable(self) -> int:
        """Evict every reusable block (offloading via on_evict); returns the
        number flushed. Used by cache-clear admin ops and tests."""
        n = 0
        while self.reusable_count:
            page = self._evict_one()
            b = self._blocks[page]
            b.state = "free"
            self._free.append(page)
            n += 1
        return n
