"""Pipeline assembly: compose preprocessor + backend + core engine into
OpenAI-level engines that consume request objects and stream chunk dicts.

This is the local (in-process) analogue of the reference's pipeline graph
ServiceFrontend → OpenAIPreprocessor → Backend → ServiceBackend(engine)
(reference: launch/dynamo-run/src/input/http.rs:86, lib/runtime/src/pipeline.rs).
"""

from __future__ import annotations

import contextlib
from typing import Any, AsyncIterator, Dict, List, Optional

from ..runtime.engine import AsyncEngine, Context
from ..utils.tracing import get_tracer
from .backend import Backend
from .engines import EchoFullEngine
from .model_card import ModelDeploymentCard
from .preprocessor import Preprocessor
from .protocols.common import BackendInput, EngineOutput, FinishReason
from .protocols.openai import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    CompletionRequest,
    ProtocolError,
    usage_dict,
)
from .tokenizer import load_tokenizer


class OpenAIChatEngine(AsyncEngine[ChatCompletionRequest, Dict[str, Any]]):
    """ChatCompletionRequest -> stream of chat.completion.chunk dicts."""

    def __init__(self, card: ModelDeploymentCard,
                 core_engine: AsyncEngine[BackendInput, EngineOutput]):
        self.card = card
        self.preprocessor = Preprocessor(card)
        self.backend = Backend(core_engine, self.preprocessor.tokenizer)

    async def generate(self, request: ChatCompletionRequest,
                       context: Context) -> AsyncIterator[Dict[str, Any]]:
        from .tools import ToolCallingMatcher, normalize_tool_choice

        with get_tracer().span("preprocess", trace_id=context.id) as psp:
            pre = self.preprocessor.preprocess_chat(request)
            if psp is not None:
                psp.attrs["prompt_tokens"] = len(pre.backend_input.token_ids)
        gen = ChatDeltaGenerator(request.model, request_id=f"chatcmpl-{context.id[:24]}")
        prompt_tokens = len(pre.backend_input.token_ids)
        completion_tokens = 0
        mode, forced = normalize_tool_choice(request.tool_choice, request.tools)
        matcher = ToolCallingMatcher(mode, forced) if mode != "none" else None
        # With tools active the text is buffered: a tool call can only be
        # recognized on the complete message (reference tools.rs matches whole
        # messages), and streaming content that later turns out to be a tool
        # call would hand the client both.
        buffered: List[str] = []
        buffered_lp: List[Dict[str, Any]] = []
        if pre.annotations:
            yield {"event": "annotations", "data": pre.annotations}
        # aclosing: the early return on finish must close the backend (and
        # transitively the core engine) generator immediately
        stream_cm = contextlib.aclosing(
            self.backend.generate(pre.backend_input, context))
        async with stream_cm as stream:
            async for out in stream:
                completion_tokens += len(out.token_ids)
                # with logprobs on, even a token with no visible text (partial
                # UTF-8, stop-jail) must carry its logprob entry downstream
                want_lp = bool(request.logprobs and out.logprobs)
                if out.text or (want_lp and out.token_ids):
                    if matcher is not None:
                        if out.text:
                            buffered.append(out.text)
                        if want_lp:
                            buffered_lp.extend(
                                self._chat_logprobs(out)["content"])
                    else:
                        chunk = gen.text_chunk(out.text or "", out.index)
                        if want_lp:
                            chunk["choices"][0]["logprobs"] = \
                                self._chat_logprobs(out)
                        yield chunk
                if out.finish_reason is not None:
                    finish_override = None
                    if matcher is not None:
                        complete = out.finish_reason in (FinishReason.STOP,
                                                         FinishReason.EOS)
                        try:
                            calls = matcher.get_calls("".join(buffered),
                                                      complete)
                        except ProtocolError as e:
                            # streaming has begun (annotation/role chunks may
                            # be committed): surface as a terminal in-stream
                            # error, not an exception after a 200 header —
                            # parse-time validation already gave clean 400s
                            yield {"error": {"message": str(e),
                                             "type": "invalid_request_error"}}
                            return
                        if calls:
                            yield gen.tool_calls_chunk(calls, out.index)
                            finish_override = "tool_calls"
                        elif buffered:
                            chunk = gen.text_chunk("".join(buffered), out.index)
                            if buffered_lp:
                                chunk["choices"][0]["logprobs"] = \
                                    {"content": buffered_lp}
                            yield chunk
                    yield gen.finish_chunk(
                        out.finish_reason, out.index,
                        usage=usage_dict(prompt_tokens, completion_tokens),
                        finish_override=finish_override,
                    )
                    return

    def _chat_logprobs(self, out: EngineOutput) -> Dict[str, Any]:
        """OpenAI chat logprobs delta: one content entry per token."""
        content = []
        for tid, lp_map in zip(out.token_ids, out.logprobs or []):
            lp = next(iter(lp_map.values())) if lp_map else 0.0
            tok = self.preprocessor.tokenizer.decode([tid])
            content.append({"token": tok, "logprob": lp,
                            "bytes": list(tok.encode())})
        return {"content": content}


class OpenAICompletionEngine(AsyncEngine[CompletionRequest, Dict[str, Any]]):
    """CompletionRequest -> stream of text_completion chunk dicts."""

    def __init__(self, card: ModelDeploymentCard,
                 core_engine: AsyncEngine[BackendInput, EngineOutput]):
        self.card = card
        self.preprocessor = Preprocessor(card)
        self.backend = Backend(core_engine, self.preprocessor.tokenizer)

    async def generate(self, request: CompletionRequest,
                       context: Context) -> AsyncIterator[Dict[str, Any]]:
        with get_tracer().span("preprocess", trace_id=context.id) as psp:
            pre = self.preprocessor.preprocess_completion(request)
            if psp is not None:
                psp.attrs["prompt_tokens"] = len(pre.backend_input.token_ids)
        gen = CompletionDeltaGenerator(request.model, request_id=f"cmpl-{context.id[:24]}")
        prompt_tokens = len(pre.backend_input.token_ids)
        completion_tokens = 0
        if request.echo and pre.formatted_prompt:
            yield gen.text_chunk(pre.formatted_prompt)
        async with contextlib.aclosing(
                self.backend.generate(pre.backend_input,
                                      context)) as stream:
            async for out in stream:
                completion_tokens += len(out.token_ids)
                fin = out.finish_reason.to_openai() if out.finish_reason else None
                want_lp = request.logprobs is not None and bool(out.logprobs)
                if out.text or fin or (want_lp and out.token_ids):
                    lp = None
                    if want_lp:
                        toks = [self.preprocessor.tokenizer.decode([t])
                                for t in out.token_ids]
                        lp = {"tokens": toks,
                              "token_logprobs": [
                                  next(iter(m.values())) if m else 0.0
                                  for m in out.logprobs],
                              "top_logprobs": None,
                              "text_offset": []}
                    chunk = gen.text_chunk(out.text or "", out.index, fin,
                                           logprobs=lp)
                    if fin:
                        chunk["usage"] = usage_dict(prompt_tokens, completion_tokens)
                    yield chunk
                if fin:
                    return


class FullEngineAdapter(AsyncEngine):
    """Adapts a text-level full engine (streams plain text, e.g. EchoFullEngine
    or a pystr user engine) to OpenAI chunk dicts for both chat and
    completions. With a ``tokenizer``, usage counts are derived from the
    request/response text (full engines have no token stream of their own)."""

    def __init__(self, model: str, engine: AsyncEngine, kind: str = "chat",
                 tokenizer=None):
        self.model = model
        self.engine = engine
        self.kind = kind
        self.tokenizer = tokenizer

    async def generate(self, request, context: Context):
        if self.kind == "chat":
            gen = ChatDeltaGenerator(self.model, request_id=f"chatcmpl-{context.id[:24]}")
        else:
            gen = CompletionDeltaGenerator(self.model, request_id=f"cmpl-{context.id[:24]}")
        parts = []
        async with contextlib.aclosing(
                self.engine.generate(request, context)) as stream:
            async for text in stream:
                if self.tokenizer is not None:
                    parts.append(text)
                yield gen.text_chunk(text)
        usage = None
        if self.tokenizer is not None:
            if self.kind == "chat":
                from .preprocessor import content_text

                prompt_text = "".join(content_text(m.get("content"))
                                      for m in request.messages)
            else:
                prompt_text = request.prompt if isinstance(request.prompt, str) else ""
            usage = usage_dict(len(self.tokenizer.encode(prompt_text)),
                               len(self.tokenizer.encode("".join(parts))))
        chunk = gen.finish_chunk(FinishReason.STOP)
        if usage is not None:
            chunk["usage"] = usage
        yield chunk


def build_chat_engine(card: ModelDeploymentCard, kind: str,
                      core_engine: Optional[AsyncEngine] = None) -> AsyncEngine:
    """``kind``: 'echo_core' | 'echo_full' | 'core' (bring your own core engine)."""
    from .engines import EchoCoreEngine

    if kind == "echo_full":
        return FullEngineAdapter(card.name, EchoFullEngine(), "chat")
    if kind == "echo_core":
        return OpenAIChatEngine(card, EchoCoreEngine())
    if kind == "core":
        assert core_engine is not None
        return OpenAIChatEngine(card, core_engine)
    raise ValueError(f"unknown engine kind {kind!r}")


def build_completion_engine(card: ModelDeploymentCard, kind: str,
                            core_engine: Optional[AsyncEngine] = None) -> AsyncEngine:
    from .engines import EchoCoreEngine

    if kind == "echo_full":
        return FullEngineAdapter(card.name, EchoFullEngine(), "completion")
    if kind == "echo_core":
        return OpenAICompletionEngine(card, EchoCoreEngine())
    if kind == "core":
        assert core_engine is not None
        return OpenAICompletionEngine(card, core_engine)
    raise ValueError(f"unknown engine kind {kind!r}")
