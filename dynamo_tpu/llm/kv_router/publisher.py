"""Worker-side KV event publishing.

``KvEventPublisher`` bridges the engine's page-pool hooks (block sealed /
blocks freed) to the event plane without ever stalling the engine step loop:
events go into an unbounded in-memory queue; a background task drains and
publishes. The transport is pluggable (in-process bus for tests, the
distributed runtime's event plane in deployment).

Reference capability: lib/llm/src/kv_router/publisher.rs:32-60 (mpsc ->
NATS), and the C-ABI publish path (lib/bindings/c) that engines call.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Awaitable, Callable, List, Optional

log = logging.getLogger("dynamo_tpu.kv_events")

from ..tokens import TokenBlock
from .protocols import (
    KvCacheEvent,
    KvRemovedEvent,
    KvStoredEvent,
    RouterEvent,
    StoredBlock,
)

PublishFn = Callable[[str, dict], Awaitable[None]]


class KvEventPublisher:
    """Thread-safe producer, asyncio consumer.

    The engine thread calls ``block_stored``/``blocks_removed`` (cheap, no IO);
    ``run`` drains and hands RouterEvents to the transport publish function.
    """

    def __init__(self, worker_id: int, publish: PublishFn,
                 subject: str = "kv_events"):
        self.worker_id = worker_id
        self.subject = subject
        self._publish = publish
        self._event_id = 0
        self._buf: List[KvCacheEvent] = []
        self._lock = threading.Lock()
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.published = 0

    # -- engine-thread side (hooks for PagePool) ------------------------
    def block_stored(self, seq_id: str, block: TokenBlock, page: int,
                     lora_id: int = 0) -> None:
        ev = KvCacheEvent(
            event_id=self._next_id(),
            stored=KvStoredEvent(
                blocks=[StoredBlock(block_hash=block.sequence_hash,
                                    tokens_hash=block.block_hash)],
                parent_hash=block.parent_sequence_hash,
                lora_id=lora_id,
            ))
        self._push(ev)

    def blocks_removed(self, seq_hashes: List[int]) -> None:
        """Fired when sealed blocks are EVICTED from the device pool (with
        block reuse, sequence release keeps blocks matchable — only eviction
        removes them from this worker's prefix cache)."""
        ev = KvCacheEvent(
            event_id=self._next_id(),
            removed=KvRemovedEvent(block_hashes=list(seq_hashes)))
        self._push(ev)

    def _next_id(self) -> int:
        with self._lock:
            self._event_id += 1
            return self._event_id

    def _push(self, ev: KvCacheEvent) -> None:
        with self._lock:
            self._buf.append(ev)
        wake, loop = self._wake, self._loop
        if wake is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(wake.set)
            except RuntimeError:
                pass  # loop closed; the 0.2s poll in _run still drains

    # -- asyncio side ---------------------------------------------------
    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        self._task = asyncio.create_task(self._run(), name="kv-event-pub")

    async def stop(self) -> None:
        if self._task:
            await self.flush()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def flush(self) -> None:
        await self._drain()

    async def _drain(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        for i, ev in enumerate(batch):
            try:
                await self._publish(
                    self.subject,
                    RouterEvent(self.worker_id, ev).to_dict())
            except Exception:
                # transport outage (e.g. store reconnecting): put the
                # unsent tail back IN ORDER and retry on a later beat —
                # the router's index depends on event order per worker
                with self._lock:
                    self._buf = batch[i:] + self._buf
                raise
            self.published += 1

    async def _run(self) -> None:
        assert self._wake is not None
        while True:
            try:
                await self._drain()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - keep the pump alive
                log.debug("kv event publish deferred (%s); retrying",
                          e)
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=0.2)
                self._wake.clear()
            except asyncio.TimeoutError:
                pass
