"""KvIndexer: the router-side global prefix index.

A worker-aware radix/prefix tree over chained block hashes: each node is one
KV block (keyed by its sequence hash) and records which workers currently hold
it. ``find_matches`` walks a request's block-hash chain from the root and
scores per-worker overlap. An asyncio actor task owns all mutation (events in
via queue), so no locks — the same single-owner discipline as the reference.

Reference capability: lib/llm/src/kv_router/indexer.rs:172-438 (RadixTree,
OverlapScores, apply_event, remove_worker, expiry) and the sharded variant
(indexer.rs:670-796).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..tokens import compute_seq_hashes
from .protocols import KvCacheEvent, RouterEvent


@dataclass
class OverlapScores:
    """worker_id -> number of consecutive prefix blocks already cached."""

    scores: Dict[int, int] = field(default_factory=dict)
    # frequency of each matched block across all workers (optional telemetry)
    frequencies: List[int] = field(default_factory=list)

    def best(self) -> Tuple[Optional[int], int]:
        if not self.scores:
            return None, 0
        w = max(self.scores, key=lambda k: self.scores[k])
        return w, self.scores[w]


class _Node:
    __slots__ = ("hash", "parent", "children", "workers", "last_touch")

    def __init__(self, h: int, parent: Optional["_Node"]):
        self.hash = h
        self.parent = parent
        self.children: Dict[int, _Node] = {}
        # worker_id -> refcount: the same prefix block can be stored by
        # several concurrent sequences on one worker; a removal by one must
        # not revoke the worker's claim while others still hold it
        self.workers: Dict[int, int] = {}
        self.last_touch = time.monotonic()


class RadixTree:
    """Single-threaded prefix tree over sequence hashes."""

    def __init__(self):
        self._root = _Node(0, None)
        self._nodes: Dict[int, _Node] = {}          # seq_hash -> node
        self._worker_blocks: Dict[int, Set[int]] = {}  # worker -> seq hashes

    # -- mutation ------------------------------------------------------
    def apply_event(self, ev: RouterEvent) -> None:
        w = ev.worker_id
        e = ev.event
        if e.stored is not None:
            parent = (self._nodes.get(e.stored.parent_hash, self._root)
                      if e.stored.parent_hash is not None else self._root)
            for blk in e.stored.blocks:
                node = self._nodes.get(blk.block_hash)
                if node is None:
                    node = _Node(blk.block_hash, parent)
                    parent.children[blk.block_hash] = node
                    self._nodes[blk.block_hash] = node
                node.workers[w] = node.workers.get(w, 0) + 1
                node.last_touch = time.monotonic()
                self._worker_blocks.setdefault(w, set()).add(blk.block_hash)
                parent = node
        if e.removed is not None:
            for h in e.removed.block_hashes:
                node = self._nodes.get(h)
                if node is None:
                    continue
                n = node.workers.get(w, 0) - 1
                if n > 0:
                    node.workers[w] = n
                else:
                    node.workers.pop(w, None)
                    wb = self._worker_blocks.get(w)
                    if wb:
                        wb.discard(h)
                self._maybe_prune(node)

    def remove_worker(self, worker_id: int) -> None:
        for h in self._worker_blocks.pop(worker_id, set()):
            node = self._nodes.get(h)
            if node is not None:
                node.workers.pop(worker_id, None)
                self._maybe_prune(node)

    def _maybe_prune(self, node: _Node) -> None:
        while (node is not self._root and not node.workers
               and not node.children):
            parent = node.parent
            if parent is not None:
                parent.children.pop(node.hash, None)
            self._nodes.pop(node.hash, None)
            if parent is None or parent is self._root:
                break
            node = parent

    def expire_older_than(self, max_age_s: float) -> int:
        """Drop leaf blocks untouched for max_age_s (frequency/TTL expiry)."""
        cutoff = time.monotonic() - max_age_s
        stale = [n for n in self._nodes.values()
                 if not n.children and n.last_touch < cutoff]
        for n in stale:
            for w in list(n.workers):
                self._worker_blocks.get(w, set()).discard(n.hash)
            n.workers.clear()
            self._maybe_prune(n)
        return len(stale)

    # -- queries -------------------------------------------------------
    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Walk the chain from the root; a worker's score is the count of
        consecutive blocks it holds from the start."""
        out = OverlapScores()
        node = self._root
        active: Optional[Set[int]] = None
        for h in seq_hashes:
            child = node.children.get(h)
            if child is None:
                break
            child.last_touch = time.monotonic()
            holders = set(child.workers)
            active = holders if active is None else active & holders
            if not active:
                break
            for w in active:
                out.scores[w] = out.scores.get(w, 0) + 1
            out.frequencies.append(len(holders))
            node = child
        return out

    @property
    def num_blocks(self) -> int:
        return len(self._nodes)

    def workers(self) -> Set[int]:
        return set(self._worker_blocks)


class KvIndexer:
    """Asyncio actor owning a RadixTree; events in via queue, queries are
    cheap reads executed on the loop (single-threaded => consistent)."""

    def __init__(self, block_size: int, expiry_s: Optional[float] = None):
        self.block_size = block_size
        self.tree = RadixTree()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._expiry_s = expiry_s
        self.events_applied = 0

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="kv-indexer")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        last_expiry = time.monotonic()
        while True:
            try:
                ev = await asyncio.wait_for(self._queue.get(), timeout=1.0)
                self.tree.apply_event(ev)
                self.events_applied += 1
            except asyncio.TimeoutError:
                pass
            if self._expiry_s and time.monotonic() - last_expiry > self._expiry_s:
                self.tree.expire_older_than(self._expiry_s)
                last_expiry = time.monotonic()

    # -- producer side -------------------------------------------------
    def apply(self, ev: RouterEvent) -> None:
        """Enqueue an event (thread-safe only from the loop thread)."""
        self._queue.put_nowait(ev)

    def apply_sync(self, ev: RouterEvent) -> None:
        """Apply immediately (tests / single-threaded callers)."""
        self.tree.apply_event(ev)
        self.events_applied += 1

    def remove_worker(self, worker_id: int) -> None:
        self.tree.remove_worker(worker_id)

    # -- queries --------------------------------------------------------
    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        return self.tree.find_matches(seq_hashes)

    def find_matches_for_tokens(self, tokens: Sequence[int],
                                lora_id: int = 0) -> OverlapScores:
        """Match under an adapter: the query chain is salted exactly like
        the publishers' (same tokens + different lora_id → zero overlap)."""
        return self.find_matches(
            compute_seq_hashes(tokens, self.block_size, lora_id=lora_id))


class KvIndexerSharded:
    """Partition workers across N independent trees — bounds per-tree size
    and lets event application parallelize across actors."""

    def __init__(self, block_size: int, num_shards: int = 4):
        self.block_size = block_size
        self.shards = [KvIndexer(block_size) for _ in range(num_shards)]

    def _shard(self, worker_id: int) -> KvIndexer:
        return self.shards[worker_id % len(self.shards)]

    def apply_sync(self, ev: RouterEvent) -> None:
        self._shard(ev.worker_id).apply_sync(ev)

    def remove_worker(self, worker_id: int) -> None:
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        out = OverlapScores()
        for sh in self.shards:
            part = sh.find_matches(seq_hashes)
            out.scores.update(part.scores)
        return out

    def find_matches_for_tokens(self, tokens: Sequence[int],
                                lora_id: int = 0) -> OverlapScores:
        return self.find_matches(
            compute_seq_hashes(tokens, self.block_size, lora_id=lora_id))
