"""ctypes binding for the native KV-event publisher (native/kv_publisher.cpp).

The C ABI is the engine-integration surface the reference exposes from
lib/bindings/c (dynamo_llm_init / dynamo_kv_event_publish_stored /
dynamo_kv_event_publish_removed / dynamo_llm_shutdown): native engines link
it and report KV block store/evict without touching Python. Events arrive on
the ``{ns}.{component}.kv_events`` subject as RouterEvent JSON — exactly what
:class:`..kv_router.indexer.KvIndexer` consumes from the Python publisher.

The underlying library holds ONE process-global connection (matching the
reference's C binding); instantiate one publisher per process.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple


def _load_lib() -> ctypes.CDLL:
    from ...runtime.store_server import build_native

    path = f"{build_native('build/libdynamo_kv.so')}/libdynamo_kv.so"
    lib = ctypes.CDLL(path)
    lib.dynamo_llm_init.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int64]
    lib.dynamo_llm_init.restype = ctypes.c_int
    lib.dynamo_kv_event_publish_stored.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t, ctypes.c_int,
        ctypes.c_uint64]
    lib.dynamo_kv_event_publish_stored.restype = ctypes.c_int
    # the v2 symbol (adds lora_id) may be absent from a prebuilt library
    # built before it existed — probe instead of binding unconditionally so
    # init doesn't die on a raw ctypes AttributeError (ADVICE r4); callers
    # fall back to v1 when lora_id==0 and get a clear rebuild error otherwise
    if hasattr(lib, "dynamo_kv_event_publish_stored_v2"):
        lib.dynamo_kv_event_publish_stored_v2.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_uint64]
        lib.dynamo_kv_event_publish_stored_v2.restype = ctypes.c_int
    lib.dynamo_kv_event_publish_removed.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
    lib.dynamo_kv_event_publish_removed.restype = ctypes.c_int
    lib.dynamo_llm_shutdown.argtypes = []
    lib.dynamo_llm_shutdown.restype = ctypes.c_int
    return lib


class NativeKvPublisher:
    """Engine-side KV event publisher backed by the C library.

    Publishes on a background native thread — calls here never block on the
    network, mirroring the reference's mpsc->publisher design.
    """

    def __init__(self, host: str, port: int, namespace: str, component: str,
                 worker_id: int):
        self._lib = _load_lib()
        # probe once: ctypes does not cache symbol MISSES, so a per-call
        # hasattr on the hot path would dlsym+raise on every publish
        self._has_v2 = hasattr(self._lib, "dynamo_kv_event_publish_stored_v2")
        rc = self._lib.dynamo_llm_init(
            host.encode(), port, namespace.encode(), component.encode(),
            worker_id)
        if rc != 0:
            raise RuntimeError(
                f"dynamo_llm_init failed (rc={rc}): store at {host}:{port} "
                "unreachable or publisher already initialized in-process")
        self._event_id = 0

    def _next_id(self) -> int:
        self._event_id += 1
        return self._event_id

    def publish_stored(self, blocks: Sequence[Tuple[int, int]],
                       parent_hash: Optional[int] = None,
                       lora_id: int = 0) -> int:
        """blocks = [(block_hash a.k.a. sequence hash, tokens_hash), ...].

        The hashes must already be lora-salted at the chain root (see
        tokens.lora_chain_root); ``lora_id`` rides the wire for parity with
        the reference C ABI and consumer-side auditing."""
        n = len(blocks)
        bh = (ctypes.c_uint64 * n)(*[b for b, _ in blocks])
        th = (ctypes.c_uint64 * n)(*[t for _, t in blocks])
        eid = self._next_id()
        if self._has_v2:
            rc = self._lib.dynamo_kv_event_publish_stored_v2(
                eid, bh, th, n, int(parent_hash is not None),
                parent_hash or 0, lora_id)
        elif lora_id == 0:
            # v1 carries no lora_id field; 0 (= base model) is its implied
            # value, so the fallback is lossless
            rc = self._lib.dynamo_kv_event_publish_stored(
                eid, bh, th, n, int(parent_hash is not None),
                parent_hash or 0)
        else:
            raise RuntimeError(
                "this build of libdynamo_kv.so predates lora_id support; "
                "rebuild it (make -C native build/libdynamo_kv.so) to "
                "publish lora-tagged KV events")
        if rc != 0:
            raise RuntimeError("publisher not initialized")
        return eid

    def publish_removed(self, block_hashes: List[int]) -> int:
        n = len(block_hashes)
        bh = (ctypes.c_uint64 * n)(*block_hashes)
        eid = self._next_id()
        rc = self._lib.dynamo_kv_event_publish_removed(eid, bh, n)
        if rc != 0:
            raise RuntimeError("publisher not initialized")
        return eid

    def shutdown(self) -> None:
        self._lib.dynamo_llm_shutdown()
