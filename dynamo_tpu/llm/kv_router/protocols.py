"""KV routing wire protocol: cache events and worker load metrics.

Every worker publishes a ``RouterEvent`` when its engine stores or evicts a
full KV block; routers fold these into a global prefix index. Hashes are the
xxh3 block/sequence hashes from ``dynamo_tpu.llm.tokens``.

Reference capability: lib/llm/src/kv_router/protocols.rs:42-121 (KvCacheEvent
Stored/Removed, ForwardPassMetrics).
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional

KV_EVENT_SUBJECT = "kv_events"
KV_HIT_RATE_SUBJECT = "kv-hit-rate"
LOAD_METRICS_ENDPOINT = "load_metrics"


@dataclass
class StoredBlock:
    block_hash: int      # chained sequence hash (globally identifying prefix)
    tokens_hash: int     # content-only hash of the block's tokens


@dataclass
class KvStoredEvent:
    blocks: List[StoredBlock]
    parent_hash: Optional[int] = None  # sequence hash of the preceding block
    # Adapter the blocks were computed under (0 = base model). The hash
    # chain itself is already lora-salted at its root (tokens.py
    # lora_chain_root) so same-tokens/different-adapter cannot alias; the
    # wire field preserves C-ABI parity (ref lib/bindings/c lib.rs:253-283)
    # and lets consumers audit or partition by adapter.
    lora_id: int = 0


@dataclass
class KvRemovedEvent:
    block_hashes: List[int]


@dataclass
class KvCacheEvent:
    event_id: int
    stored: Optional[KvStoredEvent] = None
    removed: Optional[KvRemovedEvent] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"event_id": self.event_id}
        if self.stored is not None:
            d["stored"] = {
                "parent_hash": self.stored.parent_hash,
                "blocks": [asdict(b) for b in self.stored.blocks],
            }
            if self.stored.lora_id:
                d["stored"]["lora_id"] = self.stored.lora_id
        if self.removed is not None:
            d["removed"] = {"block_hashes": self.removed.block_hashes}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvCacheEvent":
        stored = None
        removed = None
        if "stored" in d and d["stored"] is not None:
            stored = KvStoredEvent(
                blocks=[StoredBlock(**b) for b in d["stored"]["blocks"]],
                parent_hash=d["stored"].get("parent_hash"),
                lora_id=int(d["stored"].get("lora_id", 0)),
            )
        if "removed" in d and d["removed"] is not None:
            removed = KvRemovedEvent(block_hashes=list(d["removed"]["block_hashes"]))
        return cls(event_id=d["event_id"], stored=stored, removed=removed)


@dataclass
class RouterEvent:
    worker_id: int
    event: KvCacheEvent

    def to_dict(self) -> Dict[str, Any]:
        return {"worker_id": self.worker_id, "event": self.event.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RouterEvent":
        return cls(worker_id=d["worker_id"],
                   event=KvCacheEvent.from_dict(d["event"]))


@dataclass
class ForwardPassMetrics:
    """Per-worker capacity snapshot, scraped/aggregated by routers."""

    request_active_slots: float = 0.0
    request_total_slots: float = 0.0
    kv_active_blocks: float = 0.0
    kv_total_blocks: float = 0.0
    num_requests_waiting: float = 0.0
    gpu_cache_usage_perc: float = 0.0   # kept name for API familiarity
    gpu_prefix_cache_hit_rate: float = 0.0
    # speculative decoding: drafted-token acceptance rate (0 = spec off or
    # nothing proposed); lets the planner/router see whether a worker's
    # decode throughput is spec-amplified
    spec_accept_rate: float = 0.0
    # goodput (utils/roofline.py): analytic MFU / memory-bandwidth
    # utilization / achieved GB/s over the engine's recent dispatch window
    # — "how close to the hardware" per worker, scraped by the aggregator,
    # planner and dyntop alongside the capacity numbers above
    mfu: float = 0.0
    mbu: float = 0.0
    hbm_gbps: float = 0.0
    # byte-honest KV residency (llm/kvpage/): total KV working set in
    # bytes (device pool in use + the paged lane's pinned host blocks)
    # against device+host capacity. Slots price every request the same;
    # these price a 128k context at its true footprint, so the router's
    # bytes-pressure term steers work away from a worker whose tiers one
    # long request is consuming (0/0 on engines that predate the fields)
    kv_resident_bytes: float = 0.0
    kv_capacity_bytes: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ForwardPassMetrics":
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})

    @property
    def cache_usage(self) -> float:
        if self.kv_total_blocks:
            return self.kv_active_blocks / self.kv_total_blocks
        return self.gpu_cache_usage_perc


@dataclass
class KVHitRateEvent:
    worker_id: int
    isl_blocks: int       # input sequence length in blocks
    overlap_blocks: int   # blocks served from prefix cache

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KVHitRateEvent":
        return cls(**d)
