"""KvScheduler: pick the worker for a tokenized request.

Default cost (reference formula, kv_router/scheduler.rs:92-205, extended
with the byte-honest residency dimension):

    logit = 2.0 * overlap_blocks_norm - cache_usage
            - normalized_active_slots - kv_bytes_frac

highest logit wins; ties break randomly; if every candidate is saturated the
request waits for capacity. ``kv_bytes_frac`` is the worker's published KV
working set in bytes over its device+host capacity — the term that prices a
long paged context at its true footprint (0 when unpublished). The selector
is pluggable (CustomWorkerSelector override point,
components/router/src/main.rs:36-95).
"""

from __future__ import annotations

import asyncio
import collections
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...runtime.engine import EngineError
from .indexer import OverlapScores
from .protocols import ForwardPassMetrics, KVHitRateEvent


def _fast_fail_enabled() -> bool:
    """``DYN_ROUTER_FAST_FAIL=1``: a fully saturated/breaker-open candidate
    set answers 503 immediately instead of capacity-wait polling for up to
    ``timeout_s`` — under overload the wait is doomed, and every parked
    waiter holds resources the fleet needs to drain. Default off (the
    pre-overload-control wait behavior)."""
    return os.environ.get("DYN_ROUTER_FAST_FAIL", "0").lower() in (
        "1", "true", "yes", "on")


@dataclass
class WorkerSnapshot:
    worker_id: int
    metrics: ForwardPassMetrics


@dataclass
class ProcessedEndpoints:
    """Aggregated view of live workers (from the metrics aggregator)."""

    workers: Dict[int, ForwardPassMetrics] = field(default_factory=dict)

    @property
    def load_avg(self) -> float:
        if not self.workers:
            return 0.0
        vals = [m.request_active_slots for m in self.workers.values()]
        return sum(vals) / len(vals)

    @property
    def load_std(self) -> float:
        if not self.workers:
            return 0.0
        avg = self.load_avg
        vals = [m.request_active_slots for m in self.workers.values()]
        return (sum((v - avg) ** 2 for v in vals) / len(vals)) ** 0.5


WorkerSelector = Callable[
    [Sequence[int], int, OverlapScores, ProcessedEndpoints],
    Optional[int]]


def _transfer_weight() -> float:
    """``DYN_ROUTER_TRANSFER_WEIGHT``: logit penalty per expected
    KV-transfer second of a placement (0 = term off)."""
    from ...utils.knobs import env_float

    return env_float("DYN_ROUTER_TRANSFER_WEIGHT", 1.0, minimum=0.0)


def score_candidates(tokens: Sequence[int], block_size: int,
                     overlaps: OverlapScores,
                     endpoints: ProcessedEndpoints,
                     cluster=None) -> List[Dict[str, Any]]:
    """The full per-candidate score breakdown of the default cost — one
    dict per live worker with every term the logit is built from, so a
    routing decision is auditable after the fact instead of being a bare
    worker id (the decision-audit ring and ``/v1/router/decisions`` expose
    exactly this).

    ``cluster`` (a :class:`~..kv_cluster.registry.ClusterOverlap`, None
    when cluster KV sharing is off) folds fleet-wide prefix availability
    into the overlap term: a candidate's OWN host/disk-tier coverage
    counts like a device hit (admission restores it locally), and the
    best prefix some *other* worker holds counts at the transfer-cost
    weight — so local hit > peer hit > miss, by construction.

    With a pair-aware cost model armed (``cluster.pair_weight`` /
    ``pair_seconds``), donor election prices the (donor → candidate)
    network pair and every candidate's logit is additionally charged
    ``transfer_weight x expected-transfer-seconds`` for the bytes its
    election would move — a candidate behind a slow pair loses to one a
    cheap fetch away even at equal prefix coverage (FlowKV/NetKV)."""
    isl_blocks = max(1, len(tokens) // block_size)
    tw = _transfer_weight()
    out: List[Dict[str, Any]] = []
    for wid, m in endpoints.workers.items():
        saturated = bool(
            m.request_total_slots
            and m.request_active_slots >= m.request_total_slots
            and m.num_requests_waiting > 0)
        overlap = overlaps.scores.get(wid, 0)
        donor = None
        donor_blocks = 0
        local_eq = overlap
        if cluster is not None:
            # the worker's own tier residency is a local hit: restore is
            # a host->device upload, no network
            local_eq = max(overlap, cluster.owners.get(wid, 0))
            donor, donor_blocks = cluster.donor_for(wid, local_eq)
        extra = max(0, donor_blocks - local_eq) if donor is not None else 0
        peer_w = (cluster.weight_for(donor, wid, extra)
                  if cluster is not None and donor is not None else 0.0)
        eff = min(local_eq + peer_w * extra, float(isl_blocks))
        overlap_norm = eff / isl_blocks
        # expected seconds the elected fetch would spend moving bytes
        # onto THIS candidate (0 without a donor / without a cost model)
        xfer_s = (cluster.seconds_for(donor, wid, extra)
                  if cluster is not None and donor is not None and extra
                  else 0.0)
        # ledger provenance of the charged transfer term: which bandwidth
        # estimate ("pair" EWMA fed by the byte-flow ledger, "into_dst"
        # mean, "fleet" rate, optimistic "default") priced xfer_s
        xfer_src = (cluster.source_for(donor, wid)
                    if cluster is not None and donor is not None and extra
                    else "")
        load = (m.request_active_slots / m.request_total_slots
                if m.request_total_slots else 0.0)
        # bytes-resident dimension: the worker's total KV working set
        # (device pool + pinned host paging blocks) over its device+host
        # capacity. cache_usage prices device blocks; this prices what
        # slots cannot see — a 128k paged context pinning half the host
        # tier. 0 on workers that don't publish the byte fields.
        bytes_frac = (m.kv_resident_bytes / m.kv_capacity_bytes
                      if m.kv_capacity_bytes else 0.0)
        # full precision: the selector's tie-break compares these — the
        # audit ring rounds at serialization time, not here
        out.append({
            "worker_id": wid,
            "overlap_blocks": overlap,
            "cluster_local_blocks": local_eq,
            "kv_donor": donor,
            "kv_donor_blocks": donor_blocks,
            "overlap_norm": overlap_norm,
            "cache_usage": m.cache_usage,
            "load": load,
            "kv_bytes_frac": bytes_frac,
            "transfer_seconds": xfer_s,
            "transfer_src": xfer_src,
            "logit": 2.0 * overlap_norm - m.cache_usage - load
            - bytes_frac - tw * xfer_s,
            "saturated": saturated,
        })
    return out


def default_selector(tokens: Sequence[int], block_size: int,
                     overlaps: OverlapScores,
                     endpoints: ProcessedEndpoints,
                     rng: Optional[random.Random] = None,
                     candidates: Optional[List[Dict[str, Any]]] = None
                     ) -> Optional[int]:
    """The 2*overlap - usage - load cost; None => no capacity anywhere.
    ``candidates`` takes a precomputed :func:`score_candidates` result so
    the audited scheduler scores each decision exactly once."""
    rng = rng or random
    best: List[int] = []
    best_logit = None
    if candidates is None:
        candidates = score_candidates(tokens, block_size, overlaps,
                                      endpoints)
    for c in candidates:
        if c["saturated"]:
            continue
        logit = c["logit"]
        if best_logit is None or logit > best_logit + 1e-9:
            best, best_logit = [c["worker_id"]], logit
        elif abs(logit - best_logit) <= 1e-9:
            best.append(c["worker_id"])
    if not best:
        return None
    return rng.choice(best)


def _audit_ring_size() -> int:
    try:
        return max(1, int(os.environ.get("DYN_ROUTER_AUDIT", "512")))
    except ValueError:
        return 512


class KvScheduler:
    """Combines overlap scores + live endpoint metrics into a decision; emits
    KVHitRateEvent telemetry for each routed request and records every
    decision's full score breakdown into a bounded audit ring
    (``DYN_ROUTER_AUDIT`` entries, default 512) — the source behind
    ``GET /v1/router/decisions`` and ``tracectl decisions``."""

    def __init__(self, block_size: int,
                 selector: Optional[WorkerSelector] = None,
                 on_hit_rate: Optional[Callable[[KVHitRateEvent], None]] = None,
                 model: Optional[str] = None):
        self.block_size = block_size
        self.selector = selector
        self.on_hit_rate = on_hit_rate
        # fleet mode: the model this scheduler's candidate set serves —
        # stamped on every audit-ring entry so a merged multi-model
        # decision log stays attributable
        self.model = model
        self.endpoints = ProcessedEndpoints()
        # optional callable -> set of breaker-OPEN worker ids (wired by the
        # router service when it has breaker visibility); fast-fail treats
        # those as non-candidates
        self.breaker_open: Optional[Callable[[], set]] = None
        self.decisions: collections.deque = collections.deque(
            maxlen=_audit_ring_size())
        self._seq = 0
        # the chosen candidate's full score breakdown from the most recent
        # successful schedule() — incl. the kv_donor election, so route()
        # stamps exactly what was scored instead of re-deriving it. Only
        # meaningful synchronously after schedule() returns (no await in
        # between); None when the last decision found no capacity.
        self.last_choice: Optional[Dict[str, Any]] = None

    def update_endpoints(self, workers: Dict[int, ForwardPassMetrics]) -> None:
        self.endpoints = ProcessedEndpoints(dict(workers))

    def remove_worker(self, worker_id: int) -> None:
        self.endpoints.workers.pop(worker_id, None)

    def decision_log(self, limit: int = 0) -> List[Dict[str, Any]]:
        """The most recent decisions, oldest first; ``limit`` 0 = all that
        survive in the ring."""
        out = list(self.decisions)
        return out[-limit:] if limit else out

    def _record(self, tokens: Sequence[int], salt: int,
                candidates: List[Dict[str, Any]],
                wid: Optional[int]) -> None:
        if wid is None:
            # capacity-wait retries poll schedule() every ~50ms: collapse
            # each waiter's saturation streak into ONE audited entry so
            # waiting requests cannot flush the ring. CONCURRENT waiters
            # interleave their polls, so scan the whole trailing run of
            # None-decisions (bounded by the waiter count) for this
            # waiter's entry, not just the newest one.
            for d in reversed(self.decisions):
                if d["worker_id"] is not None:
                    break
                if d["isl_tokens"] == len(tokens) and d["salt"] == salt:
                    d["retries"] = d.get("retries", 0) + 1
                    d["at"] = time.time()
                    return
        self._seq += 1
        # candidates are rounded here (display precision); the selector
        # saw the full-precision values
        self.decisions.append({
            "seq": self._seq,
            **({"model": self.model} if self.model is not None else {}),
            "at": time.time(),
            "isl_tokens": len(tokens),
            "isl_blocks": max(1, len(tokens) // self.block_size),
            "salt": salt,
            "worker_id": wid,           # None = no capacity anywhere
            "overlap_blocks": (next(
                (c["overlap_blocks"] for c in candidates
                 if c["worker_id"] == wid), 0) if wid is not None else 0),
            "candidates": [
                {**c, "overlap_norm": round(c["overlap_norm"], 4),
                 "cache_usage": round(c["cache_usage"], 4),
                 "load": round(c["load"], 4),
                 "kv_bytes_frac": round(c["kv_bytes_frac"], 4),
                 "transfer_seconds": round(
                     c.get("transfer_seconds", 0.0), 5),
                 "logit": round(c["logit"], 4)}
                for c in candidates],
        })

    def schedule(self, tokens: Sequence[int],
                 overlaps: OverlapScores, salt: int = 0,
                 cluster=None, exclude=None) -> Optional[int]:
        endpoints = self.endpoints
        if exclude:
            # mid-stream failover re-election: score everyone EXCEPT the
            # instances the resume layer declared dead. If that vetoes the
            # whole candidate set (single-worker pool, stall not death),
            # stand down like breaker.filter — the worker-side resume
            # supersede guard makes landing on the excluded instance safe,
            # whereas refusing to route manufactures a total outage.
            kept = {w: m for w, m in endpoints.workers.items()
                    if w not in set(exclude)}
            if kept:
                endpoints = ProcessedEndpoints(kept)
        candidates = score_candidates(tokens, self.block_size, overlaps,
                                      endpoints, cluster=cluster)
        if self.selector is not None:
            wid = self.selector(tokens, self.block_size, overlaps, endpoints)
        else:
            wid = default_selector(tokens, self.block_size, overlaps,
                                   endpoints, candidates=candidates)
        self.last_choice = next(
            (c for c in candidates if c["worker_id"] == wid), None) \
            if wid is not None else None
        self._record(tokens, salt, candidates, wid)
        if wid is not None and self.on_hit_rate:
            self.on_hit_rate(KVHitRateEvent(
                worker_id=wid,
                isl_blocks=len(tokens) // self.block_size,
                overlap_blocks=overlaps.scores.get(wid, 0)))
        return wid

    def _all_unavailable(self, tokens: Sequence[int],
                         overlaps: OverlapScores, wid: Optional[int]
                         ) -> Optional[str]:
        """Fast-fail predicate: None when some candidate can take work,
        else the reason ("saturated" / "breaker_open") why every live
        candidate is unavailable right now."""
        if not self.endpoints.workers:
            return None            # membership empty: 503s elsewhere
        open_ids = set(self.breaker_open()) if self.breaker_open else set()
        if wid is None:
            return "saturated"     # selector found no capacity anywhere
        if wid in open_ids:
            cands = score_candidates(tokens, self.block_size, overlaps,
                                     self.endpoints)
            if all(c["saturated"] or c["worker_id"] in open_ids
                   for c in cands):
                return "breaker_open"
        return None

    async def schedule_or_wait(self, tokens: Sequence[int],
                               overlaps: OverlapScores,
                               poll_s: float = 0.05,
                               timeout_s: float = 30.0,
                               salt: int = 0,
                               fast_fail: Optional[bool] = None,
                               cluster=None, exclude=None) -> int:
        """Wait for capacity when all workers are saturated — unless
        ``fast_fail`` (param, or ``DYN_ROUTER_FAST_FAIL``, or a brownout
        level above normal at the router service) is active: then a fully
        saturated/breaker-open candidate set raises a typed 503
        immediately, shedding in milliseconds instead of parking every
        overload victim in a retry loop."""
        if fast_fail is None:
            fast_fail = _fast_fail_enabled()
        deadline = asyncio.get_event_loop().time() + timeout_s
        while True:
            wid = self.schedule(tokens, overlaps, salt=salt,
                                cluster=cluster, exclude=exclude)
            if fast_fail:
                why = self._all_unavailable(tokens, overlaps, wid)
                if why is not None:
                    n = len(self.endpoints.workers)
                    raise EngineError(
                        f"router fast-fail: all {n} candidates "
                        f"unavailable ({why})", 503,
                        stage="router", reason=why, retry_after=1.0)
            if wid is not None:
                return wid
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("no worker capacity")
            await asyncio.sleep(poll_s)
