"""KvScheduler: pick the worker for a tokenized request.

Default cost (reference formula, kv_router/scheduler.rs:92-205):

    logit = 2.0 * overlap_blocks_norm - cache_usage - normalized_active_slots

highest logit wins; ties break randomly; if every candidate is saturated the
request waits for capacity. The selector is pluggable (CustomWorkerSelector
override point, components/router/src/main.rs:36-95).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .indexer import OverlapScores
from .protocols import ForwardPassMetrics, KVHitRateEvent


@dataclass
class WorkerSnapshot:
    worker_id: int
    metrics: ForwardPassMetrics


@dataclass
class ProcessedEndpoints:
    """Aggregated view of live workers (from the metrics aggregator)."""

    workers: Dict[int, ForwardPassMetrics] = field(default_factory=dict)

    @property
    def load_avg(self) -> float:
        if not self.workers:
            return 0.0
        vals = [m.request_active_slots for m in self.workers.values()]
        return sum(vals) / len(vals)

    @property
    def load_std(self) -> float:
        if not self.workers:
            return 0.0
        avg = self.load_avg
        vals = [m.request_active_slots for m in self.workers.values()]
        return (sum((v - avg) ** 2 for v in vals) / len(vals)) ** 0.5


WorkerSelector = Callable[
    [Sequence[int], int, OverlapScores, ProcessedEndpoints],
    Optional[int]]


def default_selector(tokens: Sequence[int], block_size: int,
                     overlaps: OverlapScores,
                     endpoints: ProcessedEndpoints,
                     rng: Optional[random.Random] = None) -> Optional[int]:
    """The 2*overlap - usage - load cost; None => no capacity anywhere."""
    rng = rng or random
    isl_blocks = max(1, len(tokens) // block_size)
    best: List[int] = []
    best_logit = None
    for wid, m in endpoints.workers.items():
        if (m.request_total_slots
                and m.request_active_slots >= m.request_total_slots
                and m.num_requests_waiting > 0):
            continue  # saturated
        overlap = overlaps.scores.get(wid, 0)
        logit = (2.0 * (overlap / isl_blocks)
                 - m.cache_usage
                 - (m.request_active_slots / m.request_total_slots
                    if m.request_total_slots else 0.0))
        if best_logit is None or logit > best_logit + 1e-9:
            best, best_logit = [wid], logit
        elif abs(logit - best_logit) <= 1e-9:
            best.append(wid)
    if not best:
        return None
    return rng.choice(best)


class KvScheduler:
    """Combines overlap scores + live endpoint metrics into a decision; emits
    KVHitRateEvent telemetry for each routed request."""

    def __init__(self, block_size: int,
                 selector: Optional[WorkerSelector] = None,
                 on_hit_rate: Optional[Callable[[KVHitRateEvent], None]] = None):
        self.block_size = block_size
        self.selector = selector
        self.on_hit_rate = on_hit_rate
        self.endpoints = ProcessedEndpoints()

    def update_endpoints(self, workers: Dict[int, ForwardPassMetrics]) -> None:
        self.endpoints = ProcessedEndpoints(dict(workers))

    def remove_worker(self, worker_id: int) -> None:
        self.endpoints.workers.pop(worker_id, None)

    def schedule(self, tokens: Sequence[int],
                 overlaps: OverlapScores) -> Optional[int]:
        if self.selector is not None:
            wid = self.selector(tokens, self.block_size, overlaps, self.endpoints)
        else:
            wid = default_selector(tokens, self.block_size, overlaps,
                                   self.endpoints)
        if wid is not None and self.on_hit_rate:
            self.on_hit_rate(KVHitRateEvent(
                worker_id=wid,
                isl_blocks=len(tokens) // self.block_size,
                overlap_blocks=overlaps.scores.get(wid, 0)))
        return wid

    async def schedule_or_wait(self, tokens: Sequence[int],
                               overlaps: OverlapScores,
                               poll_s: float = 0.05,
                               timeout_s: float = 30.0) -> int:
        """Wait for capacity when all workers are saturated."""
        deadline = asyncio.get_event_loop().time() + timeout_s
        while True:
            wid = self.schedule(tokens, overlaps)
            if wid is not None:
                return wid
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("no worker capacity")
            await asyncio.sleep(poll_s)
