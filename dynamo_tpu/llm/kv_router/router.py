"""KvRouter: composes indexer + metrics aggregation + scheduler into a
routing service over the distributed runtime.

- subscribes the target component's ``kv_events`` subject -> KvIndexer
- scrapes worker ForwardPassMetrics from the store prefix -> scheduler
- tracks the worker endpoint's live instance set (drops dead workers from
  the index)
- serves ``route``: {token_ids} -> {worker_id, overlap_blocks}

Reference capability: lib/llm/src/kv_router.rs (KvRouter), metrics_aggregator.rs,
components/router binary.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, Optional

from ...runtime.component import Client, Component, DistributedRuntime
from ...runtime.engine import EngineError
from ...utils.aiotasks import cancel_all, spawn
from ..tokens import compute_seq_hashes
from .indexer import KvIndexer
from .protocols import KV_EVENT_SUBJECT, ForwardPassMetrics, RouterEvent
from .scheduler import KvScheduler

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouterService:
    def __init__(self, drt: DistributedRuntime, namespace: str,
                 worker_component: str, block_size: int = 64,
                 scrape_interval: float = 0.5,
                 model: Optional[str] = None):
        self.drt = drt
        self.namespace = namespace
        self.worker_component = worker_component
        # fleet mode: the model this router instance serves. Candidate
        # sets are per-component BY CONSTRUCTION (the indexer subscribes
        # one component's kv_events, the scrape reads one component's
        # metrics prefix, the cluster index filters by component), so
        # one KvRouterService per model pool IS the model-scoped router;
        # the name here just makes scoring/audit entries attributable.
        self.model = model
        self.indexer = KvIndexer(block_size)
        self.scheduler = KvScheduler(block_size,
                                     on_hit_rate=self._emit_hit_rate,
                                     model=model)
        self.scrape_interval = scrape_interval
        self._scrape_task: Optional[asyncio.Task] = None
        self.worker_client: Optional[Client] = None
        self._hit_events = 0
        self._publish_tasks: set = set()   # in-flight hit-rate publishes
        # fleet brownout view (utils/overload.BrownoutState, armed by the
        # router binary): any level above normal turns on scheduler
        # fast-fail — under declared overload, capacity-waiting is doomed
        self.brownout = None
        # cluster KV sharing (DYN_KV_CLUSTER=1): registry reader + transfer
        # cost model; when armed, route() scores cluster hits and stamps
        # the elected donor on the response
        self.cluster_index = None
        self.cost_model = None

    def _emit_hit_rate(self, ev) -> None:
        self._hit_events += 1
        # retained handle: a failed publish (store outage mid-churn) must
        # log, not vanish as a GC'd "exception never retrieved"
        spawn(self.drt.namespace(self.namespace).publish(
                  "kv-hit-rate", ev.to_dict()),
              name="kv-hit-rate-publish", store=self._publish_tasks)

    # ------------------------------------------------------------------
    async def start(self) -> "KvRouterService":
        ns = self.drt.namespace(self.namespace)
        component = ns.component(self.worker_component)

        async def on_kv_event(payload: Dict) -> None:
            self.indexer.apply_sync(RouterEvent.from_dict(payload))

        await component.subscribe(KV_EVENT_SUBJECT, on_kv_event)

        # live worker set: prune index + scheduler on death
        self.worker_client = await component.endpoint("generate").client().start()
        # breaker visibility for the scheduler's fast-fail: instances THIS
        # process's client currently holds OPEN count as non-candidates
        from ...runtime.circuit_breaker import OPEN

        self.scheduler.breaker_open = lambda: {
            i for i in self.worker_client.instances
            if self.worker_client.breaker.state(i) == OPEN}

        from .. import kv_cluster

        if kv_cluster.enabled():
            self.cluster_index = await kv_cluster.KvClusterIndex().start(
                self.drt.store, self.namespace)
            self.cost_model = kv_cluster.TransferCostModel()

        def on_change():
            live = set(self.worker_client.instances)
            for w in self.indexer.tree.workers() - live:
                self.indexer.remove_worker(w)
            for w in list(self.scheduler.endpoints.workers) :
                if w not in live:
                    self.scheduler.remove_worker(w)
            if self.cluster_index is not None:
                # belt over the lease-bound suspenders: a donor whose
                # endpoint registration vanished must stop being scored
                # immediately, even if its registry delete is in flight
                for w in list(self.cluster_index.records):
                    if w not in live:
                        self.cluster_index.remove_worker(w)

        self.worker_client.on_instances_changed = on_change
        self._scrape_task = asyncio.create_task(self._scrape_loop())
        return self

    async def stop(self) -> None:
        if self._scrape_task:
            self._scrape_task.cancel()
        await cancel_all(self._publish_tasks)

    async def _scrape_loop(self) -> None:
        from ..metrics_aggregator import METRICS_PREFIX

        prefix = f"{METRICS_PREFIX}{self.namespace}/{self.worker_component}/"
        beat = 0
        while True:
            try:
                items = await self.drt.store.get_prefix(prefix)
                workers = {}
                live = set(self.worker_client.instances) \
                    if self.worker_client else None
                for key, value in items:
                    wid = int(key.rsplit("/", 1)[1], 16)
                    if live is not None and wid not in live:
                        continue
                    workers[wid] = ForwardPassMetrics.from_dict(
                        json.loads(value.decode()))
                self.scheduler.update_endpoints(workers)
                if self.cost_model is not None and beat % 10 == 0:
                    # refresh the peer-fetch bandwidth estimate from the
                    # merged llm_kv_transfer histograms — every ~10 beats,
                    # the stage merge is heavier than the metrics scrape
                    from ..metrics_aggregator import fetch_stage_states

                    self.cost_model.update_from_states(
                        await fetch_stage_states(self.drt.store,
                                                 self.namespace))
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("metrics scrape failed")
            beat += 1
            await asyncio.sleep(self.scrape_interval)

    # ------------------------------------------------------------------
    def _cluster_overlap(self, seq_hashes):
        """Cluster-wide prefix availability of a request's hash chain
        (None when cluster sharing is off or the registry is empty),
        armed with the pair-aware transfer-cost callables so donor
        election and candidate scoring price the (src,dst) network pair
        the placement would actually move bytes over."""
        if (self.cluster_index is None or not self.cluster_index.records
                or not seq_hashes):
            return None
        index, cm = self.cluster_index, self.cost_model
        weight = cm.weight(len(seq_hashes), index.any_block_bytes())
        # only owners of the routed component: a foreign component's
        # record (disagg prefill pool, another model) is unreachable
        # through the worker's fetch client
        ov = index.find(seq_hashes, weight=weight,
                        component=self.worker_component)

        def _bb(src):
            return index.block_bytes(src) or index.any_block_bytes()

        ov.pair_weight = lambda src, dst, blocks: cm.weight(
            blocks, _bb(src), src=src, dst=dst)
        ov.pair_seconds = lambda src, dst, blocks: cm.estimate_seconds(
            blocks, _bb(src), src=src, dst=dst)
        ov.pair_source = lambda src, dst: cm.bandwidth_info(
            src=src, dst=dst)[1]
        return ov

    async def route(self, token_ids, lora_id: int = 0,
                    exclude=None) -> Dict:
        # hash the prompt chain ONCE; the indexer and the cluster index
        # query the same salted chain
        hashes = compute_seq_hashes(token_ids, self.indexer.block_size,
                                    lora_id=lora_id)
        overlaps = self.indexer.find_matches(hashes)
        cluster = self._cluster_overlap(hashes)
        # brownout level > 0 forces fast-fail regardless of the env knob;
        # None defers to DYN_ROUTER_FAST_FAIL
        fast_fail = True if (self.brownout is not None
                             and self.brownout.level > 0) else None
        wid = await self.scheduler.schedule_or_wait(token_ids, overlaps,
                                                    salt=lora_id,
                                                    fast_fail=fast_fail,
                                                    cluster=cluster,
                                                    exclude=exclude)
        resp = {"worker_id": wid,
                "overlap_blocks": overlaps.scores.get(wid, 0)}
        # stamp the donor score_candidates elected for the chosen worker
        # (scheduler.last_choice is this decision's: schedule_or_wait
        # returns synchronously after its final schedule()) — the worker
        # fetches without a registry round-trip, and the stamp is exactly
        # what the audit ring recorded
        chosen = self.scheduler.last_choice
        if (cluster is not None and chosen is not None
                and chosen["worker_id"] == wid
                and chosen.get("kv_donor") is not None
                # a donor the caller excluded is a dead instance whose
                # registry delete is still in flight: stamping it would
                # burn the fetch timeout on a resume's critical path
                and (not exclude or chosen["kv_donor"] not in exclude)):
            from ...utils.prometheus import stage_metrics

            stage_metrics().kv_cluster_hits.inc()
            resp["kv_donor"] = chosen["kv_donor"]
            resp["kv_donor_blocks"] = chosen["kv_donor_blocks"]
        return resp

    def decisions(self, limit: int = 0):
        """The audit ring: every routed request's full score breakdown."""
        return self.scheduler.decision_log(limit)

    async def serve(self, component: Component,
                    endpoint_name: str = "route") -> None:
        async def handler(request, ctx):
            yield await self.route(request["token_ids"],
                                   int(request.get("lora_id", 0)),
                                   exclude=request.get("exclude"))

        await component.endpoint(endpoint_name).serve(handler)

        # decision audit: the frontend's GET /v1/router/decisions and
        # `tracectl decisions` read the ring over this endpoint
        async def decisions_handler(request, ctx):
            limit = int((request or {}).get("limit", 0) or 0)
            yield {"decisions": self.decisions(limit)}

        await component.endpoint("decisions").serve(decisions_handler)


class FleetKvRouter:
    """One routing service for a whole multi-model fleet.

    The model-scoped candidate set comes for free from the existing
    per-component machinery: each model pool is its own store component,
    so one :class:`KvRouterService` per model *is* the model-scoped
    router — its indexer subscribes only that component's ``kv_events``,
    its scrape reads only that component's metrics prefix, and its
    cluster index already filters donors by component. This class keeps
    the set of inner services in lockstep with the fleet registry
    (``ctl fleet add`` mid-traffic arms routing for the new model within
    a watch delivery) and serves the same ``route``/``decisions``
    endpoints, dispatching on the request's ``model`` field.

    A request for an unregistered model is a typed 503 — the frontend's
    :class:`~..remote.RemoteCoreEngine` catches it and falls back to
    random dispatch over its own (model-correct) worker client, so a
    registry lag costs prefix affinity, never correctness.
    """

    def __init__(self, drt: DistributedRuntime, namespace: str,
                 block_size: int = 64):
        self.drt = drt
        self.namespace = namespace
        self.block_size = block_size
        self.routers: Dict[str, KvRouterService] = {}
        self.registry = None
        self.brownout = None        # shared BrownoutState (cli/router)
        self._sync_tasks: set = set()
        self._sync_lock = asyncio.Lock()

    async def start(self) -> "FleetKvRouter":
        from ...fleet.registry import FleetRegistry

        self.registry = FleetRegistry(self.drt.store, self.namespace)

        def on_change(name, spec):
            # registry hook is sync; the (idempotent, lock-serialized)
            # sync runs as a retained task
            spawn(self._sync_model(name, spec),
                  name=f"fleet-router-sync:{name}",
                  store=self._sync_tasks)

        self.registry.on_change = on_change
        await self.registry.start()
        # the snapshot fired on_change per record; wait for those syncs
        # so start() returns with routing armed for the known fleet
        for t in list(self._sync_tasks):
            await t
        return self

    async def _sync_model(self, name: str, spec) -> None:
        async with self._sync_lock:
            cur = self.routers.get(name)
            if spec is None:
                if cur is not None:
                    del self.routers[name]
                    await cur.stop()
                    log.info("fleet router: dropped model %s", name)
                return
            if cur is not None and cur.worker_component == spec.component:
                return
            if cur is not None:
                await cur.stop()
            svc = KvRouterService(self.drt, self.namespace, spec.component,
                                  block_size=self.block_size, model=name)
            svc.brownout = self.brownout
            await svc.start()
            self.routers[name] = svc
            log.info("fleet router: routing model %s -> component %s",
                     name, spec.component)

    async def stop(self) -> None:
        await cancel_all(self._sync_tasks)
        for svc in list(self.routers.values()):
            await svc.stop()
        self.routers.clear()

    # ------------------------------------------------------------------
    def _pick(self, model: Optional[str]) -> Optional[KvRouterService]:
        if model:
            return self.routers.get(model)
        if len(self.routers) == 1:
            # single-model fleet: legacy clients that send no model
            # field keep working
            return next(iter(self.routers.values()))
        return None

    async def route(self, token_ids, lora_id: int = 0,
                    model: Optional[str] = None, exclude=None) -> Dict:
        svc = self._pick(model)
        if svc is None:
            raise EngineError(
                f"router: model {model!r} has no routing pool "
                f"(fleet registry: {sorted(self.routers) or 'empty'})",
                503, stage="router", reason="unknown_model",
                retry_after=1.0)
        return await svc.route(token_ids, lora_id, exclude=exclude)

    def decisions(self, limit: int = 0, model: Optional[str] = None):
        """Merged audit across models (each entry carries its ``model``
        stamp), or one model's ring when ``model`` is given."""
        if model:
            svc = self.routers.get(model)
            return svc.decisions(limit) if svc else []
        merged = [d for svc in self.routers.values()
                  for d in svc.decisions(0)]
        merged.sort(key=lambda d: d.get("at", 0.0))
        return merged[-limit:] if limit else merged

    async def serve(self, component: Component,
                    endpoint_name: str = "route") -> None:
        async def handler(request, ctx):
            yield await self.route(request["token_ids"],
                                   int(request.get("lora_id", 0)),
                                   model=request.get("model"),
                                   exclude=request.get("exclude"))

        await component.endpoint(endpoint_name).serve(handler)

        async def decisions_handler(request, ctx):
            req = request or {}
            yield {"decisions": self.decisions(
                int(req.get("limit", 0) or 0), model=req.get("model"))}

        await component.endpoint("decisions").serve(decisions_handler)
