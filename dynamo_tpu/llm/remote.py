"""Remote engine plumbing: serve a core engine over the runtime's data plane
and call it from a frontend, with optional KV-aware routing.

Wire shape on the ``generate`` endpoint: request = BackendInput.to_dict(),
stream items = EngineOutput.to_dict(). The KV router service serves ``route``:
{token_ids} -> {worker_id, overlap_blocks}.

Reference capability: the dyn:// egress path (launch/dynamo-run in=http
out=dyn://, lib/runtime egress/push.rs) and components/router.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from ..runtime.component import Client, Endpoint
from ..runtime.engine import AsyncEngine, Context, EngineError
from .protocols.common import BackendInput, EngineOutput
from .model_card import ModelDeploymentCard

log = logging.getLogger("dynamo_tpu.remote")

MODEL_PREFIX = "models/"  # store keys: models/{chat|completion}/{name}


def model_key(model_type: str, name: str) -> str:
    return f"{MODEL_PREFIX}{model_type}/{name}"


class RemoteCoreEngine(AsyncEngine[BackendInput, EngineOutput]):
    """Frontend-side core engine that forwards BackendInput to a remote
    worker endpoint; optionally consults a router endpoint first and pins the
    request to the returned worker (KV-aware routing)."""

    def __init__(self, worker_client: Client,
                 router_client: Optional[Client] = None):
        self.worker_client = worker_client
        self.router_client = router_client

    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        mode = "random"
        instance_id = None
        if self.router_client is not None and self.router_client.instances:
            try:
                async for resp in self.router_client.generate(
                        {"token_ids": request.token_ids}, context.child()):
                    wid = resp.get("worker_id")
                    if wid is not None and wid in self.worker_client.instances:
                        mode, instance_id = "direct", wid
                    break
            except EngineError:
                log.warning("router unavailable; falling back to random")
        async for item in self.worker_client.generate(
                request.to_dict(), context, mode=mode,
                instance_id=instance_id):
            yield EngineOutput.from_dict(item)


async def serve_core_engine(endpoint: Endpoint, engine: AsyncEngine) -> None:
    """Expose a local core engine (BackendInput->EngineOutput) on an
    endpoint, handling dict (de)serialization."""

    async def handler(request, ctx):
        bi = BackendInput.from_dict(request)
        async for out in engine.generate(bi, ctx):
            yield out.to_dict()

    await endpoint.serve(handler)


async def register_model(store, card: ModelDeploymentCard,
                         endpoint_path: str, model_type: str = "chat",
                         lease: Optional[int] = None) -> None:
    """llmctl add: advertise model -> endpoint mapping for frontends."""
    import json

    payload = json.dumps({"card": card.to_dict(),
                          "endpoint": endpoint_path}).encode()
    await store.put(model_key(model_type, card.name), payload, lease=lease)


async def unregister_model(store, name: str, model_type: str = "chat") -> None:
    await store.delete(model_key(model_type, name))


async def list_models(store):
    import json

    out = []
    for key, value in await store.get_prefix(MODEL_PREFIX):
        d = json.loads(value.decode())
        _, mtype, name = key.split("/", 2)
        out.append({"name": name, "type": mtype,
                    "endpoint": d["endpoint"],
                    "card": d.get("card")})
    return out
