"""Remote engine plumbing: serve a core engine over the runtime's data plane
and call it from a frontend, with optional KV-aware routing.

Wire shape on the ``generate`` endpoint: request = BackendInput.to_dict(),
stream items = EngineOutput.to_dict(). The KV router service serves ``route``:
{token_ids} -> {worker_id, overlap_blocks}.

Reference capability: the dyn:// egress path (launch/dynamo-run in=http
out=dyn://, lib/runtime egress/push.rs) and components/router.
"""

from __future__ import annotations

import asyncio
import logging
import re
from typing import AsyncIterator, Optional, Tuple

from ..runtime.component import Client, Endpoint
from ..runtime.engine import AsyncEngine, Context, EngineError
from .protocols.common import BackendInput, EngineOutput
from .model_card import ModelDeploymentCard

log = logging.getLogger("dynamo_tpu.remote")

# store keys: models/{chat|completion}/{name}[:i-{lease_hex}]
# Lease-bound registrations are per-instance (suffixed with the worker's
# lease id, ref endpoint.rs `{key}:{lease_id_hex}`): replicas of one model
# must not overwrite each other's liveness binding, or the model drops for
# everyone when the LAST registrant dies — not when ALL of them have.
# The ``:i-`` marker keeps the suffix parse unambiguous for model names
# that themselves contain ':' (e.g. ollama-style "llama3:8b").
MODEL_PREFIX = "models/"

_LEASE_SUFFIX = re.compile(r":i-[0-9a-f]+$")


def model_key(model_type: str, name: str,
              lease: Optional[int] = None) -> str:
    base = f"{MODEL_PREFIX}{model_type}/{name}"
    return f"{base}:i-{lease:x}" if lease is not None else base


def split_model_key(key: str) -> Optional[Tuple[str, str]]:
    """``models/chat/m:i-1f`` → ("chat", "m"); None for foreign keys."""
    if not key.startswith(MODEL_PREFIX):
        return None
    parts = key[len(MODEL_PREFIX):].split("/", 1)
    if len(parts) != 2:
        return None
    mtype, rest = parts
    return mtype, _LEASE_SUFFIX.sub("", rest)


class RemoteCoreEngine(AsyncEngine[BackendInput, EngineOutput]):
    """Frontend-side core engine that forwards BackendInput to a remote
    worker endpoint; optionally consults a router endpoint first and pins the
    request to the returned worker (KV-aware routing)."""

    def __init__(self, worker_client: Client,
                 router_client: Optional[Client] = None,
                 model_name: Optional[str] = None):
        self.worker_client = worker_client
        self.router_client = router_client
        # fleet routing: the model this engine serves, carried on every
        # route request so a FleetKvRouter scores the right candidate
        # set (single-model routers ignore the field)
        self.model_name = model_name

    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        from . import resume

        if resume.max_attempts() > 0:
            # mid-stream failover: a transport break / inter-frame stall
            # re-enters _dispatch_once with the dead instance excluded and
            # the emitted tokens folded into the resume prefix — the
            # detokenizer above this engine sees one continuous stream
            async for item in resume.run(self._dispatch_once, request,
                                         context,
                                         breaker=self.worker_client.breaker):
                yield item
            return
        async for item in self._dispatch_once(request, context, set(), 0,
                                              None):
            yield item

    async def _dispatch_once(self, request: BackendInput, context: Context,
                             exclude: set, resume_no: int,
                             on_instance) -> AsyncIterator[EngineOutput]:
        """One routed attempt: consult the router (minus ``exclude``), pin
        to the elected worker, stream the response. The resume layer calls
        this repeatedly under one context id; ``resume_no`` rides the wire
        envelope so a zombie context of a lower ordinal yields server-side,
        and ``on_instance`` reports who was chosen (the blame target when
        the stream later breaks)."""
        mode = "random"
        instance_id = None
        if self.router_client is not None and self.router_client.instances:
            try:
                async for resp in self.router_client.generate(
                        # kv_salt (VLM: lora ^ image digest) is the salt the
                        # engine publishes blocks under — score overlap with
                        # it so image prompts get router-side prefix credit
                        {"token_ids": request.token_ids,
                         "lora_id": request.kv_salt or request.lora_id,
                         **({"model": self.model_name}
                            if self.model_name else {}),
                         **({"exclude": sorted(exclude)}
                            if exclude else {})},
                        context.child()):
                    wid = resp.get("worker_id")
                    if wid is not None and wid in self.worker_client.instances:
                        mode, instance_id = "direct", wid
                        # cluster KV sharing: carry the router's donor
                        # election to the worker, which fetches the prefix
                        # peer-to-peer before the request enters its engine
                        if resp.get("kv_donor"):
                            request.kv_donor = int(resp["kv_donor"])
                            request.kv_donor_blocks = int(
                                resp.get("kv_donor_blocks", 0))
                    break
            except EngineError:
                log.warning("router unavailable; falling back to random")
        # the router's scheduler stands down when exclusion would veto the
        # whole pool; the random fallback needs the same stand-down here
        ex = exclude
        if ex and not (set(self.worker_client.instances) - ex):
            ex = set()
        async for item in self.worker_client.generate(
                request.to_dict(), context, mode=mode,
                instance_id=instance_id, exclude=ex, resume=resume_no,
                on_instance=on_instance):
            yield EngineOutput.from_dict(item)


async def serve_core_engine(endpoint: Endpoint, engine: AsyncEngine) -> None:
    """Expose a local core engine (BackendInput->EngineOutput) on an
    endpoint, handling dict (de)serialization."""

    async def handler(request, ctx):
        bi = BackendInput.from_dict(request)
        async for out in engine.generate(bi, ctx):
            yield out.to_dict()

    await endpoint.serve(handler)


async def register_model(store, card: ModelDeploymentCard,
                         endpoint_path: str, model_type: str = "chat",
                         lease: Optional[int] = None) -> None:
    """llmctl add: advertise model -> endpoint mapping for frontends."""
    import json

    payload = json.dumps({"card": card.to_dict(),
                          "endpoint": endpoint_path}).encode()
    await store.put(model_key(model_type, card.name, lease=lease),
                    payload, lease=lease)


async def unregister_model(store, name: str, model_type: str = "chat") -> None:
    """llmctl remove: drop the manual entry and every per-instance one."""
    base = model_key(model_type, name)
    await store.delete(base)
    for key, _ in await store.get_prefix(base + ":i-"):
        if _LEASE_SUFFIX.search(key):   # never sweep a ':'-containing name
            await store.delete(key)


async def list_models(store):
    """One entry per (type, name): N replicas register N lease-suffixed keys
    for the same model — surface them as ``instances: N``, not N duplicate
    rows in llmctl output (ADVICE r4). A manual (lease-less) ``llmctl add``
    entry is not a replica, so it never inflates the count; registrations
    that disagree on the endpoint are surfaced, not silently collapsed."""
    import json

    by_model: dict = {}
    for key, value in await store.get_prefix(MODEL_PREFIX):
        mt_name = split_model_key(key)
        if mt_name is None:
            continue
        d = json.loads(value.decode())
        is_instance = _LEASE_SUFFIX.search(key) is not None
        entry = by_model.get(mt_name)
        if entry is None:
            entry = by_model[mt_name] = {
                "name": mt_name[1], "type": mt_name[0],
                "endpoint": d["endpoint"], "card": d.get("card"),
                "instances": 1 if is_instance else 0}
        else:
            if is_instance:
                entry["instances"] += 1
        if d["endpoint"] != entry["endpoint"]:
            entry.setdefault("conflicting_endpoints", []).append(
                d["endpoint"])
    # a model present only via a manual entry still serves: show 1
    for entry in by_model.values():
        entry["instances"] = entry["instances"] or 1
    return list(by_model.values())
