"""Mid-stream failover: resumable generation.

A decode worker that dies or wedges mid-stream used to end the request
with a typed 503 — all prefill compute and every decoded token thrown
away. This module makes the stream *resumable*: the frontend-side engine
keeps a per-stream resume record (the prompt, every emitted token id, the
sampling seed, the original deadline) and on a stream break re-enters the
router with the dead instance excluded, up to ``DYN_RESUME_MAX`` attempts
inside the original deadline. The client sees a pause, not a 503.

The resume request carries ``prompt + emitted`` as the effective prefix
with ``resume_pos = len(emitted)``: the new worker reconstructs KV the
cheap way first — admission restores the longest surviving sealed prefix
from its tiers (cluster-fetched from the dead donor's host-tier mirror or
any other owner via :class:`~.kv_cluster.fetch.ClusterFetcher`) and
teacher-forces only the unsealed tail — falling back to full local
prefill when no donor survives. Greedy resume is token-identical to an
unkilled run (the forced prefix pins the argmax chain); sampled requests
replay the emitted prefix verbatim and re-seed their RNG stream at the
resume position (:func:`~..engine.sampling` fold), so a seeded stream
stays deterministic without pretending the dead worker's unreplayable
draws continued.

Break classes that resume (each is a provably-dead or wedged stream whose
re-dispatch cannot double-emit — the worker-side resume-supersede guard
kills a zombie context of the same id):

- transport break — the worker dropped the stream mid-response
  (typed 503, no machine reason) or spoke a malformed frame (502);
- inter-frame stall — no frame for ``DYN_RESUME_STALL`` seconds; the
  stalled instance also takes a circuit-breaker hit here (transport
  breaks are already counted inside ``Client.generate``).

Typed failures (overload sheds, router fast-fail, admission 4xx, deadline
504s) carry a machine ``reason`` and are never resumed — they are
decisions, not deaths. Exhausting the attempt budget raises a typed 503
``reason="resume_exhausted"``; the original deadline expiring mid-retry
raises the standard 504 naming stage ``stream_resume``. Outcomes count in
``dyn_stream_resumes_total{outcome}``; each successful resume observes
its client-visible pause in ``dyn_resume_latency_seconds``; the flight
recorder gets a ``stream.resume`` event per attempt so incident bundles
show the failover timeline.
"""

from __future__ import annotations

import asyncio
import copy
import logging
import time
from typing import AsyncIterator, Callable, List, Optional, Set

from ..obs.flightrec import note_event
from ..runtime import deadline as dl
from ..runtime.engine import Context, EngineError
from ..utils.knobs import env_float
from ..utils.prometheus import stage_metrics
from .protocols.common import BackendInput, EngineOutput, FinishReason

log = logging.getLogger("dynamo_tpu.resume")

#: the stage name resume-layer errors (503 resume_exhausted, 504 expiry)
#: carry in the uniform error body
RESUME_STAGE = "stream_resume"

#: dispatch(request, context, exclude, resume_no, on_instance) -> stream;
#: one routed attempt (RemoteCoreEngine._dispatch_once is the production
#: implementation)
Dispatch = Callable[..., AsyncIterator[EngineOutput]]


def max_attempts() -> int:
    """``DYN_RESUME_MAX``: resume attempts per stream (0 disables
    mid-stream failover entirely — breaks surface as before)."""
    return int(env_float("DYN_RESUME_MAX", 2, minimum=0.0))


def stall_budget() -> float:
    """``DYN_RESUME_STALL``: seconds without a frame before a live
    connection is declared wedged (0 disables the stall detector; breaks
    then require a transport-level failure). Inter-frame, so it bounds the
    longest decode-step gap, not total stream duration — and it must stay
    well above the worst legitimate prefill time."""
    return env_float("DYN_RESUME_STALL", 30.0, minimum=0.0)


def resumable(e: BaseException) -> bool:
    """A break worth resuming: transport-class 502/503 with no machine
    ``reason``. Typed decisions (overload sheds, router fast-fail,
    quota rejects — all reason-carrying) and deadline 504s are final."""
    return (isinstance(e, EngineError)
            and e.code in (502, 503)
            and getattr(e, "reason", None) is None)


def _resume_request(orig: BackendInput, base_tokens: List[int],
                    emitted: List[int], orig_max: Optional[int],
                    orig_min: Optional[int]) -> BackendInput:
    """The re-entry request: prompt + emitted as the effective prefix,
    token budgets re-derived from the ORIGINAL grant (the dead worker's
    output already spent part of it). The stale donor stamp is cleared —
    the re-election routes against the post-death registry."""
    req = copy.copy(orig)
    req.stop = copy.copy(orig.stop)
    req.token_ids = list(base_tokens) + list(emitted)
    req.resume_pos = len(emitted)
    if orig_max is not None:
        req.stop.max_tokens = orig_max - len(emitted)
    if orig_min:
        req.stop.min_tokens = max(0, orig_min - len(emitted))
    req.kv_donor = 0
    req.kv_donor_blocks = 0
    return req


async def _reap(agen) -> None:
    """Close a broken attempt's stream so its socket/tasks release before
    the next attempt dispatches (never let teardown mask the break)."""
    aclose = getattr(agen, "aclose", None)
    if aclose is None:
        return
    try:
        await aclose()
    except Exception:  # noqa: BLE001 - the break already surfaced
        log.debug("broken stream close failed", exc_info=True)


async def run(dispatch: Dispatch, request: BackendInput, context: Context,
              breaker=None) -> AsyncIterator[EngineOutput]:
    """Drive ``dispatch`` to stream completion, transparently re-entering
    it on resumable breaks. ``breaker`` (the worker client's
    :class:`~..runtime.circuit_breaker.InstanceBreaker`) takes the hit
    for stall-class breaks."""
    stage = stage_metrics()
    base_tokens = list(request.token_ids)
    orig_max = request.stop.max_tokens
    orig_min = request.stop.min_tokens
    emitted: List[int] = []
    exclude: Set[int] = set()
    attempt = 0
    limit = max_attempts()
    stall = stall_budget()
    cur = {"iid": None}
    t_break: Optional[float] = None

    while True:
        agen = dispatch(request, context, exclude, attempt,
                        lambda iid: cur.__setitem__("iid", iid))
        broke: Optional[EngineError] = None
        stalled = False
        got_any = False
        try:
            it = agen.__aiter__()
            while True:
                try:
                    if stall:
                        item = await asyncio.wait_for(it.__anext__(), stall)
                    else:
                        # stall detector off: boundedness falls back to the
                        # deadline layer inside Client.generate
                        item = await it.__anext__()
                except StopAsyncIteration:
                    return
                except asyncio.TimeoutError:
                    stalled = True
                    break
                if attempt and not got_any:
                    # the replacement worker's first frame: the resume
                    # worked — the pause the client saw is the metric
                    stage.stream_resumes.inc("resumed")
                    if t_break is not None:
                        stage.resume_latency.observe(
                            value=time.monotonic() - t_break)
                    note_event("stream.resume", context=context.id,
                               attempt=attempt, outcome="resumed",
                               emitted=len(emitted))
                got_any = True
                if item.token_ids:
                    emitted.extend(item.token_ids)
                yield item
                if item.finish_reason is not None:
                    return
        except EngineError as e:
            if not resumable(e):
                await _reap(agen)
                raise
            broke = e

        # ---- the stream broke: decide whether / how to re-enter --------
        await _reap(agen)
        t_break = time.monotonic()
        attempt += 1
        iid = cur["iid"]
        cur["iid"] = None
        why = "stall" if stalled else f"break({broke.code})"
        if stalled and iid is not None and breaker is not None:
            # stall-class breaks feed the per-instance circuit breaker —
            # transport breaks already counted inside Client.generate, but
            # a wedged worker never errors the socket, so without this hit
            # it keeps receiving fresh streams until its lease dies
            breaker.record_failure(iid)
        if iid is not None:
            exclude.add(iid)
        note_event("stream.resume", context=context.id, attempt=attempt,
                   outcome="resuming", why=why, emitted=len(emitted),
                   instance=f"{iid:x}" if iid is not None else "?")
        if attempt > limit:
            stage.stream_resumes.inc("exhausted")
            note_event("stream.resume", context=context.id,
                       attempt=attempt, outcome="exhausted")
            raise EngineError(
                f"stream broke {attempt} time(s) (last: {why}); resume "
                f"budget DYN_RESUME_MAX={limit} exhausted", 503,
                stage=RESUME_STAGE, reason="resume_exhausted") from broke
        if dl.expired(context.deadline):
            # the retry loop re-derives remaining budget from the ORIGINAL
            # wire deadline — a resume never restarts the clock
            stage.stream_resumes.inc("expired")
            note_event("stream.resume", context=context.id,
                       attempt=attempt, outcome="expired")
            raise dl.expire(RESUME_STAGE, context.deadline) from broke
        if orig_max is not None and len(emitted) >= orig_max:
            # the dead worker emitted the full token budget but its finish
            # frame died with the connection: close the stream ourselves
            # instead of dispatching a zero-budget request
            yield EngineOutput(finish_reason=FinishReason.LENGTH)
            return
        log.warning("resuming stream %s (attempt %d/%d, %s on instance "
                    "%s, %d tokens emitted)", context.id, attempt, limit,
                    why, f"{iid:x}" if iid is not None else "?",
                    len(emitted))
        request = _resume_request(request, base_tokens, emitted,
                                  orig_max, orig_min)
