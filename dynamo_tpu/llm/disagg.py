"""Disaggregated prefill/decode serving: queue, decision router, protocol.

The decode worker receives every request. For long, cold prompts it enqueues a
:class:`RemotePrefillRequest` on the shared prefill queue (dynstore work queue
— the JetStream role) instead of prefilling locally; a prefill worker pulls
the queue, computes the prompt KV on its own TPU slice and pushes the blocks
straight to the decode worker's ``kv_receive`` endpoint over the data plane
(the NIXL-RDMA role, host-staged over DCN on TPU). The decode worker then
enters the sequence directly into its decode batch.

The local-vs-remote decision and its live-reloadable threshold mirror the
reference's DisaggregatedRouter (lib/llm/src/disagg_router.rs:146-262:
``prefill_length - prefix_hit_length > max_local_prefill_length`` and queue
depth below ``max_prefill_queue_size``; etcd-watched config at
lib/llm/src/disagg_router.rs:38-143). The queue protocol mirrors
examples/llm/utils/nats_queue.py:27-150; the request shape mirrors the vLLM
patch's RemotePrefillRequest (patch:3716-3789).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..utils.overload import (OverloadError, PRIORITIES,
                              PRIORITY_INTERACTIVE, ServiceTimeEstimator,
                              should_shed)
from ..utils.prometheus import stage_metrics
from ..utils.tracing import extract_wire, get_tracer, wire_context

log = logging.getLogger("dynamo_tpu.disagg")

DISAGG_CONFIG_PREFIX = "disagg/"  # store key: disagg/{namespace}/{model}


def disagg_config_key(namespace: str, model: str = "default") -> str:
    return f"{DISAGG_CONFIG_PREFIX}{namespace}/{model}"


def prefill_queue_name(namespace: str,
                       priority: str = PRIORITY_INTERACTIVE) -> str:
    """Per-priority queue names: interactive keeps the legacy name (old
    producers/consumers interoperate unchanged), batch gets a sibling."""
    base = f"{namespace}.prefill"
    if priority and priority != PRIORITY_INTERACTIVE:
        return f"{base}.{priority}"
    return base


def prefill_queue_names(namespace: str) -> List[str]:
    """Every priority's queue — depth readers (planner, dyntop) sum these."""
    return [prefill_queue_name(namespace, p) for p in PRIORITIES]


@dataclass
class RemotePrefillRequest:
    """One unit of prefill work handed from a decode worker to the queue.

    ``decode_worker_id`` lets the prefill worker route the computed KV back
    with direct addressing; ``request`` is the full BackendInput dict so the
    prefill engine can honour sampling for the first generated token.
    """

    request_id: str
    decode_worker_id: int
    request: Dict[str, Any]
    prefix_hit_tokens: int = 0
    attempts: int = 0
    # overload-control class: routes the job to its priority's queue;
    # consumers drain interactive strictly first
    priority: str = PRIORITY_INTERACTIVE
    # span context ([trace_id, parent_span_id]) + enqueue wall-clock: the
    # prefill worker parents its spans under the decode worker's and turns
    # the enqueue->dequeue gap into the queue-wait span/histogram
    trace: Optional[List[Optional[str]]] = None
    enqueued_at: float = 0.0
    # end-to-end deadline (absolute time.time()): a job that expires while
    # queued is acked-and-dropped at dequeue — never computed
    deadline: Optional[float] = None

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.time() >= self.deadline

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "RemotePrefillRequest":
        return cls(**json.loads(b.decode()))


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, os.environ.get(name))
        return default


class PrefillQueue:
    """Shared work queue of RemotePrefillRequests over the dynstore queue
    plane. Unacked messages are redelivered when a prefill worker dies
    mid-job (at-least-once, like the durable JetStream pull consumer).

    Overload control (utils/overload.py):

    - one queue PER PRIORITY; :meth:`dequeue` drains interactive strictly
      before batch;
    - hard depth bounds (``DYN_PREFILL_QUEUE_MAX``, batch's lower
      ``DYN_PREFILL_QUEUE_MAX_BATCH``; 0 = unbounded) enforced at enqueue;
    - predictive shedding at enqueue: when queue depth x the observed
      per-item remote-prefill service time already exceeds the job's
      remaining deadline, the enqueue raises :class:`OverloadError` in
      milliseconds instead of queueing work that is doomed to expire —
      the decode worker falls back to local prefill.
    """

    def __init__(self, store, namespace: str,
                 max_depth: Optional[int] = None,
                 max_depth_batch: Optional[int] = None):
        self.store = store
        self.namespace = namespace
        self.queue = prefill_queue_name(namespace)   # interactive/legacy
        self.queues = {p: prefill_queue_name(namespace, p)
                       for p in PRIORITIES}
        self.max_depth = _env_int("DYN_PREFILL_QUEUE_MAX", 0) \
            if max_depth is None else int(max_depth)
        if max_depth_batch is None:
            max_depth_batch = _env_int("DYN_PREFILL_QUEUE_MAX_BATCH",
                                       self.max_depth // 2)
        self.max_depth_batch = int(max_depth_batch)
        # observed full remote-prefill turnaround (decode-side), the
        # predictive shed's per-item service estimate
        self.service = ServiceTimeEstimator()
        self._pulls: Dict[str, asyncio.Task] = {}   # parked per-queue pulls
        self._msg_queue: Dict[int, str] = {}        # msg_id -> queue name

    def observe_service(self, seconds: float) -> None:
        self.service.observe(seconds)
        stage_metrics().stage_service.observe("prefill_remote",
                                              value=seconds)

    def _bound(self, priority: str) -> int:
        return self.max_depth_batch if priority != PRIORITY_INTERACTIVE \
            else self.max_depth

    async def enqueue(self, req: RemotePrefillRequest,
                      enforce_bounds: bool = True) -> int:
        qname = self.queues.get(req.priority, self.queue)
        if enforce_bounds:
            depth = await self.store.q_len(qname)
            if req.priority != PRIORITY_INTERACTIVE:
                # batch's (lower) bound counts TOTAL backlog: interactive
                # depth alone closes the door on batch — strictly prefer
                # interactive at every decision point
                depth += await self.store.q_len(self.queue)
            bound = self._bound(req.priority)
            svc = self.service.mean()
            if bound and depth >= bound:
                stage_metrics().queue_shed.inc("prefill_enqueue")
                raise OverloadError(
                    f"prefill queue full ({depth} >= {bound}, "
                    f"priority={req.priority})",
                    stage="prefill_enqueue", reason="queue_full",
                    retry_after=max(svc or 0.0, 0.05))
            remaining = None if req.deadline is None \
                else req.deadline - time.time()
            if should_shed(depth + 1, svc, remaining):
                stage_metrics().queue_shed.inc("prefill_enqueue")
                raise OverloadError(
                    f"prefill queue wait ~{(depth + 1) * (svc or 0):.2f}s "
                    f"exceeds the remaining deadline "
                    f"({remaining:.2f}s); shedding at enqueue",
                    stage="prefill_enqueue", reason="predicted_late",
                    retry_after=svc)
        if req.trace is None:
            req.trace = wire_context()
        if not req.enqueued_at:
            req.enqueued_at = time.time()
        return await self.store.q_push(qname, req.to_bytes())

    async def _pull_any(self) -> Tuple[int, bytes, str]:
        """One message from any priority queue, interactive strictly first.
        Keeps a PARKED pull per queue across calls (never cancelled mid-
        delivery — a cancelled pull could strand a delivered message until
        the connection closes); a message landing on the other queue's
        parked pull is simply returned by the next call."""
        while True:
            for p in PRIORITIES:
                q = self.queues[p]
                if q not in self._pulls:
                    self._pulls[q] = asyncio.ensure_future(
                        self.store.q_pull(q))
            tasks = [self._pulls[self.queues[p]] for p in PRIORITIES]
            # unbounded-ok: queue consumers park until work arrives by
            # design; drain cancels the dequeue() wrapper task
            await asyncio.wait(tasks, return_when=asyncio.FIRST_COMPLETED)
            for p in PRIORITIES:            # strict priority order
                q = self.queues[p]
                t = self._pulls.get(q)
                if t is not None and t.done():
                    del self._pulls[q]
                    exc = t.exception()
                    if exc is not None:
                        raise exc
                    msg_id, payload = t.result()
                    return msg_id, payload, q

    async def dequeue(self) -> tuple:
        """Blocks until work is available. Returns (msg_id, request);
        the caller MUST ack(msg_id) after the KV has been delivered.
        Jobs whose end-to-end deadline expired while queued are acked and
        dropped here — never handed to the engine (counted per stage in
        ``dyn_deadline_expiries_total{stage="prefill_dequeue"}``)."""
        while True:
            msg_id, payload, qname = await self._pull_any()
            self._msg_queue[msg_id] = qname
            req = RemotePrefillRequest.from_bytes(payload)
            if not req.expired:
                break
            await self.ack(msg_id)
            stage_metrics().deadline_expiries.inc("prefill_dequeue")
            log.info("dropping expired prefill job %s "
                     "(deadline passed while queued)", req.request_id)
        if req.enqueued_at:
            # queue wait, measured across processes on wall clocks (skew
            # bounds accuracy; clamp so a skewed clock never goes negative)
            now = time.time()
            wait = max(0.0, now - req.enqueued_at)
            stage_metrics().queue_wait.observe(value=wait)
            get_tracer().record(
                "prefill.queue_wait", start=min(req.enqueued_at, now),
                end=now,
                parent=extract_wire(req.trace,
                                    default_trace_id=req.request_id),
                request_id=req.request_id, attempts=req.attempts)
        return msg_id, req

    async def ack(self, msg_id: int) -> None:
        await self.store.q_ack(self._msg_queue.pop(msg_id, self.queue),
                               msg_id)

    async def size(self) -> int:
        total = 0
        for q in self.queues.values():
            total += await self.store.q_len(q)
        return total

    def close(self) -> None:
        """Cancel parked pulls (worker drain / tests). Any message a
        cancelled pull had already been handed is requeued when this
        client's store connection closes (at-least-once)."""
        for t in self._pulls.values():
            t.cancel()
        self._pulls.clear()

    # ------------------------------------------------------------------
    # cancellation: the submitter gave up (timeout / client gone). A
    # tombstone key lets prefill workers drop the job at dequeue instead of
    # computing KV nobody will accept.
    def _cancel_key(self, request_id: str) -> str:
        return f"{self.queue}/cancelled/{request_id}"

    async def cancel(self, request_id: str, ttl: float = 600.0) -> None:
        # TTL-leased so tombstones for jobs already dequeued (and thus never
        # consumed at dequeue) don't accumulate in the store forever
        lease = await self.store.lease_grant(ttl=ttl, auto_keepalive=False)
        await self.store.put(self._cancel_key(request_id), b"1", lease=lease)

    async def consume_cancelled(self, request_id: str) -> bool:
        """Check-and-clear the tombstone. True => drop the job unprocessed."""
        if await self.store.get(self._cancel_key(request_id)) is not None:
            await self.store.delete(self._cancel_key(request_id))
            return True
        return False


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 1000
    max_prefill_queue_size: int = 2

    def to_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


class DisaggRouter:
    """The local-vs-remote prefill decision, with the threshold live-reloaded
    from the store (set via ``dynamo-ctl disagg set``)."""

    def __init__(self, namespace: str, model: str = "default",
                 config: Optional[DisaggConfig] = None):
        self.namespace = namespace
        self.model = model
        self.config = config or DisaggConfig()

    def length_exceeds_local(self, prefill_length: int,
                             prefix_hit_length: int) -> bool:
        """Cheap first-stage check (no queue RPC needed)."""
        return (prefill_length - prefix_hit_length
                > self.config.max_local_prefill_length)

    def should_prefill_remote(self, prefill_length: int,
                              prefix_hit_length: int,
                              queue_size: int) -> bool:
        return (self.length_exceeds_local(prefill_length, prefix_hit_length)
                and queue_size < self.config.max_prefill_queue_size)

    # ------------------------------------------------------------------
    async def start(self, store) -> "DisaggRouter":
        """Load current config and watch the key for live updates."""
        key = disagg_config_key(self.namespace, self.model)

        async def on_change(k: str, value: Optional[bytes], deleted: bool):
            # prefix watch: ignore sibling models whose name extends ours
            if k == key and not deleted and value:
                self._apply(value)

        snapshot = await store.watch_prefix(key, on_change)
        for k, value in snapshot:
            if k == key:
                self._apply(value)
        return self

    def _apply(self, value: bytes) -> None:
        try:
            d = json.loads(value.decode())
            self.config = DisaggConfig(
                max_local_prefill_length=int(
                    d.get("max_local_prefill_length",
                          self.config.max_local_prefill_length)),
                max_prefill_queue_size=int(
                    d.get("max_prefill_queue_size",
                          self.config.max_prefill_queue_size)))
            log.info("disagg config updated: %s", self.config)
        except (ValueError, json.JSONDecodeError):
            log.warning("ignoring malformed disagg config: %r", value)


async def set_disagg_config(store, namespace: str, config: DisaggConfig,
                            model: str = "default") -> None:
    await store.put(disagg_config_key(namespace, model),
                    json.dumps(config.to_dict()).encode())
