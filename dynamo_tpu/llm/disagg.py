"""Disaggregated prefill/decode serving: queue, decision router, protocol.

The decode worker receives every request. For long, cold prompts it enqueues a
:class:`RemotePrefillRequest` on the shared prefill queue (dynstore work queue
— the JetStream role) instead of prefilling locally; a prefill worker pulls
the queue, computes the prompt KV on its own TPU slice and pushes the blocks
straight to the decode worker's ``kv_receive`` endpoint over the data plane
(the NIXL-RDMA role, host-staged over DCN on TPU). The decode worker then
enters the sequence directly into its decode batch.

The local-vs-remote decision and its live-reloadable threshold mirror the
reference's DisaggregatedRouter (lib/llm/src/disagg_router.rs:146-262:
``prefill_length - prefix_hit_length > max_local_prefill_length`` and queue
depth below ``max_prefill_queue_size``; etcd-watched config at
lib/llm/src/disagg_router.rs:38-143). The queue protocol mirrors
examples/llm/utils/nats_queue.py:27-150; the request shape mirrors the vLLM
patch's RemotePrefillRequest (patch:3716-3789).
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.prometheus import stage_metrics
from ..utils.tracing import extract_wire, get_tracer, wire_context

log = logging.getLogger("dynamo_tpu.disagg")

DISAGG_CONFIG_PREFIX = "disagg/"  # store key: disagg/{namespace}/{model}


def disagg_config_key(namespace: str, model: str = "default") -> str:
    return f"{DISAGG_CONFIG_PREFIX}{namespace}/{model}"


def prefill_queue_name(namespace: str) -> str:
    return f"{namespace}.prefill"


@dataclass
class RemotePrefillRequest:
    """One unit of prefill work handed from a decode worker to the queue.

    ``decode_worker_id`` lets the prefill worker route the computed KV back
    with direct addressing; ``request`` is the full BackendInput dict so the
    prefill engine can honour sampling for the first generated token.
    """

    request_id: str
    decode_worker_id: int
    request: Dict[str, Any]
    prefix_hit_tokens: int = 0
    attempts: int = 0
    # span context ([trace_id, parent_span_id]) + enqueue wall-clock: the
    # prefill worker parents its spans under the decode worker's and turns
    # the enqueue->dequeue gap into the queue-wait span/histogram
    trace: Optional[List[Optional[str]]] = None
    enqueued_at: float = 0.0
    # end-to-end deadline (absolute time.time()): a job that expires while
    # queued is acked-and-dropped at dequeue — never computed
    deadline: Optional[float] = None

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.time() >= self.deadline

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "RemotePrefillRequest":
        return cls(**json.loads(b.decode()))


class PrefillQueue:
    """Shared work queue of RemotePrefillRequests over the dynstore queue
    plane. Unacked messages are redelivered when a prefill worker dies
    mid-job (at-least-once, like the durable JetStream pull consumer)."""

    def __init__(self, store, namespace: str):
        self.store = store
        self.queue = prefill_queue_name(namespace)

    async def enqueue(self, req: RemotePrefillRequest) -> int:
        if req.trace is None:
            req.trace = wire_context()
        if not req.enqueued_at:
            req.enqueued_at = time.time()
        return await self.store.q_push(self.queue, req.to_bytes())

    async def dequeue(self) -> tuple:
        """Blocks until work is available. Returns (msg_id, request);
        the caller MUST ack(msg_id) after the KV has been delivered.
        Jobs whose end-to-end deadline expired while queued are acked and
        dropped here — never handed to the engine (counted per stage in
        ``dyn_deadline_expiries_total{stage="prefill_dequeue"}``)."""
        while True:
            msg_id, payload = await self.store.q_pull(self.queue)
            req = RemotePrefillRequest.from_bytes(payload)
            if not req.expired:
                break
            await self.ack(msg_id)
            stage_metrics().deadline_expiries.inc("prefill_dequeue")
            log.info("dropping expired prefill job %s "
                     "(deadline passed while queued)", req.request_id)
        if req.enqueued_at:
            # queue wait, measured across processes on wall clocks (skew
            # bounds accuracy; clamp so a skewed clock never goes negative)
            now = time.time()
            wait = max(0.0, now - req.enqueued_at)
            stage_metrics().queue_wait.observe(value=wait)
            get_tracer().record(
                "prefill.queue_wait", start=min(req.enqueued_at, now),
                end=now,
                parent=extract_wire(req.trace,
                                    default_trace_id=req.request_id),
                request_id=req.request_id, attempts=req.attempts)
        return msg_id, req

    async def ack(self, msg_id: int) -> None:
        await self.store.q_ack(self.queue, msg_id)

    async def size(self) -> int:
        return await self.store.q_len(self.queue)

    # ------------------------------------------------------------------
    # cancellation: the submitter gave up (timeout / client gone). A
    # tombstone key lets prefill workers drop the job at dequeue instead of
    # computing KV nobody will accept.
    def _cancel_key(self, request_id: str) -> str:
        return f"{self.queue}/cancelled/{request_id}"

    async def cancel(self, request_id: str, ttl: float = 600.0) -> None:
        # TTL-leased so tombstones for jobs already dequeued (and thus never
        # consumed at dequeue) don't accumulate in the store forever
        lease = await self.store.lease_grant(ttl=ttl, auto_keepalive=False)
        await self.store.put(self._cancel_key(request_id), b"1", lease=lease)

    async def consume_cancelled(self, request_id: str) -> bool:
        """Check-and-clear the tombstone. True => drop the job unprocessed."""
        if await self.store.get(self._cancel_key(request_id)) is not None:
            await self.store.delete(self._cancel_key(request_id))
            return True
        return False


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 1000
    max_prefill_queue_size: int = 2

    def to_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


class DisaggRouter:
    """The local-vs-remote prefill decision, with the threshold live-reloaded
    from the store (set via ``dynamo-ctl disagg set``)."""

    def __init__(self, namespace: str, model: str = "default",
                 config: Optional[DisaggConfig] = None):
        self.namespace = namespace
        self.model = model
        self.config = config or DisaggConfig()

    def length_exceeds_local(self, prefill_length: int,
                             prefix_hit_length: int) -> bool:
        """Cheap first-stage check (no queue RPC needed)."""
        return (prefill_length - prefix_hit_length
                > self.config.max_local_prefill_length)

    def should_prefill_remote(self, prefill_length: int,
                              prefix_hit_length: int,
                              queue_size: int) -> bool:
        return (self.length_exceeds_local(prefill_length, prefix_hit_length)
                and queue_size < self.config.max_prefill_queue_size)

    # ------------------------------------------------------------------
    async def start(self, store) -> "DisaggRouter":
        """Load current config and watch the key for live updates."""
        key = disagg_config_key(self.namespace, self.model)

        async def on_change(k: str, value: Optional[bytes], deleted: bool):
            # prefix watch: ignore sibling models whose name extends ours
            if k == key and not deleted and value:
                self._apply(value)

        snapshot = await store.watch_prefix(key, on_change)
        for k, value in snapshot:
            if k == key:
                self._apply(value)
        return self

    def _apply(self, value: bytes) -> None:
        try:
            d = json.loads(value.decode())
            self.config = DisaggConfig(
                max_local_prefill_length=int(
                    d.get("max_local_prefill_length",
                          self.config.max_local_prefill_length)),
                max_prefill_queue_size=int(
                    d.get("max_prefill_queue_size",
                          self.config.max_prefill_queue_size)))
            log.info("disagg config updated: %s", self.config)
        except (ValueError, json.JSONDecodeError):
            log.warning("ignoring malformed disagg config: %r", value)


async def set_disagg_config(store, namespace: str, config: DisaggConfig,
                            model: str = "default") -> None:
    await store.put(disagg_config_key(namespace, model),
                    json.dumps(config.to_dict()).encode())
