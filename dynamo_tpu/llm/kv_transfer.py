"""Bulk KV-block movement between workers over the data plane.

Prefill→decode transfer rides the same TCP two-part codec as requests, but as
a *streaming request*: one JSON meta header (request id, first token, tensor
geometry) followed by 2·L binary parts — layer k then layer v, in layer order
— so the receiver can scatter layer l into its device pool while layer l+1 is
still in flight (the layer-pipelined CopyStream idea,
lib/llm/src/kv/layer.rs:619-1132). On TPU this is the host-staged DCN path
replacing the reference's NIXL RDMA plane (docs/disagg_serving.md:58-91);
intra-slice movement stays inside XLA as collectives.

Sender: :func:`push_kv` (prefill worker). Receiver: :class:`KvReceiver`
(decode worker) — serves the ``kv_receive`` endpoint and hands the assembled
arrays to whoever is awaiting that request id.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import AsyncIterator, Dict, Optional, Tuple

import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
import numpy as np

from ..runtime.component import Client, StreamingRequest
from ..runtime.engine import Context
from ..utils.prometheus import stage_metrics
from ..utils.tracing import extract_wire, get_tracer, wire_context

log = logging.getLogger("dynamo_tpu.kv_transfer")

KV_RECEIVE_ENDPOINT = "kv_receive"


def _meta(request_id: str, first_token: int, first_logprob: float,
          k: np.ndarray) -> dict:
    L, T, H, D = k.shape
    return {
        "request_id": request_id,
        "first_token": int(first_token),
        "first_logprob": float(first_logprob),
        "layers": int(L), "tokens": int(T),
        "kv_heads": int(H), "head_dim": int(D),
        "dtype": str(k.dtype),
        # span context rides the meta header (not just the wire control) so
        # the receive side stitches even on planes that drop control fields
        "trace": wire_context(),
    }


async def push_kv(client: Client, decode_worker_id: int, request_id: str,
                  first_token: int, first_logprob: float,
                  k: np.ndarray, v: np.ndarray,
                  context: Optional[Context] = None) -> dict:
    """Stream a sequence's prompt KV ([L,T,Hkv,Dh] each) to the decode
    worker that owns ``request_id``. Returns the receiver's ack."""
    meta = _meta(request_id, first_token, first_logprob, k)
    nbytes = k.nbytes + v.nbytes

    async def parts() -> AsyncIterator[bytes]:
        from ..utils import faults

        for layer in range(k.shape[0]):
            # chaos hook: kill/stall the KV stream mid-flight (per part)
            await faults.fire("kv.push.part")
            yield k[layer].tobytes()
            yield v[layer].tobytes()

    stage = stage_metrics()
    ack = None
    async with get_tracer().span("kv.push", trace_id=request_id,
                                 bytes=nbytes, tokens=meta["tokens"],
                                 layers=meta["layers"]):
        # restamp inside the scope so the receiver's kv.receive span
        # parents under kv.push, not under this function's caller
        meta["trace"] = wire_context()
        t0 = time.monotonic()
        async for resp in client.generate(meta, context, mode="direct",
                                          instance_id=decode_worker_id,
                                          parts=parts()):
            ack = resp
        stage.kv_transfer.observe("send", value=time.monotonic() - t0)
        stage.kv_transfer_bytes.inc("send", amount=nbytes)
    return ack or {}


class RemotePrefillError(RuntimeError):
    pass


async def _cancel_quietly(queue, request_id: str) -> None:
    """Tombstone a queued job, best-effort: a store mid-outage must not
    mask the caller's own outcome (timeout / client stop)."""
    try:
        await queue.cancel(request_id)
    except Exception:  # noqa: BLE001
        log.debug("prefill cancel tombstone for %s failed (store down?)",
                  request_id)


async def await_remote_kv(ctx: Context, fut: asyncio.Future, queue,
                          receiver: "KvReceiver",
                          remote_timeout: float):
    """Decode-side wait for the remotely computed KV, racing client-stop,
    the request's end-to-end deadline, and the fallback timeout. Returns
    the KV tuple, or None => fall back to local prefill. An expired
    deadline raises a 504 naming the stage (``decode_kv_wait``) — there is
    no point prefilling locally for a caller that already timed out."""
    from ..runtime import deadline as dl

    stop = asyncio.ensure_future(ctx.stopped())
    try:
        timeout = remote_timeout
        rem = dl.remaining(ctx.deadline)
        deadline_first = rem is not None and rem < timeout
        if deadline_first:
            timeout = rem
        done, _ = await asyncio.wait(
            {fut, stop}, timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED)
        if fut in done:
            return fut.result()  # may raise RemotePrefillError
        if stop in done:
            await _cancel_quietly(queue, ctx.id)
            raise asyncio.CancelledError
        # tombstone the queued job so a prefill worker doesn't burn a
        # full prompt prefill on KV nobody will accept
        await _cancel_quietly(queue, ctx.id)
        if deadline_first or dl.expired(ctx.deadline):
            raise dl.expire("decode_kv_wait", ctx.deadline)
        log.warning("remote prefill for %s timed out after %.0fs; "
                    "prefilling locally", ctx.id, remote_timeout)
        return None
    finally:
        stop.cancel()
        receiver.abandon(ctx.id)


async def push_kv_error(client: Client, decode_worker_id: int,
                        request_id: str, message: str) -> None:
    """Tell the decode worker its remote prefill failed permanently so the
    parked request errors out instead of waiting forever."""
    meta = {"request_id": request_id, "error": message}

    async def no_parts() -> AsyncIterator[bytes]:
        return
        yield  # pragma: no cover

    async for _ in client.generate(meta, mode="direct",
                                   instance_id=decode_worker_id,
                                   parts=no_parts()):
        pass


class KvReceiver:
    """Decode-worker side: collects streamed KV for requests this worker
    parked while their prefill ran remotely."""

    def __init__(self) -> None:
        self._pending: Dict[str, asyncio.Future] = {}

    def expect(self, request_id: str) -> asyncio.Future:
        """Register interest; the future resolves to
        (k, v, first_token, first_logprob) when the KV arrives."""
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        return fut

    def abandon(self, request_id: str) -> None:
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    async def handler(self, request: StreamingRequest, ctx: Context):
        meta = request.meta
        rid = meta["request_id"]
        if meta.get("error"):
            async for _ in request.parts:
                pass
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_exception(RemotePrefillError(meta["error"]))
            yield {"ok": True}
            return
        L, T = meta["layers"], meta["tokens"]
        H, D = meta["kv_heads"], meta["head_dim"]
        dtype = np.dtype(meta["dtype"])
        k = np.empty((L, T, H, D), dtype)
        v = np.empty((L, T, H, D), dtype)
        i = 0
        nbytes = 0
        t0 = time.monotonic()
        recv_span = get_tracer().start_span(
            "kv.receive", parent=extract_wire(meta.get("trace"), rid),
            request_id=rid, tokens=T, layers=L)
        try:
            async for part in request.parts:
                layer, is_v = divmod(i, 2)
                if layer >= L:
                    raise ValueError(f"kv stream for {rid}: too many parts")
                arr = np.frombuffer(part, dtype).reshape(T, H, D)
                (v if is_v else k)[layer] = arr
                i += 1
                nbytes += len(part)
            if i != 2 * L:
                raise ValueError(
                    f"kv stream for {rid}: got {i}/{2 * L} parts")
        except BaseException:
            get_tracer().finish(recv_span, status="error")
            raise
        if recv_span is not None:
            recv_span.attrs["bytes"] = nbytes
        get_tracer().finish(recv_span)
        stage = stage_metrics()
        stage.kv_transfer.observe("recv", value=time.monotonic() - t0)
        stage.kv_transfer_bytes.inc("recv", amount=nbytes)
        fut = self._pending.pop(rid, None)
        if fut is None or fut.done():
            log.warning("unexpected KV for request %s (client gone?)", rid)
            yield {"ok": False, "error": "no pending request"}
            return
        fut.set_result((k, v, meta["first_token"], meta["first_logprob"]))
        yield {"ok": True, "tokens": T}
