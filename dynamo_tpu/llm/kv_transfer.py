"""Bulk KV-block movement between workers over the data plane.

Prefill→decode transfer rides the same TCP two-part codec as requests, but as
a *streaming request*: one JSON meta header (request id, first token, tensor
geometry) followed by 2·L binary parts — layer k then layer v, in layer order
— so the receiver can scatter layer l into its device pool while layer l+1 is
still in flight (the layer-pipelined CopyStream idea,
lib/llm/src/kv/layer.rs:619-1132). On TPU this is the host-staged DCN path
replacing the reference's NIXL RDMA plane (docs/disagg_serving.md:58-91);
intra-slice movement stays inside XLA as collectives.

Sender: :func:`push_kv` (prefill worker). Receiver: :class:`KvReceiver`
(decode worker) — serves the ``kv_receive`` endpoint and hands the sequence
to whoever is awaiting that request id. With ``DYN_KV_STREAM`` (default on)
and an engine that supports it, the receiver drives a **layer-streamed
ingest**: each arriving layer's device scatter is enqueued on the engine
thread while later layers are still on the wire, and the awaited future
resolves once the final scatter is *enqueued* — never synced — so decode
step 1 overlaps the transfer tail instead of starting after it. A torn
stream (donor death, codec violation, abandoned waiter) aborts the ingest
with the partially-written pool pages released before anything referenced
them: attention can never observe a half-arrived prompt.

:class:`LayerStream` is the one assembler for the layer-major codec — the
disagg push above and the cluster peer-fetch receive path
(``kv_cluster/fetch.py``) both validate and dispatch arrivals through it.
Both ends account their bytes through the flow ledger
(``obs/flows.py``), which in turn feeds :func:`observe_pair_bw`, the
per-(src,dst) bandwidth EWMA behind the router's transfer-cost scoring.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from typing import AsyncIterator, Callable, Dict, Optional, Tuple

import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
import numpy as np

from ..obs import flightrec as _flightrec
from ..obs import incidents as _incidents
from ..obs.flows import record_flow
from ..runtime.component import Client, StreamingRequest
from ..runtime.engine import Context
from ..utils.knobs import env_float
from ..utils.prometheus import stage_metrics
from ..utils.tracing import extract_wire, get_tracer, wire_context

log = logging.getLogger("dynamo_tpu.kv_transfer")

KV_RECEIVE_ENDPOINT = "kv_receive"

#: per-pair bandwidth source label for senders that are not addressable
#: workers (the anonymous prefill-worker pool behind the queue)
ANON_SRC = "q"


def stream_enabled() -> bool:
    """``DYN_KV_STREAM`` (default on): layer-streamed ingest of disagg KV
    pushes. ``0`` restores the legacy full-arrival import — the bench
    harness's A/B switch."""
    return os.environ.get("DYN_KV_STREAM", "1").lower() in (
        "1", "true", "yes", "on")


# ---------------------------------------------------------------------------
# per-(src,dst) transfer bandwidth (receiver-side EWMA)
# ---------------------------------------------------------------------------

_pair_bw: Dict[Tuple[str, str], float] = {}
_pair_lock = threading.Lock()


def observe_pair_bw(src: str, dst: str, nbytes: int,
                    seconds: float) -> None:
    """Fold one observed transfer into the (src,dst) bandwidth EWMA and
    export it as ``llm_kv_pair_bw_bytes_per_s`` — the series the router's
    :class:`~.kv_cluster.registry.TransferCostModel` reads back out of
    the merged stage dumps."""
    if nbytes <= 0 or seconds <= 0:
        return
    alpha = env_float("DYN_KV_BW_ALPHA", 0.3, minimum=0.0)
    alpha = min(alpha, 1.0)
    bw = nbytes / seconds
    with _pair_lock:
        prev = _pair_bw.get((src, dst))
        cur = bw if prev is None else alpha * bw + (1.0 - alpha) * prev
        _pair_bw[(src, dst)] = cur
    stage_metrics().kv_pair_bw.set(src, dst, value=cur)
    # EWMA snapshot into the flight-recorder ring: an incident bundle
    # shows what bandwidth the placement signals were actually seeing
    _flightrec.note_event("kv.pair_bw", src=src, dst=dst,
                          bw=round(cur), sample_bw=round(bw))


# ---------------------------------------------------------------------------
# the layer-major codec assembler (disagg push + cluster fetch share it)
# ---------------------------------------------------------------------------

class RemotePrefillError(RuntimeError):
    pass


class KvStreamError(RemotePrefillError):
    """A KV stream violated the layer-major codec or tore mid-flight.
    Subclasses :class:`RemotePrefillError` so every waiter's existing
    typed-fallback path (local prefill) handles it unchanged."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"kv stream {reason}: {detail}")
        self.reason = reason


class LayerStream:
    """Incremental assembler for the layer-major two-part codec: 2·L
    parts, layer k then layer v, strictly in layer order. ``sink(layer,
    k, v)`` fires the moment a layer's pair is complete — while later
    layers are still in flight. :meth:`close` enforces completeness;
    every violation is a typed :class:`KvStreamError` naming the reason
    (the fallback counters' label)."""

    def __init__(self, layers: int,
                 sink: Callable[[int, np.ndarray, np.ndarray], None]):
        self.layers = int(layers)
        self.sink = sink
        self._i = 0
        self._k: Optional[np.ndarray] = None

    @property
    def parts_fed(self) -> int:
        return self._i

    @property
    def complete(self) -> bool:
        return self._i == 2 * self.layers

    def feed(self, arr: np.ndarray) -> None:
        """One wire part in arrival order (positional layer index)."""
        layer, is_v = divmod(self._i, 2)
        if layer >= self.layers:
            raise KvStreamError(
                "over_count",
                f"part {self._i} beyond {2 * self.layers} expected")
        if not is_v:
            self._k = arr
        else:
            k, self._k = self._k, None
            self.sink(layer, k, arr)
        self._i += 1

    def feed_layer(self, layer: int, k: np.ndarray,
                   v: np.ndarray) -> None:
        """Explicit-index entry point (sender-declared layer indices):
        the codec is strictly in-order, so a skipped or repeated index is
        a torn stream, not a reordering to tolerate."""
        if self._i % 2 or layer != self._i // 2:
            raise KvStreamError(
                "out_of_order",
                f"layer {layer} arrived at codec position {self._i}")
        self.feed(k)
        self.feed(v)

    def close(self) -> None:
        if not self.complete:
            raise KvStreamError(
                "truncated",
                f"got {self._i}/{2 * self.layers} parts")


def _meta(request_id: str, first_token: int, first_logprob: float,
          k: np.ndarray, src_worker: Optional[int] = None) -> dict:
    L, T, H, D = k.shape
    return {
        "request_id": request_id,
        "first_token": int(first_token),
        "first_logprob": float(first_logprob),
        "layers": int(L), "tokens": int(T),
        "kv_heads": int(H), "head_dim": int(D),
        "dtype": str(k.dtype),
        # sender identity for the receiver's per-pair bandwidth EWMA
        # (absent/0 = the anonymous prefill pool)
        "src": f"{src_worker:x}" if src_worker else ANON_SRC,
        # span context rides the meta header (not just the wire control) so
        # the receive side stitches even on planes that drop control fields
        "trace": wire_context(),
    }


async def push_kv(client: Client, decode_worker_id: int, request_id: str,
                  first_token: int, first_logprob: float,
                  k: np.ndarray, v: np.ndarray,
                  context: Optional[Context] = None,
                  src_worker: Optional[int] = None) -> dict:
    """Stream a sequence's prompt KV ([L,T,Hkv,Dh] each) to the decode
    worker that owns ``request_id``. Returns the receiver's ack."""
    meta = _meta(request_id, first_token, first_logprob, k, src_worker)
    nbytes = k.nbytes + v.nbytes

    async def parts() -> AsyncIterator[bytes]:
        from ..utils import faults

        for layer in range(k.shape[0]):
            # chaos hook: kill/stall the KV stream mid-flight (per part)
            await faults.fire("kv.push.part")
            yield k[layer].tobytes()
            yield v[layer].tobytes()

    stage = stage_metrics()
    ack = None
    async with get_tracer().span("kv.push", trace_id=request_id,
                                 bytes=nbytes, tokens=meta["tokens"],
                                 layers=meta["layers"]):
        # restamp inside the scope so the receiver's kv.receive span
        # parents under kv.push, not under this function's caller
        meta["trace"] = wire_context()
        t0 = time.monotonic()
        async for resp in client.generate(meta, context, mode="direct",
                                          instance_id=decode_worker_id,
                                          parts=parts()):
            ack = resp
        elapsed = time.monotonic() - t0
        stage.kv_transfer.observe("send", value=elapsed)
        stage.kv_transfer_bytes.inc("send", amount=nbytes)
        record_flow("disagg_push", nbytes, elapsed,
                    src=meta["src"], dst=f"{decode_worker_id:x}",
                    trace_id=request_id)
    return ack or {}


async def _cancel_quietly(queue, request_id: str) -> None:
    """Tombstone a queued job, best-effort: a store mid-outage must not
    mask the caller's own outcome (timeout / client stop)."""
    try:
        await queue.cancel(request_id)
    except Exception:  # noqa: BLE001
        log.debug("prefill cancel tombstone for %s failed (store down?)",
                  request_id)


def _discard_streamed(fut: asyncio.Future) -> None:
    """A future that resolved while its waiter was giving up may hold a
    streamed-ingest handle whose sequence ALREADY entered decode; the
    waiter will never consume it, so the orphan must be cancelled (a
    buffered tuple result needs nothing — it's just host arrays)."""
    if not fut.done() or fut.cancelled() or fut.exception() is not None:
        return
    discard = getattr(fut.result(), "discard", None)
    if discard is not None:
        try:
            discard()
        except Exception:  # noqa: BLE001 - cleanup must not mask outcome
            log.exception("streamed-ingest discard failed")


async def await_remote_kv(ctx: Context, fut: asyncio.Future, queue,
                          receiver: "KvReceiver",
                          remote_timeout: float):
    """Decode-side wait for the remotely computed KV, racing client-stop,
    the request's end-to-end deadline, and the fallback timeout. Returns
    the KV tuple (buffered mode), a streamed-ingest handle (the sequence
    is already entering decode — consume it with
    ``engine.generate_streamed``), or None => fall back to local prefill.
    An expired deadline raises a 504 naming the stage
    (``decode_kv_wait``) — there is no point prefilling locally for a
    caller that already timed out."""
    from ..runtime import deadline as dl

    stop = asyncio.ensure_future(ctx.stopped())
    try:
        timeout = remote_timeout
        rem = dl.remaining(ctx.deadline)
        deadline_first = rem is not None and rem < timeout
        if deadline_first:
            timeout = rem
        done, _ = await asyncio.wait(
            {fut, stop}, timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED)
        if fut in done:
            return fut.result()  # may raise RemotePrefillError
        if stop in done:
            await _cancel_quietly(queue, ctx.id)
            _discard_streamed(fut)
            raise asyncio.CancelledError
        # tombstone the queued job so a prefill worker doesn't burn a
        # full prompt prefill on KV nobody will accept. The await can
        # let the in-flight stream FINISH (and a streamed ingest enter
        # decode): re-check the future after it — a race the outcome
        # branches below must each resolve, never leak
        await _cancel_quietly(queue, ctx.id)
        if deadline_first or dl.expired(ctx.deadline):
            _discard_streamed(fut)
            raise dl.expire("decode_kv_wait", ctx.deadline)
        if fut.done() and not fut.cancelled() \
                and fut.exception() is None:
            # the arrival won the race against the tombstone write:
            # serve the completed transfer instead of discarding it
            return fut.result()
        log.warning("remote prefill for %s timed out after %.0fs; "
                    "prefilling locally", ctx.id, remote_timeout)
        return None
    finally:
        stop.cancel()
        receiver.abandon(ctx.id)


async def push_kv_error(client: Client, decode_worker_id: int,
                        request_id: str, message: str) -> None:
    """Tell the decode worker its remote prefill failed permanently so the
    parked request errors out instead of waiting forever."""
    meta = {"request_id": request_id, "error": message}

    async def no_parts() -> AsyncIterator[bytes]:
        return
        yield  # pragma: no cover

    # dynalint: ok(flow-accounting) zero-byte error signal — the stream
    # carries no KV payload, there are no bytes to meter
    async for _ in client.generate(meta, mode="direct",
                                   instance_id=decode_worker_id,
                                   parts=no_parts()):
        pass


class KvReceiver:
    """Decode-worker side: collects streamed KV for requests this worker
    parked while their prefill ran remotely.

    Two ingest modes per request:

    - **streamed** (``DYN_KV_STREAM`` + an ingest handle registered via
      :meth:`expect`): layer pairs are forwarded to the engine the moment
      they complete, the future resolves to the ingest handle once the
      final scatter is enqueued, and any mid-stream failure aborts the
      engine-side ingest (pool pages released unseen) before the waiter
      is failed over to local prefill;
    - **buffered** (legacy / no handle / handle declined the geometry):
      the full [L,T,Hkv,Dh] arrays assemble in host memory and the future
      resolves to ``(k, v, first_token, first_logprob)`` after the last
      part, exactly the old contract.
    """

    def __init__(self, worker_id: int = 0) -> None:
        self._pending: Dict[str, asyncio.Future] = {}
        self._ingests: Dict[str, object] = {}
        self._dst = f"{worker_id:x}" if worker_id else str(os.getpid())

    def expect(self, request_id: str,
               ingest: Optional[object] = None) -> asyncio.Future:
        """Register interest; the future resolves to
        (k, v, first_token, first_logprob) — or to ``ingest`` itself when
        the arrival was streamed straight into the engine through it."""
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        if ingest is not None:
            self._ingests[request_id] = ingest
        return fut

    def abandon(self, request_id: str) -> None:
        """Waiter gave up (timeout / deadline / client stop) or is done
        consuming. A BEGUN-but-unfinished ingest must be aborted HERE,
        before the caller's local-prefill fallback resubmits the same
        seq_id: the abort rides the engine's FIFO inbox ahead of the
        resubmit, so the half-streamed pool sequence is released first
        (``KvIngest.abort`` is a no-op for finished/never-begun ingests,
        so the success path's abandon leaves the live stream alone)."""
        ingest = self._ingests.pop(request_id, None)
        if ingest is not None:
            try:
                ingest.abort()
            except Exception:  # noqa: BLE001 - cleanup must not mask
                log.exception("kv ingest abort failed for %s", request_id)
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    def _fail(self, rid: str, ingest, exc: KvStreamError) -> None:
        """Torn-stream cleanup: abort the engine-side ingest FIRST (the
        partially-scattered pool pages release before any waiter can
        race a local prefill into the same engine), then fail the waiter
        over to local prefill and count the reason."""
        if ingest is not None:
            try:
                ingest.abort()
            except Exception:  # noqa: BLE001 - cleanup must not mask
                log.exception("kv ingest abort failed for %s", rid)
        stage_metrics().kv_stream_fallbacks.inc(exc.reason)
        _flightrec.note_event("kv.torn", rid=rid, reason=exc.reason)
        # a torn disagg stream is an incident trigger: every process that
        # touched this request freezes and dumps its rings
        _incidents.trigger("torn_stream", trace_id=rid, cause=exc.reason)
        fut = self._pending.pop(rid, None)
        self._ingests.pop(rid, None)
        if fut is not None and not fut.done():
            fut.set_exception(exc)

    async def handler(self, request: StreamingRequest, ctx: Context):
        meta = request.meta
        rid = meta["request_id"]
        if meta.get("error"):
            async for _ in request.parts:
                pass
            self._ingests.pop(rid, None)
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_exception(RemotePrefillError(meta["error"]))
            yield {"ok": True}
            return
        L, T = meta["layers"], meta["tokens"]
        H, D = meta["kv_heads"], meta["head_dim"]
        dtype = np.dtype(meta["dtype"])
        fut = self._pending.get(rid)
        ingest = self._ingests.get(rid) if stream_enabled() else None
        if ingest is not None and (fut is None or fut.done()
                                   or not ingest.begin(meta)):
            # waiter gone, or the engine declined the stream's geometry:
            # assemble buffered (the legacy path validates/fails later)
            ingest = None
        k = v = None
        if ingest is None:
            k = np.empty((L, T, H, D), dtype)
            v = np.empty((L, T, H, D), dtype)

            def sink(layer: int, ka: np.ndarray, va: np.ndarray) -> None:
                k[layer] = ka
                v[layer] = va
        else:
            def sink(layer: int, ka: np.ndarray, va: np.ndarray) -> None:
                ingest.layer(layer, ka, va)
        stream = LayerStream(L, sink)
        nbytes = 0
        t0 = time.monotonic()
        recv_span = get_tracer().start_span(
            "kv.receive", parent=extract_wire(meta.get("trace"), rid),
            request_id=rid, tokens=T, layers=L,
            streamed=ingest is not None)
        # watchdog heartbeat: an in-flight stream making no layer
        # progress inside the budget is a wedged transfer (stall:transfer)
        hb_name = f"kv.recv:{rid}"
        _flightrec.hb_begin(
            hb_name, stall="transfer", trace_id=rid,
            budget=env_float("DYN_WATCHDOG_TRANSFER", 5.0, minimum=0.1))
        try:
            async for part in request.parts:
                if fut is not None and fut.done():
                    # the waiter gave up mid-stream (deadline / client
                    # stop): abort the ingest and drain without feeding —
                    # no further pool writes for a request nobody owns
                    raise KvStreamError("abandoned",
                                        f"waiter for {rid} gone")
                stream.feed(np.frombuffer(part, dtype).reshape(T, H, D))
                nbytes += len(part)
                _flightrec.hb_progress(hb_name)
            stream.close()
        except KvStreamError as e:
            get_tracer().finish(recv_span, status="error")
            self._fail(rid, ingest, e)
            yield {"ok": False, "error": str(e)}
            return
        except BaseException as e:
            # transport tear (donor death mid-push): same cleanup, then
            # propagate so the plane surfaces the broken stream
            get_tracer().finish(recv_span, status="error")
            self._fail(rid, ingest, KvStreamError("torn", str(e)))
            raise
        finally:
            _flightrec.hb_end(hb_name)
        if recv_span is not None:
            recv_span.attrs["bytes"] = nbytes
        get_tracer().finish(recv_span)
        stage = stage_metrics()
        elapsed = time.monotonic() - t0
        stage.kv_transfer.observe("recv", value=elapsed)
        stage.kv_transfer_bytes.inc("recv", amount=nbytes)
        # the ledger feeds the per-pair EWMA (observe_pair_bw) itself —
        # one record accounts the link AND prices the router's pair
        record_flow("disagg_stream_rx", nbytes, elapsed,
                    src=meta.get("src") or ANON_SRC, dst=self._dst,
                    trace_id=rid)
        self._ingests.pop(rid, None)
        fut = self._pending.pop(rid, None)
        if fut is None or fut.done():
            if ingest is not None:
                # fully-arrived stream whose waiter vanished between the
                # last part and here: the ingest must not enter decode
                try:
                    ingest.abort()
                except Exception:  # noqa: BLE001
                    log.exception("kv ingest abort failed for %s", rid)
            log.warning("unexpected KV for request %s (client gone?)", rid)
            yield {"ok": False, "error": "no pending request"}
            return
        if ingest is not None:
            # the final scatter is ENQUEUED (engine thread drains the
            # command queue); resolve now — decode's first step chains on
            # the pool arrays by data dependency, no sync needed here
            ingest.finish(meta["first_token"], meta["first_logprob"])
            stage.kv_stream_ingests.inc()
            fut.set_result(ingest)
        else:
            fut.set_result((k, v, meta["first_token"],
                            meta["first_logprob"]))
        yield {"ok": True, "tokens": T, "streamed": ingest is not None}
