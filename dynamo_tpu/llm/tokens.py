"""Token-block chunking and chained block hashing.

The whole KV subsystem (router radix index, reuse pool, transfer protocol)
keys on fixed-size token blocks with two 64-bit hashes per block:

- ``block_hash``  — hash of the block's own tokens (position independent).
- ``sequence_hash`` — chained hash folding in the parent block's sequence
  hash, so equal sequence_hash ⇒ equal full prefix. This is what prefix
  matching and block reuse key on.

Reference capability: lib/llm/src/tokens.rs:30-226 (TokenBlock/TokenSequence)
and lib/llm/src/kv_router/indexer.rs:87-123 (xxh3 block hashing).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence

import xxhash

# Seed pinned so hashes are stable across processes/hosts (wire protocol).
_HASH_SEED = 1337


def hash_tokens(tokens: Sequence[int], seed: int = _HASH_SEED) -> int:
    """xxh3-64 over the little-endian u32 encoding of the tokens.

    Ids are masked to u32 so out-of-range values (which the preprocessor
    rejects at the API edge) can never raise from deep inside the KV path.
    """
    return xxhash.xxh3_64_intdigest(
        struct.pack(f"<{len(tokens)}I", *(t & 0xFFFFFFFF for t in tokens)),
        seed=seed,
    )


def chain_hash(parent_sequence_hash: Optional[int], block_hash: int) -> int:
    """Fold a block hash into the running sequence hash."""
    parent = parent_sequence_hash if parent_sequence_hash is not None else 0
    return xxhash.xxh3_64_intdigest(struct.pack("<QQ", parent, block_hash), seed=_HASH_SEED)


def lora_chain_root(lora_id: int) -> Optional[int]:
    """Root of the sequence-hash chain for an adapter.

    ``lora_id`` salts the chain at its ROOT, so every sequence hash
    downstream is adapter-distinct: identical tokens under different LoRA
    adapters can never alias in the radix index (ref carries lora_id
    through the C ABI, lib/bindings/c/src/lib.rs:253-283; folding it into
    the hash is the indexer-side half it left as a TODO,
    kv_router/indexer.rs:104-110). ``lora_id == 0`` (base model) keeps
    chains bit-identical to the unsalted protocol."""
    if not lora_id:
        return None
    return xxhash.xxh3_64_intdigest(
        struct.pack("<Q", lora_id & 0xFFFFFFFFFFFFFFFF), seed=_HASH_SEED ^ 0x10AA)


@dataclass(frozen=True)
class TokenBlock:
    """A full block of ``block_size`` tokens with its two hashes."""

    tokens: tuple
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: Optional[int] = None

    def __len__(self) -> int:
        return len(self.tokens)


@dataclass
class TokenSequence:
    """An append-only token stream chunked into hashed blocks.

    ``blocks`` holds completed blocks; ``partial`` the tail (< block_size).
    Appending tokens seals blocks as they fill, maintaining the hash chain.
    """

    block_size: int
    blocks: List[TokenBlock] = field(default_factory=list)
    partial: List[int] = field(default_factory=list)
    lora_id: int = 0            # salts the chain root (adapter-distinct)

    @classmethod
    def from_tokens(cls, tokens: Iterable[int], block_size: int,
                    lora_id: int = 0) -> "TokenSequence":
        seq = cls(block_size=block_size, lora_id=lora_id)
        seq.extend(tokens)
        return seq

    def extend(self, tokens: Iterable[int]) -> None:
        for t in tokens:
            self.append(int(t))

    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the newly sealed block if one completed."""
        self.partial.append(token)
        if len(self.partial) < self.block_size:
            return None
        parent = (self.blocks[-1].sequence_hash if self.blocks
                  else lora_chain_root(self.lora_id))
        bh = hash_tokens(self.partial)
        block = TokenBlock(
            tokens=tuple(self.partial),
            block_hash=bh,
            sequence_hash=chain_hash(parent, bh),
            parent_sequence_hash=parent,
        )
        self.blocks.append(block)
        self.partial = []
        return block

    @property
    def total_tokens(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial)

    def all_tokens(self) -> List[int]:
        out: List[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial)
        return out

    def sequence_hashes(self) -> List[int]:
        return [b.sequence_hash for b in self.blocks]

    def block_hashes(self) -> List[int]:
        return [b.block_hash for b in self.blocks]


def compute_block_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """Per-block content hashes for the full blocks of ``tokens`` (the router's
    match key stream; partial trailing block is excluded)."""
    return [
        hash_tokens(tokens[i : i + block_size])
        for i in range(0, len(tokens) - block_size + 1, block_size)
    ]


def compute_seq_hashes(tokens: Sequence[int], block_size: int,
                       lora_id: int = 0) -> List[int]:
    """Chained sequence hashes for the full blocks of ``tokens``; the chain
    root is salted by ``lora_id`` (0 = base model, unsalted)."""
    out: List[int] = []
    parent: Optional[int] = lora_chain_root(lora_id)
    for i in range(0, len(tokens) - block_size + 1, block_size):
        h = chain_hash(parent, hash_tokens(tokens[i : i + block_size]))
        out.append(h)
        parent = h
    return out
