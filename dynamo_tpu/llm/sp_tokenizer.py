"""SentencePiece (SPM/unigram) tokenizer built from GGUF metadata.

Stock Mistral/Llama GGUF artifacts embed an SPM vocab (pieces + unigram
log-prob scores + token types) rather than a tokenizer.json; the serving
stack must tokenize from that alone. This implements the SP unigram
algorithm natively: Viterbi segmentation maximizing the sum of piece
scores, with SP's ``▁`` whitespace convention and llama.cpp's byte-fallback
pieces (``<0x..>``) for anything outside the vocab. No sentencepiece
dependency.

Reference capability: lib/llm/src/tokenizers/sp.rs (SP wrapper) +
lib/llm/src/gguf/gguf_tokenizer.rs (tokenizer from GGUF metadata).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

_SPACE = "▁"  # ▁

# tokenizer.ggml.token_type values (llama.cpp llama_token_type)
_TYPE_NORMAL, _TYPE_UNKNOWN, _TYPE_CONTROL, _TYPE_USER, _TYPE_UNUSED, \
    _TYPE_BYTE = 1, 2, 3, 4, 5, 6


class SpTokenizer:
    """SPM unigram tokenizer over a (pieces, scores, types) vocab."""

    def __init__(self, pieces: Sequence[str], scores: Sequence[float],
                 types: Optional[Sequence[int]] = None,
                 bos_id: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 unk_id: int = 0,
                 add_bos: bool = True):
        self.pieces = list(pieces)
        self.scores = list(scores) if scores else [0.0] * len(self.pieces)
        self.types = (list(types) if types
                      else [_TYPE_NORMAL] * len(self.pieces))
        self._bos = bos_id
        self._eos = eos_id
        self._unk = unk_id
        self._add_bos = add_bos

        self._lookup: Dict[str, Tuple[int, float]] = {}
        self._byte_ids: Dict[int, int] = {}
        self._max_len = 1
        for i, p in enumerate(self.pieces):
            t = self.types[i] if i < len(self.types) else _TYPE_NORMAL
            if t == _TYPE_BYTE:
                # "<0xNN>" byte-fallback piece
                try:
                    self._byte_ids[int(p[3:5], 16)] = i
                except (ValueError, IndexError):
                    pass
                continue
            if t in (_TYPE_CONTROL, _TYPE_UNUSED, _TYPE_UNKNOWN):
                continue
            # keep the best-scoring piece for duplicate strings
            prev = self._lookup.get(p)
            if prev is None or self.scores[i] > prev[1]:
                self._lookup[p] = (i, self.scores[i])
            self._max_len = max(self._max_len, len(p))

    # ------------------------------------------------------------------
    @classmethod
    def from_gguf_metadata(cls, md: Dict) -> "SpTokenizer":
        pieces = md.get("tokenizer.ggml.tokens") or []
        scores = md.get("tokenizer.ggml.scores") or []
        types = md.get("tokenizer.ggml.token_type")
        bos = md.get("tokenizer.ggml.bos_token_id")
        eos = md.get("tokenizer.ggml.eos_token_id")
        unk = md.get("tokenizer.ggml.unknown_token_id", 0)
        add_bos = bool(md.get("tokenizer.ggml.add_bos_token", True))
        return cls(pieces, scores, types,
                   bos_id=int(bos) if bos is not None else None,
                   eos_id=int(eos) if eos is not None else None,
                   unk_id=int(unk), add_bos=add_bos)

    @classmethod
    def from_gguf(cls, path: str) -> "SpTokenizer":
        from .gguf import read_gguf

        g = read_gguf(path)
        try:
            return cls.from_gguf_metadata(g.metadata)
        finally:
            g.close()

    # ------------------------------------------------------------------
    def encode(self, text: str) -> List[int]:
        # SP normalization: spaces become ▁, and a leading ▁ marks the
        # word boundary at sequence start (llama/mistral convention)
        norm = _SPACE + text.replace(" ", _SPACE)
        ids = self._viterbi(norm)
        if self._add_bos and self._bos is not None:
            return [self._bos] + ids
        return ids

    def _viterbi(self, s: str) -> List[int]:
        """Unigram segmentation: max total piece score over the string."""
        n = len(s)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, int]]] = [None] * (n + 1)  # (start, id)
        best[0] = 0.0
        # byte fallback cost: below any real piece so it's a last resort
        byte_cost = -20.0
        for i in range(n):
            if best[i] == NEG:
                continue
            hi = min(n, i + self._max_len)
            for j in range(i + 1, hi + 1):
                hit = self._lookup.get(s[i:j])
                if hit is None:
                    continue
                cand = best[i] + hit[1]
                if cand > best[j]:
                    best[j] = cand
                    back[j] = (i, hit[0])
            # single-char fallback: byte pieces (or unk)
            j = i + 1
            nb = len(s[i:j].encode())
            cand = best[i] + byte_cost * nb
            if cand > best[j]:
                best[j] = cand
                back[j] = (i, -1)
        out: List[int] = []
        pos = n
        while pos > 0:
            assert back[pos] is not None
            start, pid = back[pos]
            if pid >= 0:
                out.append(pid)
            else:
                # byte-fallback (reversed append order handled below)
                bs = s[start:pos].encode()
                for b in reversed(bs):
                    out.append(self._byte_ids.get(b, self._unk))
            pos = start
        out.reverse()
        return out

    # ------------------------------------------------------------------
    def decode(self, ids: Sequence[int]) -> str:
        parts: List[bytes] = []
        for i in ids:
            if i < 0 or i >= len(self.pieces):
                continue
            t = self.types[i] if i < len(self.types) else _TYPE_NORMAL
            if t == _TYPE_BYTE:
                try:
                    parts.append(bytes([int(self.pieces[i][3:5], 16)]))
                    continue
                except (ValueError, IndexError):
                    pass
            if t == _TYPE_CONTROL:
                continue
            parts.append(self.pieces[i].replace(_SPACE, " ").encode())
        return b"".join(parts).decode("utf-8", errors="replace")

    # ------------------------------------------------------------------
    @property
    def eos_token_ids(self) -> List[int]:
        return [self._eos] if self._eos is not None else []

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)
