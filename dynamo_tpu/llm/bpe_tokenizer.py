"""Byte-level BPE (GPT-2 style) tokenizer built from GGUF metadata.

Qwen2/GPT-2-family GGUF artifacts carry ``tokenizer.ggml.model = "gpt2"``
with a token list and a merge table instead of an SPM vocab.  The serving
stack must tokenize from that alone — the reference builds an HF
``tokenizers`` byte-level BPE from the same metadata
(lib/llm/src/gguf/gguf_tokenizer.rs:121-125, 234-283); this implements the
algorithm natively:

- GPT-2 byte↔unicode table (every byte maps to a printable codepoint, so
  the merge table operates on strings while round-tripping raw bytes);
- regex pre-tokenization (GPT-2 pattern by default; the Qwen2 variant when
  ``tokenizer.ggml.pre`` says so, matching llama.cpp's pre-tokenizer tags);
- lowest-rank-first pair merging per pre-token, memoized;
- special/control tokens split out of the text before BPE so
  ``<|endoftext|>``-style markers encode to their single id.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import regex as _re

# llama.cpp llama_token_type values (same table as sp_tokenizer)
_TYPE_NORMAL, _TYPE_UNKNOWN, _TYPE_CONTROL, _TYPE_USER, _TYPE_UNUSED, \
    _TYPE_BYTE = 1, 2, 3, 4, 5, 6

# GPT-2 pre-tokenization pattern (HF ByteLevel default — what the reference
# gets from pre_tokenizers::byte_level::ByteLevel).
_GPT2_PAT = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
             r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")
# Qwen2 / llama-3 family pattern (tokenizer.json pre_tokenizer split regex;
# llama.cpp selects it via the "qwen2"/"llama3" pre-tokenizer tags).
_QWEN2_PAT = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}"
              r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+")

_PRE_PATTERNS = {
    "default": _GPT2_PAT,
    "gpt-2": _GPT2_PAT,
    "qwen2": _QWEN2_PAT,
    "llama3": _QWEN2_PAT,
    "llama-bpe": _QWEN2_PAT,
}


def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte→printable-codepoint table."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_B2U = _bytes_to_unicode()
_U2B = {u: b for b, u in _B2U.items()}


class BpeTokenizer:
    """Byte-level BPE over a (tokens, merges) vocab from GGUF metadata."""

    def __init__(self, tokens: Sequence[str], merges: Sequence[str],
                 types: Optional[Sequence[int]] = None,
                 bos_id: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 add_bos: bool = False,
                 pre: str = "default"):
        self.tokens = list(tokens)
        self.types = (list(types) if types
                      else [_TYPE_NORMAL] * len(self.tokens))
        self._vocab: Dict[str, int] = {}
        for i, t in enumerate(self.tokens):
            self._vocab.setdefault(t, i)
        self._ranks: Dict[Tuple[str, str], int] = {}
        for r, m in enumerate(merges):
            a, _, b = m.partition(" ")
            self._ranks[(a, b)] = r
        self._bos = bos_id
        self._eos = eos_id
        self._add_bos = add_bos
        self._pat = _re.compile(
            _PRE_PATTERNS.get(pre, _GPT2_PAT))
        # specials are matched verbatim before byte-level pre-tokenization
        specials = [self.tokens[i] for i in range(len(self.tokens))
                    if self.types[i] in (_TYPE_CONTROL, _TYPE_USER)
                    and self.tokens[i]]
        self._special_pat = (_re.compile(
            "|".join(_re.escape(s) for s in
                     sorted(specials, key=len, reverse=True)))
            if specials else None)
        self._cache: Dict[str, List[int]] = {}

    # ------------------------------------------------------------------
    @classmethod
    def from_gguf_metadata(cls, md: Dict) -> "BpeTokenizer":
        tokens = md.get("tokenizer.ggml.tokens")
        merges = md.get("tokenizer.ggml.merges")
        if not tokens:
            raise ValueError("gpt2 BPE tokenizer requires tokenizer.ggml.tokens")
        if merges is None:
            raise ValueError("gpt2 BPE tokenizer requires tokenizer.ggml.merges")
        bos = md.get("tokenizer.ggml.bos_token_id")
        eos = md.get("tokenizer.ggml.eos_token_id")
        return cls(tokens, merges,
                   types=md.get("tokenizer.ggml.token_type"),
                   bos_id=int(bos) if bos is not None else None,
                   eos_id=int(eos) if eos is not None else None,
                   add_bos=bool(md.get("tokenizer.ggml.add_bos_token", False)),
                   pre=str(md.get("tokenizer.ggml.pre", "default")))

    @classmethod
    def from_gguf(cls, path: str) -> "BpeTokenizer":
        from .gguf import read_gguf

        g = read_gguf(path)
        try:
            return cls.from_gguf_metadata(g.metadata)
        finally:
            g.close()

    # ------------------------------------------------------------------
    def _bpe_word(self, word: str) -> List[int]:
        """Merge one pre-token (already byte-mapped) to ids."""
        hit = self._cache.get(word)
        if hit is not None:
            return hit
        parts = list(word)
        while len(parts) > 1:
            best_rank, best_i = None, -1
            for i in range(len(parts) - 1):
                r = self._ranks.get((parts[i], parts[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out: List[int] = []
        for p in parts:
            i = self._vocab.get(p)
            if i is not None:
                out.append(i)
            else:
                # unmergeable fragment: fall back to per-byte tokens
                for ch in p:
                    j = self._vocab.get(ch)
                    if j is not None:
                        out.append(j)
        if len(self._cache) < 65536:
            self._cache[word] = out
        return out

    def _encode_span(self, text: str) -> List[int]:
        ids: List[int] = []
        for m in self._pat.finditer(text):
            mapped = "".join(_B2U[b] for b in m.group().encode("utf-8"))
            ids.extend(self._bpe_word(mapped))
        return ids

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        if self._add_bos and self._bos is not None:
            ids.append(self._bos)
        if self._special_pat is None:
            ids.extend(self._encode_span(text))
            return ids
        pos = 0
        for m in self._special_pat.finditer(text):
            if m.start() > pos:
                ids.extend(self._encode_span(text[pos:m.start()]))
            ids.append(self._vocab[m.group()])
            pos = m.end()
        if pos < len(text):
            ids.extend(self._encode_span(text[pos:]))
        return ids

    # ------------------------------------------------------------------
    def decode(self, ids: Sequence[int]) -> str:
        bs = bytearray()
        out: List[str] = []
        for i in ids:
            if i < 0 or i >= len(self.tokens):
                continue
            t = self.types[i] if i < len(self.types) else _TYPE_NORMAL
            if t in (_TYPE_CONTROL, _TYPE_UNUSED):
                continue
            tok = self.tokens[i]
            if t == _TYPE_USER:
                if bs:
                    out.append(bs.decode("utf-8", errors="replace"))
                    bs = bytearray()
                out.append(tok)
                continue
            for ch in tok:
                b = _U2B.get(ch)
                if b is not None:
                    bs.append(b)
                else:  # not byte-mapped (shouldn't happen for gpt2 vocabs)
                    bs.extend(ch.encode("utf-8"))
        if bs:
            out.append(bs.decode("utf-8", errors="replace"))
        return "".join(out)

    # ------------------------------------------------------------------
    @property
    def eos_token_ids(self) -> List[int]:
        return [self._eos] if self._eos is not None else []

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos

    @property
    def vocab_size(self) -> int:
        return len(self.tokens)
