"""OpenAI-compatible HTTP frontend (aiohttp).

Routes: POST /v1/chat/completions, POST /v1/completions, GET /v1/models,
GET /health, GET /metrics (Prometheus), GET /v1/traces[/{request_id}]
(request span timelines; ``?format=chrome`` exports Perfetto-loadable
trace-event JSON). SSE streaming with client-disconnect propagation into
engine cancellation; a ModelManager maps model name → engines and supports
live add/remove (used by etcd-style discovery later).

Every request opens a root span whose trace id is the request id (echoed
back as the ``x-request-id`` response header); per-stage latencies (TTFT,
inter-token) land in the process StageMetrics and /metrics additionally
merges the stage histograms workers publish to the store.

Reference capability: lib/llm/src/http/service/{service_v2,openai,metrics,
discovery}.rs — axum server, ModelManager, disconnect monitor, Prometheus.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from aiohttp import web

from ..runtime import deadline as dl
from ..runtime.engine import AsyncEngine, Context, EngineError
from ..utils import overload, tracing
from ..utils.prometheus import Registry, render_states, stage_metrics

log = logging.getLogger("dynamo_tpu.http_service")
from .model_card import ModelDeploymentCard
from .protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    ProtocolError,
    SSE_DONE,
    aggregate_chat_chunks,
    aggregate_completion_chunks,
    sse_encode,
)


@dataclass
class ServedModel:
    card: ModelDeploymentCard
    chat_engine: Optional[AsyncEngine] = None
    completion_engine: Optional[AsyncEngine] = None


class ModelManager:
    """Live registry of served models; safe to mutate while serving."""

    def __init__(self):
        self._models: Dict[str, ServedModel] = {}

    def add(self, model: ServedModel) -> None:
        self._models[model.card.name] = model

    def remove(self, name: str) -> None:
        self._models.pop(name, None)

    def get(self, name: str) -> Optional[ServedModel]:
        return self._models.get(name)

    def list(self):
        return list(self._models.values())


class HttpService:
    def __init__(self, manager: Optional[ModelManager] = None,
                 host: str = "0.0.0.0", port: int = 8080, store=None,
                 namespace: Optional[str] = None,
                 router_decisions=None, admission=None, tenants=None):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        # overload control (utils/overload.py): admission gate (DYN_ADMIT_*
        # knobs; inert when none are set) + this process's view of the
        # fleet brownout level (armed against the store by cli/http)
        self.admission = admission if admission is not None \
            else overload.AdmissionController.from_env()
        # per-tenant quotas (x-tenant header): DYN_TENANT_QUOTAS env table,
        # refreshed live from the fleet registry's per-model tenant tables
        # by cli/http. Inert when no tenant has a quota.
        self.tenants = tenants if tenants is not None \
            else overload.TenantAdmission.from_env()
        self.brownout = overload.BrownoutState()
        # fleet plane hooks (cli/http wires both in discovery mode):
        # async () -> {model: status_dict} merging fleet_models/ desired
        # state with the planner's lease-bound fleet_status/ records —
        # GET /v1/models reports per-model state instead of bare names
        self.fleet_status = None
        # () -> set of registry model names: a 404 for a REGISTERED model
        # is labelled with its name (bounded set — the planner's
        # scale-from-zero wake signal); everything else stays "unknown"
        self.known_models = None
        # optional dynstore client: lets /v1/traces fetch spans published by
        # worker processes and /metrics merge their stage histograms —
        # scoped to ``namespace`` when set (a shared store may carry other
        # deployments' dumps, which must not pollute this scrape)
        self.store = store
        self.namespace = namespace
        # optional async callable ``(limit) -> list | None``: fetches the
        # KV router's decision-audit ring (None = router not reachable);
        # unset when the deployment has no router at all
        self.router_decisions = router_decisions
        # set when this frontend also PUBLISHES a stage dump to the store
        # (cli/http discovery mode): /metrics must skip its own published
        # key or the scrape would merge this process's counters twice
        self.stage_worker_id: Optional[int] = None
        # queue-until-boot (DYN_BOOT_WAIT): requests currently parked at
        # ingress waiting for a scaled-to-zero model's replica to boot
        self._boot_parked = 0
        self.stage = stage_metrics()
        self.registry = Registry()
        m = self.registry
        self.m_requests = m.counter(
            "dyn_http_requests_total", "HTTP requests",
            ("model", "endpoint", "status", "tenant"))
        self.m_inflight = m.gauge(
            "dyn_http_inflight_requests", "In-flight requests", ("model",))
        self.m_duration = m.histogram(
            "dyn_http_request_duration_seconds", "Request duration",
            ("model", "endpoint"))
        self.m_ttft = m.histogram(
            "dyn_http_time_to_first_token_seconds", "Time to first streamed token",
            ("model",))
        self.m_tokens = m.counter(
            "dyn_http_output_tokens_total", "Completion tokens produced", ("model",))
        self._runner: Optional[web.AppRunner] = None
        self.app = self._build_app()

    # ------------------------------------------------------------------
    def _build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/v1/traces", self._list_traces)
        app.router.add_get("/v1/traces/{request_id}", self._get_trace)
        app.router.add_get("/v1/router/decisions", self._router_decisions)
        app.router.add_get("/v1/incidents", self._list_incidents)
        app.router.add_get("/v1/incidents/{incident_id}", self._get_incident)
        app.router.add_get("/v1/flows", self._list_flows)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        return app

    async def start(self) -> int:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve the actual port (port=0 supported for tests)
        for s in site._server.sockets:  # type: ignore[union-attr]
            self.port = s.getsockname()[1]
            break
        return self.port

    async def stop(self) -> None:
        pub = getattr(self, "_stage_pub_task", None)
        if pub is not None:          # discovery-mode stage publish loop
            pub.cancel()
        obs_h = getattr(self, "_obs_handle", None)
        if obs_h is not None:        # discovery-mode flight-recorder plane
            await obs_h.stop()
        if self._runner:
            await self._runner.cleanup()

    async def run_forever(self) -> None:
        await self.start()
        while True:
            await asyncio.sleep(3600)

    # ------------------------------------------------------------------
    async def _health(self, _req: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "models": [m.card.name for m in self.manager.list()]}
        )

    async def _metrics(self, _req: web.Request) -> web.Response:
        text = self.registry.render()
        # per-stage histograms: this process's, plus — in discovery mode —
        # the dumps every worker publishes under metrics_stage/ (component-
        # labelled, merged across replicas)
        states = [("http", self.stage.registry.state_dump())]
        if self.store is not None:
            try:
                from .metrics_aggregator import fetch_stage_states

                states += await fetch_stage_states(
                    self.store, self.namespace,
                    exclude_worker=self.stage_worker_id)
            except Exception:
                log.exception("stage metrics scrape failed")
        text += render_states(states)
        return web.Response(text=text, content_type="text/plain")

    # ------------------------------------------------------------------
    async def _list_traces(self, req: web.Request) -> web.Response:
        try:
            limit = int(req.query.get("limit", "50"))
        except ValueError:
            return _err(400, "limit must be an integer")
        ids = tracing.get_tracer().recent_trace_ids(limit)
        return web.json_response({"traces": ids})

    async def _get_trace(self, req: web.Request) -> web.Response:
        rid = req.match_info["request_id"]
        local = tracing.get_tracer().spans_for(rid)
        remote = []
        if self.store is not None:
            try:
                remote = await tracing.fetch_trace_spans(self.store, rid)
            except Exception:
                log.exception("trace fetch from store failed")
        spans = tracing.merge_spans(local, remote)
        if not spans:
            return _err(404, f"no trace recorded for request {rid!r}")
        if req.query.get("format") == "chrome":
            return web.json_response(tracing.to_chrome_trace(spans))
        return web.json_response(
            {"trace_id": rid, "spans": [s.to_dict() for s in spans]})

    async def _router_decisions(self, req: web.Request) -> web.Response:
        """The KV router's decision audit: per-request score breakdowns
        (overlap/cache_usage/load per candidate, chosen worker, salt) from
        the router's bounded ring. 404 when no router is configured."""
        if self.router_decisions is None:
            return _err(404, "no KV router configured on this frontend")
        try:
            limit = int(req.query.get("limit", "0"))
        except ValueError:
            return _err(400, "limit must be an integer")
        try:
            decisions = await self.router_decisions(limit)
        except Exception as e:  # noqa: BLE001 - surface, don't 500-trace
            log.exception("router decisions fetch failed")
            return _err(502, f"router decisions fetch failed: {e}")
        if decisions is None:
            return _err(404, "router not reachable (no live router "
                             "instance, or none discovered yet)")
        return web.json_response({"decisions": decisions,
                                  "count": len(decisions)})

    async def _list_flows(self, req: web.Request) -> web.Response:
        """The cluster's byte-flow ledger: per-link totals folded from
        every worker's published stage dump (plus this process's own),
        hottest link first — the same matrix ``dyntop`` renders as
        ``links:`` and ``ctl flows`` prints."""
        from ..obs.flows import flows_from_states

        try:
            limit = int(req.query.get("limit", "0"))
        except ValueError:
            return _err(400, "limit must be an integer")
        states = [("http", self.stage.registry.state_dump())]
        if self.store is not None:
            try:
                from .metrics_aggregator import fetch_stage_states

                states += await fetch_stage_states(
                    self.store, self.namespace,
                    exclude_worker=self.stage_worker_id)
            except Exception:
                log.exception("stage dump scrape for /v1/flows failed")
        links = flows_from_states(states)
        if limit > 0:
            links = links[:limit]
        return web.json_response({"links": links, "count": len(links)})

    async def _list_incidents(self, _req: web.Request) -> web.Response:
        """Live incident beacons (flight-recorder capture coordination) —
        the same view ``ctl incident ls`` renders. 404 without a store."""
        if self.store is None:
            return _err(404, "no store configured on this frontend")
        from ..obs import incidents as _incidents

        ns = self.namespace or "dynamo"
        beacons = await _incidents.list_incidents(self.store, ns)
        return web.json_response({"incidents": beacons,
                                  "count": len(beacons)})

    async def _get_incident(self, req: web.Request) -> web.Response:
        """One assembled incident bundle: manifest + per-process ring
        dumps + the trigger's retro-assembled trace."""
        if self.store is None:
            return _err(404, "no store configured on this frontend")
        from ..obs import incidents as _incidents

        iid = req.match_info["incident_id"]
        ns = self.namespace or "dynamo"
        bundle = await _incidents.fetch_bundle(self.store, ns, iid)
        if bundle is None:
            return _err(404, f"no incident {iid!r} (expired or never "
                             f"captured)")
        return web.json_response(bundle)

    async def _models(self, _req: web.Request) -> web.Response:
        now = int(time.time())
        rows = {
            m.card.name: {"id": m.card.name, "object": "model",
                          "created": now, "owned_by": "dynamo_tpu",
                          "context_length": m.card.context_length}
            for m in self.manager.list()
        }
        # fleet view: per-model state (ready/booting/draining/off),
        # replica counts and targets from the registry + the planner's
        # lease-bound status — including registered models with NO live
        # replica (scaled to zero / still booting), which the discovery
        # manager alone cannot see
        if self.fleet_status is not None:
            try:
                for name, st in (await self.fleet_status()).items():
                    row = rows.setdefault(name, {
                        "id": name, "object": "model", "created": now,
                        "owned_by": "dynamo_tpu"})
                    row["state"] = st.get("state", "unknown")
                    # wake_path/wake_seconds: how this model last came
                    # up — "swap" (in-place weight swap, seconds-scale)
                    # or "cold" (full boot) — and what it cost
                    for fld in ("replicas", "target", "component",
                                "chips", "priority", "wake_path",
                                "wake_seconds"):
                        if st.get(fld) is not None:
                            row[fld] = st[fld]
            except Exception:
                log.exception("fleet status fetch failed; serving bare "
                              "model list")
        return web.json_response({
            "object": "list",
            "data": sorted(rows.values(), key=lambda r: r["id"]),
        })

    # ------------------------------------------------------------------
    async def _chat(self, req: web.Request) -> web.StreamResponse:
        return await self._serve(req, "chat")

    async def _completions(self, req: web.Request) -> web.StreamResponse:
        return await self._serve(req, "completions")

    def _count(self, model: str, endpoint: str, status: str,
               tenant: str) -> None:
        """The one request-accounting path: the HTTP counter (tenant
        label bounded to the quota table + 'other') and the per-tenant
        stage counter the fleet-wide tenant burn is computed from."""
        tlabel = self.tenants.label(tenant)
        self.m_requests.inc(model, endpoint, status, tlabel)
        self.stage.tenant_requests.inc(tlabel, status)

    async def _serve(self, req: web.Request, endpoint: str) -> web.StreamResponse:
        started = time.monotonic()
        # ---- overload admission: the cheapest possible shed, decided from
        # headers alone before the body is even read. A rejected request
        # costs microseconds and a 429 + Retry-After — never a queue slot,
        # never a deadline burn. Order: brownout (fleet state), tenant
        # quota (isolation — a hog is shed before it touches the shared
        # caps), then the global admission gate.
        tenant = overload.DEFAULT_TENANT
        try:
            priority = overload.parse_priority(
                req.headers.get(overload.PRIORITY_HEADER))
            tenant = overload.parse_tenant(
                req.headers.get(overload.TENANT_HEADER))
        except ValueError as e:
            self._count("unknown", endpoint, "400", tenant)
            return _err(400, str(e))
        level = self.brownout.level
        tenant_held = False
        shed = overload.brownout_reject(priority, level)
        if shed is None:
            shed = self.tenants.try_admit(tenant, priority)
            tenant_held = shed is None
        if shed is None:
            shed = self.admission.try_admit(priority)
            if shed is not None and tenant_held:
                self.tenants.release(tenant)
                tenant_held = False
        if shed is not None:
            self._count("unknown", endpoint, str(shed.code), tenant)
            return _err_engine(shed)
        try:
            return await self._serve_admitted(req, endpoint, started,
                                              priority, level, tenant)
        finally:
            self.admission.release()
            self.admission.release_kv(req.get("dyn_kv_cost", 0.0))
            self.tenants.release(tenant)

    async def _serve_admitted(self, req: web.Request, endpoint: str,
                              started: float, priority: str, level: int,
                              tenant: str) -> web.StreamResponse:
        model_name = "unknown"
        try:
            body = await req.json()
        # dynalint: ok(swallowed-exception) malformed client JSON: counted
        # through _count (the tenant-labelled request counter) and
        # answered with a 400 — the parse error text is client data
        except Exception:
            self._count(model_name, endpoint, "400", tenant)
            return _err(400, "invalid JSON body")
        if not isinstance(body, dict):
            self._count(model_name, endpoint, "400", tenant)
            return _err(400, "request body must be a JSON object")
        try:
            if endpoint == "chat":
                oai_req = ChatCompletionRequest.from_dict(body)
            else:
                oai_req = CompletionRequest.from_dict(body)
        except ProtocolError as e:
            self._count("unknown", endpoint, "400", tenant)
            return _err(400, str(e))
        except Exception as e:
            # any other parse failure is still the client's malformed input
            self._count("unknown", endpoint, "400", tenant)
            return _err(400, f"malformed request: {e}")
        try:
            timeout = _request_timeout(req)
        except ValueError as e:
            self._count("unknown", endpoint, "400", tenant)
            return _err(400, str(e))
        # brownout degradation (fleet level, store-published): shrink the
        # work an admitted request may cost — cap max_tokens, drop
        # speculative decoding's extra programs
        cap = overload.max_tokens_cap(level)
        if cap is not None:
            oai_req.max_tokens = cap if oai_req.max_tokens is None \
                else min(oai_req.max_tokens, cap)
        if overload.disables_spec(level):
            oai_req.ext["no_spec"] = True
        # byte-honest admission, second gate: with the body read, price
        # the request's KV working set (estimated tokens x per-token
        # bytes) against the in-flight budget — one long-context request
        # consumes its true share of the envelope, not one slot. Released
        # in _serve's finally via the request-scoped cost.
        if self.admission.kv_enabled:
            kv_cost = self.admission.price_kv(
                overload.estimate_request_tokens(oai_req))
            shed = self.admission.try_reserve_kv(kv_cost,
                                                 priority)
            if shed is not None:
                self._count("unknown", endpoint, str(shed.code), tenant)
                return _err_engine(shed)
            req["dyn_kv_cost"] = kv_cost
        model_name = oai_req.model
        engine = self._engine_for(model_name, endpoint)
        if engine is None:
            # label with a constant to keep metric cardinality bounded
            # (model names of 404s are client-controlled) — EXCEPT for
            # fleet-registered models, a bounded set whose 404s are the
            # planner's scale-from-zero wake signal
            known = self.known_models() if self.known_models else ()
            label = model_name if model_name in known else "unknown"
            if label != "unknown":
                # queue-until-boot (DYN_BOOT_WAIT): park the request,
                # bounded and deadline-aware, until the wake signal has
                # booted a replica — scale-from-zero then costs latency
                # instead of a 404 retry storm
                t_park = time.monotonic()
                engine, shed = await self._queue_until_boot(
                    model_name, endpoint, timeout)
                if shed is not None:
                    self._count(label, endpoint, str(shed.code), tenant)
                    return _err_engine(shed)
                if engine is not None and timeout is not None:
                    # the park spent part of the request's end-to-end
                    # budget; the serve gets the remainder, never a
                    # fresh full window
                    timeout = max(timeout - (time.monotonic() - t_park),
                                  0.05)
            if engine is None:
                self._count(label, endpoint, "404", tenant)
                return _err(404, f"model {model_name!r} not found"
                            + (" (registered, no live replica — booting "
                               "or scaled to zero)"
                               if label != "unknown" else ""))

        # end-to-end deadline (x-request-timeout header, DYN_REQUEST_TIMEOUT
        # default): every downstream hop sees it via the context / wire
        # envelope; expiry anywhere surfaces as a 504 naming the stage.
        # The priority class rides the same envelope.
        ctx = Context(deadline=dl.from_timeout(timeout), priority=priority)
        # request-id span: every log line in this async call chain (and in
        # remote workers via the wire context_id) carries ctx.id
        from ..utils.logging_ext import request_id_var
        request_id_var.set(ctx.id)
        # root span: trace id IS the request id; every downstream span —
        # local pipeline stages and remote workers via the wire trace
        # field — stitches under it. GET /v1/traces/{ctx.id} replays it.
        tracer = tracing.get_tracer()
        root = tracer.start_span(f"http:{endpoint}", trace_id=ctx.id,
                                 model=model_name)
        root_token = tracing.current_span_var.set(root.context()) \
            if root is not None else None
        self.m_inflight.inc(model_name)
        status = "200"
        try:
            if oai_req.stream:
                try:
                    resp = await self._stream(req, engine, oai_req, ctx,
                                              model_name, endpoint, started)
                except (ConnectionResetError, asyncio.CancelledError):
                    status = "499"   # client closed mid-stream
                    raise
                # mid-stream failures can't change the committed 200, but
                # the root span / request counter must reflect them; a
                # pre-commit failure returns a plain 4xx/5xx response
                status = getattr(resp, "_dyn_error_status",
                                 str(resp.status))
                return resp
            chunks = []
            first = True
            try:
                async for ch in dl.guard_stream(
                        engine.generate(oai_req, ctx), ctx.deadline,
                        "http_aggregate", slack=0.5):
                    if "event" in ch:
                        continue  # annotations only meaningful when streaming
                    if "error" in ch:
                        # a pipeline that already yielded chunks reports
                        # failures in-stream; here nothing is committed yet
                        # so it can still be a clean 4xx
                        status = "400"
                        return _err(400, ch["error"]["message"], ctx.id)
                    if first:
                        self.stage.ttft.observe(
                            model_name, value=time.monotonic() - started)
                        first = False
                    chunks.append(ch)
                    u = ch.get("usage")
                    if u:
                        self.m_tokens.inc(model_name,
                                          amount=u["completion_tokens"])
            except ProtocolError as e:
                status = "400"
                return _err(400, str(e), ctx.id)
            except EngineError as e:
                status = str(e.code)
                return _err_engine(e, ctx.id)
            agg = (aggregate_chat_chunks(chunks) if endpoint == "chat"
                   else aggregate_completion_chunks(chunks))
            return web.json_response(agg,
                                     headers={"x-request-id": ctx.id})
        finally:
            if root_token is not None:
                tracing.current_span_var.reset(root_token)
            tracer.finish(root, status="ok" if status == "200" else "error")
            self.m_inflight.dec(model_name)
            self._count(model_name, endpoint, status, tenant)
            self.m_duration.observe(model_name, endpoint,
                                    value=time.monotonic() - started)

    def _engine_for(self, model_name: str,
                    endpoint: str) -> Optional[AsyncEngine]:
        served = self.manager.get(model_name)
        if served is None:
            return None
        return (served.chat_engine if endpoint == "chat"
                else served.completion_engine)

    async def _queue_until_boot(self, model_name: str, endpoint: str,
                                timeout: Optional[float]):
        """Park a request for a fleet-registered model with no live
        replica until one boots: ``(engine, None)`` when a replica
        appeared, ``(None, shed)`` for a typed 503 (park window expired
        while still booting, or the bounded park queue is full), and
        ``(None, None)`` when the feature is off (caller 404s as
        before). Parks are counted per model
        (``dyn_queue_until_boot_total``) and feed the planner's
        unserved-demand wake signal exactly like the 404s they
        replace."""
        from ..utils.knobs import env_float

        wait_s = env_float("DYN_BOOT_WAIT", 0.0, minimum=0.0)
        if wait_s <= 0:
            return None, None
        # deadline-aware: never park past the request's own budget
        # (leave a slice of it for the actual serve)
        if timeout is not None:
            wait_s = min(wait_s, max(timeout * 0.8, 0.0))
        max_parked = int(env_float("DYN_BOOT_WAIT_QUEUE", 64, minimum=0))
        qub = self.stage.queue_until_boot
        if self._boot_parked >= max_parked:
            qub.inc(model_name, "overflow")
            return None, EngineError(
                f"model {model_name!r} is booting and the park queue is "
                f"full ({max_parked} requests already waiting)", 503,
                stage="ingress", reason="boot_queue_full",
                retry_after=2.0)
        qub.inc(model_name, "parked")
        self._boot_parked += 1
        try:
            deadline = time.monotonic() + wait_s
            while True:
                engine = self._engine_for(model_name, endpoint)
                if engine is not None:
                    qub.inc(model_name, "served")
                    return engine, None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                await asyncio.sleep(min(0.25, remaining))
        finally:
            self._boot_parked -= 1
        qub.inc(model_name, "expired")
        return None, EngineError(
            f"model {model_name!r} has no live replica after waiting "
            f"{wait_s:.1f}s for boot (registered — scale-from-zero in "
            f"progress)", 503, stage="ingress", reason="booting",
            retry_after=2.0)

    async def _stream(self, req: web.Request, engine: AsyncEngine, oai_req,
                      ctx: Context, model: str, endpoint: str,
                      started: float) -> web.StreamResponse:
        agen = engine.generate(oai_req, ctx)
        # Pull the first item BEFORE committing the 200/SSE response so that
        # preprocessing failures (context overflow, bad template) still map to
        # a proper 4xx status instead of an error inside a 200 stream — and
        # a pre-first-token deadline expiry to a clean 504.
        try:
            first_item = await dl.wait_for(agen.__anext__(), ctx.deadline,
                                           "http_first_token", slack=0.5)
        except StopAsyncIteration:
            first_item = None
        except ProtocolError as e:
            return _err(400, str(e), ctx.id)
        except EngineError as e:
            return _err_engine(e, ctx.id)
        if isinstance(first_item, dict) and "error" in first_item:
            # a pipeline that reports failures in-stream (tool matcher) may
            # fail before any content chunk; nothing is committed yet so it
            # can still be a proper 4xx
            return _err(400, first_item["error"]["message"], ctx.id)

        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "x-request-id": ctx.id},
        )
        await resp.prepare(req)
        first = True
        last_chunk_at: Optional[float] = None
        stage = self.stage
        tracer = tracing.get_tracer()
        sse_span = tracer.start_span("sse.egress", model=model)
        chunks_out = 0

        async def chain():
            if first_item is not None:
                yield first_item
            async for item in agen:
                yield item

        try:
            async for ch in dl.guard_stream(chain(), ctx.deadline,
                                            "http_stream", slack=0.5):
                if "event" in ch:
                    payload = (f"event: {ch['event']}\n"
                               f"data: {json.dumps(ch['data'])}\n\n").encode()
                    await resp.write(payload)
                    continue
                if "error" in ch:
                    # in-band error after chunks were committed: the HTTP
                    # status is already 200, but traces/metrics must not
                    # call this request ok
                    resp._dyn_error_status = "500"
                    await resp.write(sse_encode(json.dumps(ch)))
                    continue
                now = time.monotonic()
                if first:
                    ttft = now - started
                    self.m_ttft.observe(model, value=ttft)
                    stage.ttft.observe(model, value=ttft)
                    first = False
                elif last_chunk_at is not None:
                    stage.inter_token.observe(model,
                                              value=now - last_chunk_at)
                last_chunk_at = now
                chunks_out += 1
                u = ch.get("usage")
                if u:
                    self.m_tokens.inc(model, amount=u["completion_tokens"])
                await resp.write(sse_encode(json.dumps(ch)))
            await resp.write(sse_encode(SSE_DONE))
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: propagate cancellation into the engine.
            # 499 (nginx's client-closed-request): aborted streams are the
            # requests operators trace — they must not read as clean 200s
            resp._dyn_error_status = "499"
            ctx.stop_generating()
            raise
        except ProtocolError as e:
            resp._dyn_error_status = "400"
            await resp.write(sse_encode(json.dumps({"error": {
                "message": str(e), "type": "invalid_request_error"}})))
            await resp.write(sse_encode(SSE_DONE))
        except EngineError as e:
            resp._dyn_error_status = str(e.code)
            await resp.write(sse_encode(json.dumps({"error": {
                "message": str(e), "type": "engine_error", "code": e.code}})))
            await resp.write(sse_encode(SSE_DONE))
        finally:
            if sse_span is not None:
                sse_span.attrs["chunks"] = chunks_out
            tracer.finish(sse_span,
                          status="ok" if getattr(resp, "_dyn_error_status",
                                                 "200") == "200" else "error")
            ctx.stop_generating()
        await resp.write_eof()
        return resp


def _request_timeout(req: web.Request) -> Optional[float]:
    """Per-request deadline budget in seconds: the ``x-request-timeout``
    header when present, else the ``DYN_REQUEST_TIMEOUT`` env default, else
    None (no deadline). A malformed HEADER raises ValueError (the client's
    fault — 400); a malformed env default is the operator's typo and is
    logged and ignored, never inflicted on clients."""
    import os

    raw = req.headers.get("x-request-timeout")
    if raw:
        try:
            t = float(raw)
        except ValueError:
            raise ValueError(f"x-request-timeout: {raw!r} is not a number")
        if not t > 0:
            raise ValueError(f"x-request-timeout must be > 0, got {t}")
        return t
    env = os.environ.get("DYN_REQUEST_TIMEOUT")
    if not env:
        return None
    try:
        t = float(env)
    except ValueError:
        log.warning("ignoring malformed DYN_REQUEST_TIMEOUT=%r", env)
        return None
    return t if t > 0 else None


_ERR_TYPES = {400: "invalid_request_error", 404: "not_found_error",
              429: "overloaded_error", 502: "bad_gateway_error",
              503: "service_unavailable_error", 504: "timeout_error"}

# typed-error fallbacks for EngineErrors raised by layers that predate the
# stage/reason fields (e.g. a bare 503 from the dispatch client): every
# 429/503/504 body names A stage and reason even when the thrower didn't
_FALLBACK_STAGE = {429: "admission", 502: "router", 503: "dispatch"}
_FALLBACK_REASON = {429: "overload", 503: "no_capacity", 504: "deadline"}


def _err(code: int, message: str, request_id: Optional[str] = None, *,
         stage: Optional[str] = None, reason: Optional[str] = None,
         retry_after: Optional[float] = None) -> web.Response:
    """The ONE error-body shape: ``{"error": {message, type, code, stage?,
    reason?, retry_after?}}``. Overload (429) and unavailability (503)
    responses always carry ``Retry-After``; errors for requests that got
    far enough to have an id carry ``x-request-id`` too — failed requests
    are the ones operators trace."""
    import math

    err: Dict[str, Any] = {"message": message,
                           "type": _ERR_TYPES.get(code, "internal_error"),
                           "code": code}
    if stage is not None:
        err["stage"] = stage
    if reason is not None:
        err["reason"] = reason
    headers: Dict[str, str] = {}
    if request_id:
        headers["x-request-id"] = request_id
    if retry_after is None and code in (429, 503):
        retry_after = 1.0
    if retry_after is not None:
        err["retry_after"] = round(float(retry_after), 3)
        headers["Retry-After"] = str(max(1, math.ceil(retry_after)))
    return web.json_response({"error": err}, status=code,
                             headers=headers or None)


def _err_engine(e: Exception,
                request_id: Optional[str] = None) -> web.Response:
    """Typed EngineError -> uniform error response: its stage/reason/
    retry_after (which survive the wire from remote workers) land in the
    body, with per-code fallbacks for untyped throwers."""
    code = getattr(e, "code", 500)
    return _err(code, str(e), request_id,
                stage=getattr(e, "stage", None) or _FALLBACK_STAGE.get(code),
                reason=(getattr(e, "reason", None)
                        or _FALLBACK_REASON.get(code)),
                retry_after=getattr(e, "retry_after", None))
