"""Engine-agnostic internal request/response protocol.

The preprocessor lowers OpenAI requests into :class:`BackendInput` (token ids +
sampling + stop conditions); engines stream back :class:`EngineOutput` deltas.
Reference capability: lib/llm/src/protocols/common.rs and
lib/llm/src/protocols/common/llm_backend.rs:1-126.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional


class FinishReason(str, enum.Enum):
    EOS = "eos"          # hit an end-of-sequence token
    STOP = "stop"        # hit a stop string/token from the request
    LENGTH = "length"    # hit max_tokens / context limit
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        if self in (FinishReason.EOS, FinishReason.STOP):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        return "stop" if self is FinishReason.CANCELLED else "error"


@dataclass
class SamplingOptions:
    temperature: Optional[float] = None  # None/0 => greedy
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1

    @property
    def greedy(self) -> bool:
        return not self.temperature or self.temperature <= 0.0


@dataclass
class StopConditions:
    max_tokens: Optional[int] = None
    stop: List[str] = field(default_factory=list)          # stop strings
    stop_token_ids: List[int] = field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False


@dataclass
class OutputOptions:
    logprobs: Optional[int] = None
    echo: bool = False  # completions-style prompt echo


@dataclass
class BackendInput:
    """What an engine consumes: pure tokens + generation config."""

    token_ids: List[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    output: OutputOptions = field(default_factory=OutputOptions)
    eos_token_ids: List[int] = field(default_factory=list)
    model: Optional[str] = None
    mdc_sum: Optional[str] = None  # model deployment card checksum
    annotations: Dict[str, Any] = field(default_factory=dict)
    # LoRA adapter the request targets (0 = base model). Salts the KV
    # block-hash chain so adapter KV can never alias base/other-adapter KV
    # in prefix reuse or the router index (ref C ABI lib.rs:253-283).
    lora_id: int = 0
    # KV block-hash chain salt (0 = derive from lora_id / image content at
    # the engine). The frontend sets this for VLM requests — lora_id folded
    # with an image-content digest — so the KV router's prefix-overlap
    # scoring hashes with the SAME salt the engine publishes blocks under
    # (without it, KV-aware routing is silently a no-op for image prompts).
    kv_salt: int = 0
    # speculative decoding opt-out: the engine proposes zero drafts for
    # this request (its decode degenerates to plain single-token steps
    # inside the verify dispatch).
    no_spec: bool = False
    # cluster KV sharing (llm/kv_cluster/): the donor worker the router
    # elected for this request's prefix (0 = none). The receiving worker
    # fetches the blocks it lacks from this peer's host tier BEFORE the
    # request enters the engine — no registry round-trip on the worker.
    # kv_donor_blocks bounds the fetch to the consecutive prefix length
    # the router actually scored (the donor may have sealed more since).
    kv_donor: int = 0
    kv_donor_blocks: int = 0
    # Mid-stream resume (llm/resume.py): number of tokens at the TAIL of
    # ``token_ids`` that were already emitted to the client by a previous
    # (now dead) worker. The engine treats the full sequence as prefix —
    # restoring surviving KV / teacher-forcing the tail, never re-emitting
    # those tokens — and generation continues from position len(token_ids).
    # Sampled requests re-seed their RNG stream as a function of
    # (seed, resume_pos) so a resumed stream never replays the dead
    # worker's draws against a different KV state.
    resume_pos: int = 0
    # VLM: normalized pixel arrays ([3, H, W]; the engine's vision tower
    # encodes them at prefill). On the wire each image travels as
    # {"b64": base64 raw bytes, "shape": [...], "dtype": "..."} — nested
    # per-pixel int lists (~tens of MB per image as JSON numbers) are still
    # ACCEPTED on read for one release, but no longer produced.
    # Image k fills the k-th ``image_token_id`` placeholder run.
    images: Optional[List[Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        if self.images is None:
            return asdict(self)
        import base64

        import numpy as np
        from dataclasses import replace

        # exclude the pixel arrays from asdict's deep copy; convert once
        d = asdict(replace(self, images=None))
        d["images"] = []
        for im in self.images:
            arr = np.ascontiguousarray(np.asarray(im))
            d["images"].append({
                "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            })
        return d

    @staticmethod
    def _decode_image(e: Any):
        """One wire image -> pixel array: base64 envelope or the legacy
        nested-list encoding (accepted for one release)."""
        if isinstance(e, dict) and "b64" in e:
            import base64

            import numpy as np
            return np.frombuffer(
                base64.b64decode(e["b64"]),
                dtype=np.dtype(e.get("dtype", "uint8"))
            ).reshape(e.get("shape", (-1,)))
        return e

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BackendInput":
        images = d.get("images")
        if images is not None:
            images = [cls._decode_image(e) for e in images]
        return cls(
            token_ids=list(d["token_ids"]),
            sampling=SamplingOptions(**d.get("sampling", {})),
            stop=StopConditions(**d.get("stop", {})),
            output=OutputOptions(**d.get("output", {})),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            model=d.get("model"),
            mdc_sum=d.get("mdc_sum"),
            annotations=dict(d.get("annotations", {})),
            lora_id=int(d.get("lora_id", 0)),
            kv_salt=int(d.get("kv_salt", 0)),
            no_spec=bool(d.get("no_spec", False)),
            kv_donor=int(d.get("kv_donor", 0)),
            kv_donor_blocks=int(d.get("kv_donor_blocks", 0)),
            resume_pos=int(d.get("resume_pos", 0)),
            images=images,
        )


@dataclass
class EngineOutput:
    """One streamed step from a core engine: newly generated token ids (and
    optionally text, if the engine detokenizes itself)."""

    token_ids: List[int] = field(default_factory=list)
    text: Optional[str] = None
    cum_log_prob: Optional[float] = None
    logprobs: Optional[List[Dict[str, float]]] = None
    finish_reason: Optional[FinishReason] = None
    # human-readable cause when finish_reason == ERROR — surfaced all the
    # way to the SSE client instead of a silently terminated stream
    error: Optional[str] = None
    # typed-error triple accompanying ``error``: http-ish status plus the
    # stage/reason fields of the uniform error body, so an engine-side
    # 400/503 maps to that status at the HTTP edge (and over the wire)
    # instead of a generic 500
    error_code: Optional[int] = None
    error_stage: Optional[str] = None
    error_reason: Optional[str] = None
    # engine-side bookkeeping surfaced for routing/metrics
    kv_prefix_hit_tokens: Optional[int] = None
    index: int = 0  # choice index for n>1

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_prob=d.get("cum_log_prob"),
            logprobs=d.get("logprobs"),
            finish_reason=FinishReason(fr) if fr else None,
            error=d.get("error"),
            error_code=d.get("error_code"),
            error_stage=d.get("error_stage"),
            error_reason=d.get("error_reason"),
            kv_prefix_hit_tokens=d.get("kv_prefix_hit_tokens"),
            index=d.get("index", 0),
        )
