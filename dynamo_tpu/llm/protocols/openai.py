"""OpenAI-compatible protocol types: chat completions + completions, streaming
deltas, and SSE aggregation back into full responses.

Plain dataclasses + dict (de)serialization — the wire format is JSON and the
frontend is asyncio, so pydantic-style machinery buys nothing here.

Reference capability: lib/llm/src/protocols/openai/* (chat_completions.rs,
completions.rs, delta.rs, aggregator.rs) and the ``nvext`` extension field
(annotations / use_raw_prompt), kept here as ``ext``.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .common import EngineOutput, FinishReason


class ProtocolError(ValueError):
    """Malformed client request (maps to HTTP 400)."""


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------

@dataclass
class ChatCompletionRequest:
    model: str
    messages: List[Dict[str, Any]]
    stream: bool = False
    max_tokens: Optional[int] = None          # also accepts max_completion_tokens
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None               # extension (vLLM-compatible)
    n: int = 1
    stop: List[str] = field(default_factory=list)
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    seed: Optional[int] = None
    logprobs: bool = False
    top_logprobs: Optional[int] = None
    min_tokens: Optional[int] = None          # extension
    ignore_eos: bool = False                  # extension
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Any = None                   # none|auto|required|{function:...}
    ext: Dict[str, Any] = field(default_factory=dict)  # our nvext equivalent
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChatCompletionRequest":
        if not isinstance(d.get("model"), str):
            raise ProtocolError("'model' must be a string")
        msgs = d.get("messages")
        if not isinstance(msgs, list) or not msgs:
            raise ProtocolError("'messages' must be a non-empty list")
        for m in msgs:
            if not isinstance(m, dict) or "role" not in m:
                raise ProtocolError("each message needs a 'role'")
        stop = d.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        from ..tools import (  # deferred: avoid import cycle
            normalize_tool_choice,
            normalize_tools,
        )

        tools = normalize_tools(d.get("tools"))
        # validate at parse time so a bad tool_choice is a clean 400, not a
        # mid-stream error after the SSE response has committed
        normalize_tool_choice(d.get("tool_choice"), tools)
        return cls(
            model=d["model"],
            messages=msgs,
            stream=bool(d.get("stream", False)),
            max_tokens=d.get("max_tokens", d.get("max_completion_tokens")),
            temperature=d.get("temperature"),
            top_p=d.get("top_p"),
            top_k=d.get("top_k"),
            n=int(d.get("n", 1)),
            stop=list(stop),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            seed=d.get("seed"),
            logprobs=bool(d.get("logprobs", False)),
            top_logprobs=d.get("top_logprobs"),
            min_tokens=d.get("min_tokens"),
            ignore_eos=bool(d.get("ignore_eos", False)),
            tools=tools,
            tool_choice=d.get("tool_choice"),
            ext=dict(d.get("ext", d.get("nvext", {}) or {})),
            raw=d,
        )


@dataclass
class CompletionRequest:
    model: str
    prompt: Any  # str | List[str] | List[int]
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: int = 1
    stop: List[str] = field(default_factory=list)
    echo: bool = False
    seed: Optional[int] = None
    logprobs: Optional[int] = None
    min_tokens: Optional[int] = None
    ignore_eos: bool = False
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    ext: Dict[str, Any] = field(default_factory=dict)
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CompletionRequest":
        if not isinstance(d.get("model"), str):
            raise ProtocolError("'model' must be a string")
        if "prompt" not in d:
            raise ProtocolError("'prompt' is required")
        stop = d.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return cls(
            model=d["model"],
            prompt=d["prompt"],
            stream=bool(d.get("stream", False)),
            max_tokens=d.get("max_tokens"),
            temperature=d.get("temperature"),
            top_p=d.get("top_p"),
            top_k=d.get("top_k"),
            n=int(d.get("n", 1)),
            stop=list(stop),
            echo=bool(d.get("echo", False)),
            seed=d.get("seed"),
            logprobs=d.get("logprobs"),
            min_tokens=d.get("min_tokens"),
            ignore_eos=bool(d.get("ignore_eos", False)),
            frequency_penalty=d.get("frequency_penalty"),
            presence_penalty=d.get("presence_penalty"),
            ext=dict(d.get("ext", d.get("nvext", {}) or {})),
            raw=d,
        )


# ---------------------------------------------------------------------------
# Streaming delta generators
# ---------------------------------------------------------------------------

def _now() -> int:
    return int(time.time())


class ChatDeltaGenerator:
    """Turns backend text deltas into ``chat.completion.chunk`` dicts."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or f"chatcmpl-{uuid.uuid4().hex[:24]}"
        self.model = model
        self.created = _now()
        self._sent_role: set = set()

    def _chunk(self, delta: Dict[str, Any], index: int,
               finish_reason: Optional[str] = None,
               usage: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
        out = {
            "id": self.id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [
                {"index": index, "delta": delta, "finish_reason": finish_reason}
            ],
        }
        if usage is not None:
            out["usage"] = usage
        return out

    def role_chunk(self, index: int = 0) -> Dict[str, Any]:
        self._sent_role.add(index)
        return self._chunk({"role": "assistant", "content": ""}, index)

    def text_chunk(self, text: str, index: int = 0) -> Dict[str, Any]:
        delta: Dict[str, Any] = {"content": text}
        if index not in self._sent_role:
            self._sent_role.add(index)
            delta["role"] = "assistant"
        return self._chunk(delta, index)

    def tool_calls_chunk(self, calls: List[Dict[str, Any]],
                         index: int = 0) -> Dict[str, Any]:
        """One delta carrying complete tool calls (arguments are not split
        across chunks: the matcher only fires on the finished message)."""
        delta: Dict[str, Any] = {
            "tool_calls": [{**c, "index": i} for i, c in enumerate(calls)],
        }
        if index not in self._sent_role:
            self._sent_role.add(index)
            delta["role"] = "assistant"
        return self._chunk(delta, index)

    def finish_chunk(self, finish_reason: FinishReason, index: int = 0,
                     usage: Optional[Dict[str, int]] = None,
                     finish_override: Optional[str] = None) -> Dict[str, Any]:
        return self._chunk({}, index,
                           finish_override or finish_reason.to_openai(), usage)


class CompletionDeltaGenerator:
    """Turns backend text deltas into ``text_completion`` chunk dicts."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or f"cmpl-{uuid.uuid4().hex[:24]}"
        self.model = model
        self.created = _now()

    def text_chunk(self, text: str, index: int = 0,
                   finish_reason: Optional[str] = None,
                   logprobs: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return {
            "id": self.id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "choices": [
                {
                    "index": index,
                    "text": text,
                    "logprobs": logprobs,
                    "finish_reason": finish_reason,
                }
            ],
        }

    def finish_chunk(self, finish_reason: FinishReason, index: int = 0) -> Dict[str, Any]:
        return self.text_chunk("", index, finish_reason.to_openai())


# ---------------------------------------------------------------------------
# Aggregators (stream of chunks -> one full response)
# ---------------------------------------------------------------------------

def usage_dict(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def aggregate_chat_chunks(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold chat.completion.chunk dicts into a full chat.completion response."""
    if not chunks:
        raise ProtocolError("empty stream")
    by_index: Dict[int, Dict[str, Any]] = {}
    usage = None
    for ch in chunks:
        if ch.get("usage"):
            usage = ch["usage"]
        for c in ch.get("choices", []):
            i = c["index"]
            acc = by_index.setdefault(
                i, {"index": i, "message": {"role": "assistant", "content": ""},
                    "finish_reason": None}
            )
            d = c.get("delta", {})
            if d.get("content"):
                acc["message"]["content"] += d["content"]
            for tc in d.get("tool_calls") or []:
                calls = acc["message"].setdefault("tool_calls", [])
                j = tc.get("index", len(calls))
                while len(calls) <= j:
                    calls.append({"id": None, "type": "function",
                                  "function": {"name": "", "arguments": ""}})
                slot = calls[j]
                if tc.get("id"):
                    slot["id"] = tc["id"]
                if tc.get("type"):
                    slot["type"] = tc["type"]
                fn = tc.get("function") or {}
                if fn.get("name"):
                    slot["function"]["name"] = fn["name"]
                if fn.get("arguments"):
                    slot["function"]["arguments"] += fn["arguments"]
            lp = c.get("logprobs")
            if lp and lp.get("content"):
                acc.setdefault("logprobs", {"content": []})["content"] \
                    .extend(lp["content"])
            if c.get("finish_reason"):
                acc["finish_reason"] = c["finish_reason"]
    first = chunks[0]
    out = {
        "id": first["id"],
        "object": "chat.completion",
        "created": first["created"],
        "model": first["model"],
        "choices": [by_index[i] for i in sorted(by_index)],
    }
    if usage:
        out["usage"] = usage
    return out


def aggregate_completion_chunks(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    if not chunks:
        raise ProtocolError("empty stream")
    by_index: Dict[int, Dict[str, Any]] = {}
    usage = None
    for ch in chunks:
        if ch.get("usage"):
            usage = ch["usage"]
        for c in ch.get("choices", []):
            i = c["index"]
            acc = by_index.setdefault(
                i, {"index": i, "text": "", "logprobs": None, "finish_reason": None}
            )
            acc["text"] += c.get("text") or ""
            lp = c.get("logprobs")
            if lp and lp.get("tokens"):
                dst = acc["logprobs"] or {"tokens": [], "token_logprobs": [],
                                          "top_logprobs": None,
                                          "text_offset": []}
                dst["tokens"].extend(lp["tokens"])
                dst["token_logprobs"].extend(lp["token_logprobs"])
                acc["logprobs"] = dst
            if c.get("finish_reason"):
                acc["finish_reason"] = c["finish_reason"]
    first = chunks[0]
    out = {
        "id": first["id"],
        "object": "text_completion",
        "created": first["created"],
        "model": first["model"],
        "choices": [by_index[i] for i in sorted(by_index)],
    }
    if usage:
        out["usage"] = usage
    return out


# ---------------------------------------------------------------------------
# SSE codec
# ---------------------------------------------------------------------------

SSE_DONE = "[DONE]"


def sse_encode(data: str) -> bytes:
    return f"data: {data}\n\n".encode()


def sse_parse_lines(lines: List[str]) -> List[str]:
    """Extract 'data:' payloads from SSE lines (test/client helper)."""
    out = []
    for line in lines:
        line = line.strip()
        if line.startswith("data:"):
            out.append(line[5:].strip())
    return out
