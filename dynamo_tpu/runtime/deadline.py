"""End-to-end request deadlines.

A deadline is an absolute wall-clock instant (``time.time()`` seconds) that
rides the wire envelope next to ``context_id`` and the trace context, so
every hop of a request — HTTP ingress, the client RPC read loop, the prefill
queue, the decode-side KV wait — can answer "is this request still worth
working on?" without coordination. Wall clock (not monotonic) because the
value crosses process and host boundaries; NTP-grade skew is absorbed by the
second-scale timeouts this is meant for.

Every enforcement point raises :class:`DeadlineExceeded` (an
:class:`EngineError` with HTTP code 504) whose message names the stage, and
counts the expiry in ``dyn_deadline_expiries_total{stage=...}`` — an expiry
is always a clean, attributable 504, never a hang.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, AsyncIterator, Awaitable, Optional, TypeVar

from .engine import EngineError
# wire-envelope field (request control header / queue job) carrying the
# absolute deadline; planes that drop unknown fields degrade to no
# deadline. Declared in the wire-field registry, re-exported here because
# every enforcement point already spells it ``dl.DEADLINE_KEY``.
from .wire import DEADLINE_KEY  # noqa: F401  (re-export)

T = TypeVar("T")


class DeadlineExceeded(EngineError):
    """The request's end-to-end deadline passed at ``stage``. Maps to HTTP
    504; the stage name travels in the message so a timed-out client knows
    WHERE the pipeline stalled."""

    def __init__(self, stage: str, deadline: Optional[float] = None):
        late = f" ({time.time() - deadline:.2f}s past deadline)" \
            if deadline else ""
        super().__init__(
            f"request deadline exceeded at stage {stage!r}{late}", 504,
            stage=stage, reason="deadline")


def expire(stage: str, deadline: Optional[float] = None) -> DeadlineExceeded:
    """Count the expiry and build the exception (callers raise it)."""
    from ..utils.prometheus import stage_metrics

    stage_metrics().deadline_expiries.inc(stage)
    return DeadlineExceeded(stage, deadline)


def expired(deadline: Optional[float]) -> bool:
    return deadline is not None and time.time() >= deadline


def remaining(deadline: Optional[float]) -> Optional[float]:
    """Seconds left, or None for no deadline. Never negative."""
    if deadline is None:
        return None
    return max(0.0, deadline - time.time())


def check(deadline: Optional[float], stage: str) -> None:
    """Raise (and count) if the deadline has passed."""
    if expired(deadline):
        raise expire(stage, deadline)


async def wait_for(aw: Awaitable[T], deadline: Optional[float],
                   stage: str, slack: float = 0.0) -> T:
    """Await ``aw`` bounded by the deadline; no deadline => unbounded.

    ``slack`` loosens OUTER enforcement layers: a hop that has deeper,
    exact enforcement beneath it (HTTP above the rpc client above the
    worker) waits slightly past the deadline so the innermost stage's 504
    — the diagnostic one — propagates up instead of being masked by a
    generic outer expiry. If the inner layer is truly hung, the outer
    guard still fires ``slack`` seconds later: hang-proof either way."""
    if deadline is None:
        return await aw  # unbounded-ok: caller declared no deadline
    rem = remaining(deadline + slack)
    if not rem:
        # cancel rather than leak the un-awaited coroutine/future
        asyncio.ensure_future(aw).cancel()
        raise expire(stage, deadline)
    try:
        return await asyncio.wait_for(aw, rem)
    except asyncio.TimeoutError:
        raise expire(stage, deadline) from None


async def guard_stream(agen: AsyncIterator[Any], deadline: Optional[float],
                       stage: str, slack: float = 0.0
                       ) -> AsyncIterator[Any]:
    """Re-yield ``agen`` enforcing the deadline on every inter-item gap.
    With no deadline this is a plain passthrough (no per-item wait_for).
    ``slack``: see :func:`wait_for`."""
    if deadline is None:
        async for item in agen:
            yield item
        return
    try:
        while True:
            try:
                item = await wait_for(agen.__anext__(), deadline, stage,
                                      slack)
            except StopAsyncIteration:
                return
            yield item
    finally:
        aclose = getattr(agen, "aclose", None)
        if aclose is not None:
            try:
                await aclose()
            except Exception:  # noqa: BLE001 - teardown must not mask
                pass


def from_timeout(timeout: Optional[float]) -> Optional[float]:
    """Absolute deadline ``timeout`` seconds from now (None passthrough)."""
    return None if timeout is None else time.time() + float(timeout)
