"""Distributed runtime: Namespace -> Component -> Endpoint model.

A worker process creates a :class:`DistributedRuntime` (store connection +
lease), names a component, and serves endpoints. Serving an endpoint:

1. starts (once per process) a TCP data-plane server speaking two-part frames,
2. registers ``{namespace}/components/{component}/{endpoint}:{lease_id}`` in
   dynstore bound to the process lease (death => key vanishes => clients
   shrink their live set automatically — the failure-detection plane).

Requests flow DIRECTLY client->worker over TCP (the reference splits NATS
request / TCP response; with no broker in the middle we collapse both onto
one connection, keeping the two-part codec, the error-before-stream prologue
and Stop/Kill control messages of the reference's wire contract,
lib/runtime/src/pipeline/network.rs:44-233).

Reference capability: lib/runtime/src/component.rs, component/endpoint.rs,
component/client.rs, distributed.rs.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import random
import socket
from dataclasses import dataclass
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional, Tuple

from ..utils import faults
from . import deadline as dl
from .circuit_breaker import InstanceBreaker
from .engine import AsyncEngine, Context, EngineError
from .store_client import StoreClient
from .wire import (CODE_KEY, CONTEXT_ID_KEY, CTYPE_KEY, ENDPOINT_KEY,
                   KIND_KEY, MESSAGE_KEY, PRIORITY_KEY, REASON_KEY,
                   RESUME_KEY, RETRY_AFTER_KEY, STAGE_KEY, STREAMING_KEY,
                   TRACE_KEY, FrameReader, attach_trace, extract_trace,
                   unpack_two_part, write_frame)

log = logging.getLogger("dynamo_tpu.runtime")

Handler = Callable[[Any, Context], AsyncIterator[Any]]


def error_control(e: Exception, code: Optional[int] = None) -> dict:
    """Error-frame control header for an exception. Typed EngineErrors keep
    their http-ish code AND their overload/deadline fields (stage, reason,
    retry_after) so the far end re-raises an equally typed error — a remote
    shed/expiry must reach the frontend's error body naming its stage."""
    c: dict = {KIND_KEY: "error", MESSAGE_KEY: str(e),
               CODE_KEY: code if code is not None else (
                   e.code if isinstance(e, EngineError) else 500)}
    for k in (STAGE_KEY, REASON_KEY, RETRY_AFTER_KEY):
        v = getattr(e, k, None)
        if v is not None:
            c[k] = v
    return c


def error_from_control(control: dict) -> EngineError:
    """The inverse: re-raise a wire error frame as a typed EngineError."""
    return EngineError(control.get(MESSAGE_KEY, "remote error"),
                       control.get(CODE_KEY, 500),
                       stage=control.get(STAGE_KEY),
                       reason=control.get(REASON_KEY),
                       retry_after=control.get(RETRY_AFTER_KEY))


async def drive_handler_stream(stream, send) -> bool:
    """Drive a handler's response stream through ``await send(control,
    payload)`` — the ONE implementation of the response wire protocol
    (error-before-stream prologue, data / bin frames, sentinel, mid-stream
    error frames) shared by the asyncio and native data planes. Connection
    errors raised by ``send`` propagate to the caller. Returns True on a
    clean full stream, False when a handler error became an error frame
    (the servers mark the request's rpc span accordingly)."""
    try:
        first = await stream.__anext__()
        have_first = True
    except StopAsyncIteration:
        have_first = False
    except EngineError as e:
        await send(error_control(e), None)
        return False
    except Exception as e:  # noqa: BLE001
        await send({KIND_KEY: "error", MESSAGE_KEY: str(e),
                    CODE_KEY: 500}, None)
        return False
    await send({KIND_KEY: "prologue"}, None)

    def enc(item):
        if isinstance(item, (bytes, bytearray)):
            return {KIND_KEY: "data", CTYPE_KEY: "bin"}, bytes(item)
        return {KIND_KEY: "data"}, json.dumps(item).encode()

    try:
        if have_first:
            await send(*enc(first))
            async for item in stream:
                await send(*enc(item))
        await send({KIND_KEY: "sentinel"}, None)
    except (ConnectionResetError, BrokenPipeError):
        raise
    except Exception as e:  # noqa: BLE001 - mid-stream failure
        # typed engine errors (e.g. DeadlineExceeded=504, OverloadError=429)
        # keep their code + stage/reason; everything else is a 500
        try:
            await send(error_control(e), None)
        except Exception:
            # peer is already gone — the error frame has no one to reach
            log.debug("error frame undeliverable (peer gone)",
                      exc_info=True)
        return False
    return True


@dataclass
class StreamingRequest:
    """A client-streamed request: a JSON meta header plus a sequence of raw
    binary parts (the KV-block upload shape). Handlers registered on an
    endpoint receive this when the caller used ``parts=``; they MUST drain
    ``parts`` before yielding responses."""

    meta: Any
    parts: AsyncIterator[bytes]


def endpoint_key(namespace: str, component: str, endpoint: str,
                 lease: int) -> str:
    return f"{namespace}/components/{component}/{endpoint}:{lease:x}"


def endpoint_prefix(namespace: str, component: str, endpoint: str) -> str:
    return f"{namespace}/components/{component}/{endpoint}:"


@dataclass
class EndpointInfo:
    """What a worker publishes to the store for one endpoint instance."""

    host: str
    port: int
    endpoint: str
    lease: int
    worker_id: int
    transport: str = "tcp"

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "EndpointInfo":
        return cls(**json.loads(b.decode()))


class DistributedRuntime:
    """Per-process handle: store connection, lease, data-plane server."""

    def __init__(self, store_host: str = "127.0.0.1", store_port: int = 4222,
                 advertise_host: Optional[str] = None):
        # DYN_STORE_SHARDS set => a ShardedStoreClient routing each
        # keyspace family to its owning dynstore; unset => the plain
        # single-store client (identical behavior)
        from .scale.shards import make_store_client
        self.store = make_store_client(store_host, store_port)
        self.lease: Optional[int] = None
        self.worker_id: int = 0
        self._advertise_host = advertise_host
        self._dp_server: Optional[asyncio.base_events.Server] = None
        self._native_dp = None   # native (C++) data plane when enabled
        self.dp_host: Optional[str] = None
        self.dp_port: Optional[int] = None
        self._handlers: Dict[str, Handler] = {}
        self._active: Dict[str, Context] = {}
        self._conn_writers: set = set()   # live data-plane connections
        # graceful drain: set once the process decided to exit — queue-pull
        # loops and periodic publishers check it to stop taking new work
        self.draining = asyncio.Event()

    async def connect(self) -> "DistributedRuntime":
        await self.store.connect()
        # Liveness TTL (DYN_LEASE_TTL): keepalives fire every ttl/3 from
        # the asyncio loop, so the margin must absorb loop starvation
        # (compile storms, loaded CI boxes). 10s = etcd-typical default;
        # worker-death detection latency is bounded by the same number.
        import math
        import os
        raw_ttl = os.environ.get("DYN_LEASE_TTL", "10.0")
        try:
            ttl = float(raw_ttl)
        except ValueError:
            ttl = -1.0
        if not (math.isfinite(ttl) and ttl > 0):
            raise ValueError(f"DYN_LEASE_TTL={raw_ttl!r} (expected a "
                             "positive number of seconds)")
        self.lease = await self.store.lease_grant(ttl=ttl)
        self.worker_id = self.lease
        return self

    async def prepare_drain(self) -> None:
        """First phase of graceful shutdown: make the worker INVISIBLE
        before anything stops serving. Revoking the lease expires every
        lease-bound key (endpoint + model registrations, metrics snapshots)
        server-side, so watchers route new work elsewhere while in-flight
        streams keep completing here. Idempotent; store-unreachable is fine
        (the lease then expires by TTL, which is the same outcome later)."""
        if self.draining.is_set():
            return
        self.draining.set()
        # the deliberate revoke below must not read as a lease LOSS
        self.store.on_lease_lost = None
        if self.lease is not None:
            try:
                await self.store.lease_revoke(self.lease)
            except Exception:  # noqa: BLE001 - store may be mid-outage
                log.info("drain: lease revoke failed (store unreachable); "
                         "lease will expire by TTL", exc_info=True)

    async def close(self) -> None:
        # orderly shutdown: the revoke below would otherwise read as a
        # lease LOSS at the next keepalive beat and fire a spurious
        # shutdown callback
        self.draining.set()
        self.store.on_lease_lost = None
        if self.lease is not None:
            try:
                await self.store.lease_revoke(self.lease)
            except Exception:
                # store likely gone already; TTL expiry reaps the lease
                log.debug("lease revoke failed during close", exc_info=True)
        if self._dp_server:
            self._dp_server.close()
        # established connections must die with the runtime (a dead process
        # would reset them; a merely-closed listener leaves clients hanging
        # on streams forever) — stop in-flight requests, drop sockets
        for ctx in list(self._active.values()):
            ctx.stop_generating()
        for w in list(self._conn_writers):
            try:
                w.close()
            # dynalint: ok(swallowed-exception) best-effort socket
            # teardown while the runtime is exiting; nothing can act on a
            # close() failure and the fd dies with the process
            except Exception:
                pass
        self._conn_writers.clear()
        if self._native_dp is not None:
            self._native_dp.stop()
            self._native_dp = None
        await self.store.close()

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    # ------------------------------------------------------------------
    # data plane (one TCP server per process, endpoints multiplexed by name)
    # ------------------------------------------------------------------
    async def _ensure_data_plane(self) -> None:
        if self._dp_server is not None or self._native_dp is not None:
            return
        import os

        # native C++ epoll plane is the deployed default; "python" forces
        # the asyncio fixture, "native" forces native (failure = error),
        # unset = auto (native when the library builds/ships, else python)
        mode = os.environ.get("DYNAMO_TPU_DATAPLANE", "auto")
        if mode not in ("auto", "python", "native"):
            raise ValueError(f"DYNAMO_TPU_DATAPLANE={mode!r}")
        if mode in ("auto", "native"):
            try:
                from .native_dataplane import NativeDataPlane

                self._native_dp = NativeDataPlane(self)
                self.dp_port = self._native_dp.start("0.0.0.0", 0)
            except Exception:
                self._native_dp = None   # half-started plane must not
                if mode == "native":     # block the asyncio fallback
                    raise
                log.info("native data plane unavailable; using asyncio",
                         exc_info=True)
        if self._native_dp is None:
            self._dp_server = await asyncio.start_server(
                self._serve_conn, "0.0.0.0", 0)
            self.dp_port = self._dp_server.sockets[0].getsockname()[1]
        self.dp_host = self._advertise_host or _local_ip()

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        fr = FrameReader(reader)
        pending = None
        self._conn_writers.add(writer)
        try:
            while True:
                # unbounded-ok: idle server connection awaiting the next
                # request; lives exactly as long as the client keeps it
                frame = pending if pending is not None else await fr.read()
                pending = None
                control, payload = unpack_two_part(frame)
                kind = control.get(KIND_KEY)
                if kind == "request":
                    # one stream at a time per connection; clients pool and
                    # reuse connections for SEQUENTIAL requests. The control
                    # watcher may race ahead and consume the next request
                    # frame — _run_request hands it back as ``pending``.
                    pending = await self._run_request(control, payload, fr,
                                                      writer)
                else:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except ValueError as e:
            # malformed frame (typed by wire.unpack_two_part / MAX_FRAME):
            # this peer speaks a broken protocol — drop the connection
            log.warning("closing data-plane connection: %s", e)
        finally:
            self._conn_writers.discard(writer)
            writer.close()

    async def _run_request(self, control: Dict[str, Any],
                           payload: Optional[bytes], fr: FrameReader,
                           writer: asyncio.StreamWriter):
        """Serve one request stream. Returns a leftover frame if the control
        watcher consumed the NEXT pipelined request off the socket."""
        ep = control.get(ENDPOINT_KEY)
        ctx_id = control.get(CONTEXT_ID_KEY) or None
        handler = self._handlers.get(ep)
        if handler is None:
            await write_frame(writer, [{KIND_KEY: "error",
                                        MESSAGE_KEY: f"no endpoint {ep!r}",
                                        CODE_KEY: 404}, None])
            return None
        if control.get(CTYPE_KEY) == "bin":
            request = payload  # raw bytes pass through untouched (KV plane)
        else:
            request = json.loads(payload.decode()) if payload else None
        resume_no = int(control.get(RESUME_KEY) or 0)
        if ctx_id is not None and ctx_id in self._active:
            stale = self._active[ctx_id]
            if resume_no > stale.resume_no:
                # mid-stream failover (llm/resume.py): the client declared
                # the active context dead (its stream broke) and re-entered
                # with a higher attempt ordinal — possibly on this same
                # worker when it merely wedged. The old handler is a zombie
                # whose output nobody consumes: kill it and serve the
                # resume. Its finally-pop is identity-conditional, so it
                # cannot reap the replacement's _active entry.
                log.warning("context %s superseded by resume attempt %d "
                            "(stale attempt %d killed)", ctx_id, resume_no,
                            stale.resume_no)
                stale.kill()
                del self._active[ctx_id]
            else:
                # duplicate-context guard: a client's stale-connection retry
                # re-sent a request whose original is still executing (the
                # connection died mid-request) — fail cleanly instead of
                # double-executing a non-idempotent handler
                await write_frame(writer, [{
                    KIND_KEY: "error", CODE_KEY: 409,
                    MESSAGE_KEY: f"context {ctx_id} is already executing "
                                 f"(duplicate delivery)"}, None])
                return None
        req_deadline = control.get(dl.DEADLINE_KEY)
        if dl.expired(req_deadline):
            # the request died in transit/queueing: refuse to burn compute
            # on work nobody is waiting for (counted per stage)
            err = dl.expire(f"worker_ingress:{ep}", req_deadline)
            await write_frame(writer, [error_control(err), None])
            return None
        ctx = Context(ctx_id, deadline=req_deadline,
                      priority=control.get(PRIORITY_KEY) or "interactive")
        ctx.resume_no = resume_no
        self._active[ctx.id] = ctx
        from ..utils.logging_ext import request_id_var
        from ..utils.tracing import current_span_var, get_tracer
        rid_token = request_id_var.set(ctx.id)  # span: this request's id
        # server span: covers the whole handler stream; parented from the
        # wire trace field when present, else a fresh parentless span on
        # trace_id == context id (requests keep their id across hops)
        tracer = get_tracer()
        srv_span = tracer.start_span(
            f"rpc:{ep}", parent=extract_trace(control, ctx.id),
            context_id=ctx.id)
        span_token = current_span_var.set(srv_span.context()) \
            if srv_span is not None else None
        leftover: List[Any] = []

        async def watch_control():
            """Stop/Kill control frames arriving mid-stream. A non-control
            frame is the next pipelined request on a reused connection:
            stash it for _serve_conn and stop reading."""
            try:
                while True:
                    # unbounded-ok: control watcher is cancelled when the
                    # request finishes; disconnects stop the context below
                    frame = await fr.read()
                    ctrl, _ = unpack_two_part(frame)
                    if ctrl.get(KIND_KEY) == "stop":
                        ctx.stop_generating()
                    elif ctrl.get(KIND_KEY) == "kill":
                        ctx.kill()
                    else:
                        leftover.append(frame)
                        return
            except (asyncio.IncompleteReadError, ConnectionResetError):
                ctx.stop_generating()
            except ValueError as e:
                # malformed frame mid-request: same broken-protocol policy
                # as _serve_conn — without this, the watcher would die
                # silently in the reap below and stop/kill frames for the
                # rest of the request would be ignored
                log.warning("closing data-plane connection mid-request: %s",
                            e)
                ctx.stop_generating()
                writer.close()

        watcher = None
        if control.get(STREAMING_KEY):
            # the connection keeps carrying request parts; stop/kill frames
            # interleave on the same stream until the "end" marker, after
            # which the normal control watcher takes over the socket
            async def parts_gen():
                nonlocal watcher
                while True:
                    # unbounded-ok: client-streamed body; a disconnect
                    # raises into the handler, which owns the request
                    ctrl, p = unpack_two_part(await fr.read())
                    kind = ctrl.get(KIND_KEY)
                    if kind == "part":
                        yield p
                    elif kind == "end":
                        watcher = asyncio.create_task(watch_control())
                        return
                    elif kind == "stop":
                        ctx.stop_generating()
                    elif kind == "kill":
                        ctx.kill()

            request = StreamingRequest(meta=request, parts=parts_gen())
        else:
            watcher = asyncio.create_task(watch_control())
        srv_status = "error"
        try:
            async def send(control, payload):
                await write_frame(writer, [control, payload])

            if await drive_handler_stream(handler(request, ctx), send):
                srv_status = "ok"
        except (ConnectionResetError, BrokenPipeError):
            ctx.stop_generating()
        finally:
            if watcher is not None:
                watcher.cancel()
                try:
                    # cancel() only schedules: AWAIT the exit so the
                    # watcher's pending read fully releases the stream
                    # before _serve_conn reads the next request frame
                    await watcher
                except asyncio.CancelledError:
                    if not watcher.cancelled():
                        raise   # OUR task was cancelled, not the watcher
                # dynalint: ok(swallowed-exception) reaping our own
                # cancelled control watcher; a watcher error mid-request
                # already surfaced as the request's stop/kill outcome
                except Exception:
                    pass
            if self._active.get(ctx.id) is ctx:
                # identity-conditional: a resume attempt may have superseded
                # this context and installed its own under the same id
                del self._active[ctx.id]
            if span_token is not None:
                current_span_var.reset(span_token)
            tracer.finish(srv_span, status=srv_status)
            # reset: a reused (pipelined) connection must not tag later
            # frames/log lines with a finished request's id
            request_id_var.reset(rid_token)
        return leftover[0] if leftover else None


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class Namespace:
    def __init__(self, drt: DistributedRuntime, name: str):
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    # namespace-scoped event plane
    async def publish(self, event: str, payload: Dict[str, Any]) -> None:
        await self.drt.store.publish(f"{self.name}.{event}",
                                     json.dumps(payload).encode())

    async def subscribe(self, event: str,
                        cb: Callable[[Dict[str, Any]], Awaitable[None]]) -> None:
        async def _cb(subject: str, payload: bytes):
            await cb(json.loads(payload.decode()))

        await self.drt.store.subscribe(f"{self.name}.{event}", _cb)


class Component:
    def __init__(self, ns: Namespace, name: str):
        self.namespace = ns
        self.name = name
        self.drt = ns.drt

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    async def publish(self, event: str, payload: Dict[str, Any]) -> None:
        await self.drt.store.publish(
            f"{self.namespace.name}.{self.name}.{event}",
            json.dumps(payload).encode())

    async def subscribe(self, event: str,
                        cb: Callable[[Dict[str, Any]], Awaitable[None]]) -> None:
        async def _cb(subject: str, payload: bytes):
            await cb(json.loads(payload.decode()))

        await self.drt.store.subscribe(
            f"{self.namespace.name}.{self.name}.{event}", _cb)


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name
        self.drt = component.drt

    @property
    def path(self) -> str:
        return (f"{self.component.namespace.name}."
                f"{self.component.name}.{self.name}")

    async def serve(self, handler: Handler) -> None:
        """Register the handler on the data plane + advertise in the store."""
        drt = self.drt
        await drt._ensure_data_plane()
        drt._handlers[self.name] = handler
        info = EndpointInfo(
            host=drt.dp_host, port=drt.dp_port, endpoint=self.name,
            lease=drt.lease, worker_id=drt.worker_id)
        key = endpoint_key(self.component.namespace.name,
                           self.component.name, self.name, drt.lease)
        await drt.store.put(key, info.to_bytes(), lease=drt.lease)

    async def serve_engine(self, engine: AsyncEngine) -> None:
        async def handler(request, ctx):
            async for item in engine.generate(request, ctx):
                yield item

        await self.serve(handler)

    def client(self) -> "Client":
        return Client(self)


class Client:
    """Watches the endpoint prefix => live instance set; issues requests with
    random / round_robin / direct routing. Data-plane connections are pooled
    per instance and reused for sequential requests (the server keeps the
    connection open across streams), saving a TCP handshake per request on
    the hot path. (Reference: component/client.rs:52-295 + egress/push.rs.)"""

    MAX_POOLED_PER_INSTANCE = 8

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self.drt = endpoint.drt
        self.instances: Dict[int, EndpointInfo] = {}
        self._rr = itertools.count()
        self._watching = False
        # (host, port) -> idle (reader, FrameReader, writer) connections
        self._pool: Dict[Tuple[str, int], List[Any]] = {}
        # cross-request per-instance failure accounting (eject / half-open
        # probe / recover) — the per-call ``failed`` set only ever protected
        # one request from re-picking a dead instance
        self.breaker = InstanceBreaker()
        self.on_instances_changed: Optional[Callable[[], None]] = None

    def _pool_get(self, key):
        conns = self._pool.get(key)
        while conns:
            item = conns.pop()
            if not item[2].is_closing():
                return item
        return None

    def _pool_put(self, key, item) -> None:
        if item[2].is_closing():
            return
        conns = self._pool.setdefault(key, [])
        conns.append(item)
        while len(conns) > self.MAX_POOLED_PER_INSTANCE:
            conns.pop(0)[2].close()

    def _pool_drop(self, key) -> None:
        for item in self._pool.pop(key, []):
            item[2].close()

    async def start(self) -> "Client":
        prefix = endpoint_prefix(self.endpoint.component.namespace.name,
                                 self.endpoint.component.name,
                                 self.endpoint.name)

        async def on_change(key: str, value: Optional[bytes], deleted: bool):
            lease = int(key.rsplit(":", 1)[1], 16)
            if deleted:
                # deregistration must evict pooled sockets too: the next
                # request would otherwise burn its same-instance retry on a
                # connection to a gone worker — and drop the breaker's
                # accounting (a re-registered id starts with a clean slate)
                info = self.instances.pop(lease, None)
                if info is not None:
                    self._pool_drop((info.host, info.port))
                self.breaker.forget(lease)
            else:
                self.instances[lease] = EndpointInfo.from_bytes(value)
            if self.on_instances_changed:
                self.on_instances_changed()

        snapshot = await self.drt.store.watch_prefix(prefix, on_change)
        for key, value in snapshot:
            lease = int(key.rsplit(":", 1)[1], 16)
            self.instances[lease] = EndpointInfo.from_bytes(value)
        self._watching = True
        return self

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while len(self.instances) < n:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self.instances)}/{n} instances")
            await asyncio.sleep(0.05)

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    def _pick(self, mode: str, instance_id: Optional[int],
              exclude: Optional[set] = None) -> Tuple[int, EndpointInfo]:
        if not self.instances:
            raise EngineError(f"no live instances of {self.endpoint.path}", 503)
        if mode == "direct":
            if instance_id not in self.instances:
                raise EngineError(
                    f"instance {instance_id} of {self.endpoint.path} is gone",
                    503)
            return instance_id, self.instances[instance_id]
        ids = sorted(i for i in self.instances
                     if not exclude or i not in exclude)
        if not ids:
            raise EngineError(
                f"all live instances of {self.endpoint.path} unreachable", 503)
        # circuit breaker: skip instances currently ejected (open). If that
        # would veto everyone, filter() stands down — the breaker may not
        # manufacture a total outage the membership plane doesn't see.
        ids = self.breaker.filter(ids)
        if mode == "round_robin":
            iid = ids[next(self._rr) % len(ids)]
        else:
            iid = random.choice(ids)
        return iid, self.instances[iid]

    async def generate(self, request: Any, context: Optional[Context] = None,
                       mode: str = "random",
                       instance_id: Optional[int] = None,
                       parts: Optional[AsyncIterator[bytes]] = None,
                       exclude: Optional[set] = None,
                       resume: int = 0,
                       on_instance: Optional[Callable[[int], None]] = None
                       ) -> AsyncIterator[Any]:
        """Issue a request; yields response items (the remote stream).
        With ``parts`` set, streams the binary chunks after the request header
        (server handler receives a :class:`StreamingRequest`).

        ``exclude`` seeds the per-call failed set (instances a resume layer
        already declared dead); ``resume`` stamps the mid-stream-failover
        attempt ordinal on the envelope (``RESUME_KEY``) so a zombie context
        of the same id yields server-side; ``on_instance`` is called with
        the chosen instance id once the first exchange succeeds — the hook a
        resume layer uses to know WHO to blame when the stream later breaks."""
        ctx = context or Context()
        dl.check(ctx.deadline, f"rpc_dispatch:{self.endpoint.name}")
        # serialize BEFORE any socket exists: a non-serializable request
        # must not leak a freshly opened connection
        if isinstance(request, (bytes, bytearray)):
            base_control = {KIND_KEY: "request", CONTEXT_ID_KEY: ctx.id,
                            CTYPE_KEY: "bin"}
            req_payload = bytes(request)
        else:
            base_control = {KIND_KEY: "request", CONTEXT_ID_KEY: ctx.id}
            req_payload = json.dumps(request).encode()
        if ctx.deadline is not None:
            # the deadline rides the envelope next to context_id/trace so
            # every downstream hop can drop work nobody awaits anymore
            base_control[dl.DEADLINE_KEY] = ctx.deadline
        if getattr(ctx, "priority", "interactive") != "interactive":
            # non-default priority rides the envelope so worker-side
            # shedding/queue ordering can prefer interactive (absent =>
            # interactive, the protective default)
            base_control[PRIORITY_KEY] = ctx.priority
        if parts is not None:
            base_control[STREAMING_KEY] = True
        if resume:
            base_control[RESUME_KEY] = int(resume)
        # client span around the whole exchange; its context rides the wire
        # so the server's rpc span parents under it. No ambient span (bare
        # client) => the request id becomes the trace id, matching the
        # server-side fallback.
        from ..utils.tracing import current_span_var, get_tracer
        tracer = get_tracer()
        amb = current_span_var.get()
        call_span = tracer.start_span(
            f"call:{self.endpoint.name}",
            trace_id=None if amb is not None else ctx.id,
            context_id=ctx.id)
        if call_span is not None:
            base_control[TRACE_KEY] = call_span.context().to_wire()
        else:
            attach_trace(base_control)

        # a stop/kill issued while we wait for the first frame (mid-prefill)
        # must reach the server immediately: the stopper lives for the whole
        # exchange and always writes to the CURRENT connection
        live: Dict[str, Any] = {"writer": None}

        async def forward_stop():
            await ctx.stopped()
            # the connect/failover window may have no writer yet — or a
            # just-closed one about to be replaced. Keep trying against the
            # CURRENT writer until a send sticks (or the exchange itself
            # ends and this task is cancelled); a stop must not be lost to
            # a connection that died the same instant, nor abandoned while
            # connect/failover churns longer than any fixed window.
            while True:
                w = live["writer"]
                if w is not None and not w.is_closing():
                    try:
                        await write_frame(w, [{KIND_KEY: "stop"}, None])
                        return
                    # dynalint: ok(swallowed-exception) the exception IS
                    # the retried condition: writer died mid-send, loop
                    # retries against the failover successor writer
                    except Exception:
                        pass
                await asyncio.sleep(0.05)

        stopper = asyncio.create_task(forward_stop())

        # Failover: a worker that died a moment ago may still be in the
        # watched live set. It engages ONLY when the connect itself is
        # refused — then provably no byte reached the peer and a retry on
        # another instance cannot double-execute. Any failure after a
        # connection existed (even a write error: the transport may have
        # delivered the frame before erroring) surfaces, except the
        # same-instance stale-pool retry whose duplicate-context guard
        # de-dupes server-side. direct mode never fails over.
        failed: set = set(exclude or ())
        try:
            while True:
                iid, info = self._pick(mode, instance_id, failed)
                key = (info.host, info.port)

                def _fail(iid=iid, key=key):
                    failed.add(iid)
                    self.breaker.record_failure(iid)
                    self._pool_drop(key)

                # part-streaming requests can't replay their body on a
                # stale pooled connection, so they always open fresh
                pooled = None if parts is not None else self._pool_get(key)
                if pooled is not None:
                    reader, fr, writer = pooled
                else:
                    try:
                        await faults.fire("client.connect")
                        reader, writer = await dl.wait_for(
                            asyncio.open_connection(info.host, info.port),
                            ctx.deadline, f"rpc_connect:{info.endpoint}")
                    except OSError as e:
                        _fail()
                        if mode == "direct":
                            raise EngineError(
                                f"connect to instance {iid:x} at "
                                f"{info.host}:{info.port} failed: {e}",
                                503) from e
                        continue   # _pick raises 503 when none are left
                    fr = FrameReader(reader)
                live["writer"] = writer

                req_control = {**base_control, ENDPOINT_KEY: info.endpoint}
                # First exchange (request out, first frame back). Failures
                # here — before ANY response frame was consumed — get one
                # same-instance retry on a fresh connection: a pooled socket
                # the server closed while idle resends harmlessly, and a
                # server that died mid-request is de-duped by its
                # duplicate-context guard (409) if it is in fact alive.
                # If the retry's CONNECT is refused, the process is gone —
                # a dead process cannot double-execute, and no frame was
                # yielded to the caller — so re-dispatching to another
                # instance is provably safe, mirroring the connect-refused
                # failover above. (Churn soak failure class: without this,
                # every request whose first frame raced a worker death
                # surfaced as a 503 even though another worker could serve
                # it.) parts-streaming requests can't replay a partially
                # consumed body: no retry, no failover.
                attempts = 2 if parts is None else 1
                refused_mid_exchange = False
                for attempt in range(attempts):
                    try:
                        await write_frame(writer, [req_control, req_payload])
                        if parts is not None:
                            async for chunk in parts:
                                await write_frame(
                                    writer,
                                    [{KIND_KEY: "part", CTYPE_KEY: "bin"},
                                     bytes(chunk)])
                            await write_frame(writer,
                                              [{KIND_KEY: "end"}, None])
                        first = await dl.wait_for(
                            fr.read(), ctx.deadline,
                            f"rpc_first_frame:{info.endpoint}", slack=0.25)
                        self.breaker.record_success(iid)
                        break
                    except (ConnectionResetError, BrokenPipeError,
                            asyncio.IncompleteReadError) as e:
                        writer.close()
                        if attempt == attempts - 1:
                            self.breaker.record_failure(iid)
                            raise EngineError(
                                f"connection to {info.host}:{info.port} "
                                f"failed: {e}", 503) from e
                        try:
                            reader, writer = await dl.wait_for(
                                asyncio.open_connection(
                                    info.host, info.port),
                                ctx.deadline,
                                f"rpc_reconnect:{info.endpoint}")
                        except ConnectionRefusedError as e2:
                            # REFUSED specifically proves the process is
                            # gone (closed listening port) — other OSErrors
                            # (fd exhaustion, transient routing) are
                            # client-side and the worker may still be
                            # executing the delivered request, where a
                            # cross-instance re-dispatch could double-
                            # execute. Drop its pooled sockets and — unless
                            # the caller pinned this instance — fail over
                            # like a refused first connect.
                            _fail()
                            if mode == "direct":
                                raise EngineError(
                                    f"instance {iid:x} at {info.host}:"
                                    f"{info.port} unreachable: {e2}",
                                    503) from e2
                            log.debug("failover: instance %x died mid-"
                                      "exchange (reconnect refused), "
                                      "re-dispatching ctx %s", iid, ctx.id)
                            refused_mid_exchange = True
                            break
                        except OSError as e2:
                            _fail()
                            raise EngineError(
                                f"instance {iid:x} at {info.host}:"
                                f"{info.port} unreachable: {e2}",
                                503) from e2
                        fr = FrameReader(reader)
                        live["writer"] = writer
                if refused_mid_exchange:
                    continue
                if on_instance is not None:
                    on_instance(iid)
                break
        except BaseException:
            stopper.cancel()
            w = live["writer"]
            if w is not None:      # e.g. deadline expiry mid-exchange: the
                w.close()          # half-used socket must not leak/pool
            tracer.finish(call_span, status="error")
            raise

        clean = False
        try:
            try:
                try:
                    control, payload = unpack_two_part(first)
                except ValueError as e:
                    # broken protocol, not a broken transport: typed 502,
                    # and the instance takes the breaker hit
                    self.breaker.record_failure(iid)
                    raise EngineError(
                        f"instance {iid:x} sent a malformed frame: {e}",
                        502) from e
                if control.get(KIND_KEY) == "error":
                    raise error_from_control(control)
                # else: prologue
                while True:
                    # inter-frame timeout: a worker that stalls mid-stream
                    # (or dies without RST) becomes a clean 504, not a hang
                    try:
                        control, payload = unpack_two_part(await dl.wait_for(
                            fr.read(), ctx.deadline,
                            f"rpc_stream:{info.endpoint}", slack=0.25))
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError) as e:
                        # worker died mid-stream: a typed 503, never a raw
                        # transport exception leaking to the frontend
                        self.breaker.record_failure(iid)
                        raise EngineError(
                            f"instance {iid:x} dropped the stream "
                            f"mid-response: {type(e).__name__}", 503) from e
                    except ValueError as e:
                        # malformed mid-stream frame: typed 502 + breaker
                        # hit, same policy as the server-side rx loops
                        self.breaker.record_failure(iid)
                        raise EngineError(
                            f"instance {iid:x} sent a malformed frame "
                            f"mid-response: {e}", 502) from e
                    kind = control.get(KIND_KEY)
                    if kind == "data":
                        if control.get(CTYPE_KEY) == "bin":
                            yield payload
                        else:
                            yield json.loads(payload.decode())
                    elif kind == "sentinel":
                        clean = True
                        return
                    elif kind == "error":
                        raise error_from_control(control)
            finally:
                stopper.cancel()
                try:
                    await stopper   # ensure no half-written stop frame races
                except asyncio.CancelledError:
                    if not stopper.cancelled():
                        raise   # OUR task was cancelled, not the stopper
                # dynalint: ok(swallowed-exception) reaping our own
                # cancelled stop-forwarder; its send errors were already
                # retried inside forward_stop until cancellation
                except Exception:
                    pass
        finally:
            tracer.finish(call_span, status="ok" if clean else "error")
            if clean:
                # full exchange completed: the connection sits at a frame
                # boundary and is safe to reuse for the next request
                self._pool_put(key, (reader, fr, writer))
            else:
                writer.close()
