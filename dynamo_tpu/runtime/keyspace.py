"""Central registry of the dynstore keyspace.

Every key the system puts/watches in dynstore belongs to exactly one
prefix family registered here: its owner subsystem, its lifecycle
(lease-bound liveness state vs persistent config/log vs TTL tombstone vs
work queue), the module that defines its helper/constant, and a one-line
description. The ``store-key-drift`` dynalint rule gates this two-way —
every store API call site must resolve (through the def-use layer) to a
registered family, and every registered family must still have call
sites — and ``docs/keyspace.md`` is generated from it::

    python -m dynamo_tpu.runtime.keyspace --write

This mirrors the knob registry (`utils/knobs.py` -> docs/configuration.md)
and the reference's single-file wire/etcd-path constant modules: the
keyspace IS an API between processes that can restart independently, so
drift between a producer's f-string and a consumer's prefix watch is a
silent cross-version outage, not a local bug.

Key families whose *literal* prefix starts with a placeholder (endpoint
registrations live under ``{namespace}/components/...``) cannot be
grepped; they are resolvable only through their registered helpers, which
is exactly why the gate is dataflow-based.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: lifecycle classes (how a key leaves the store)
LEASE = "lease"            # bound to a session lease: vanishes with owner
PERSISTENT = "persistent"  # lives until an explicit delete
TTL = "ttl"                # bound to a short no-keepalive lease
QUEUE = "queue"            # dynstore work queue (q_push/q_pull namespace)

#: shard ownership groups: the family sets that co-locate when the store
#: is split across dynstore processes (``DYN_STORE_SHARDS`` tokens may
#: name a group instead of listing its families one by one — see
#: runtime/scale/shards.py). The boundaries follow the per-family op
#: accounting (``dyn_store_op_seconds{family}``): write-heavy telemetry
#: and the TTL-churning span sink are the planes worth isolating first.
SHARD_CONTROL = "control"      # discovery/config/planner — low rate, hot
SHARD_TELEMETRY = "telemetry"  # metrics dumps + region records — high write
SHARD_TRACES = "traces"        # span sink — highest key churn (TTL)
SHARD_QUEUE = "queue"          # prefill work queues — latency-critical
SHARD_KV = "kv"                # KV-cluster registry — router-read-heavy


@dataclass(frozen=True)
class KeyFamily:
    """One registered store-key prefix family."""

    name: str                 # short id used in findings/docs
    pattern: str              # full key pattern, for humans
    owner: str                # owning subsystem (module path)
    lifecycle: str            # LEASE | PERSISTENT | TTL | QUEUE
    description: str
    #: literal prefix a key string starts with (None when the pattern
    #: starts with a placeholder and only helpers can build it)
    prefix: Optional[str] = None
    #: helper functions that build/parse keys of this family
    helpers: Tuple[str, ...] = ()
    #: module-level constants naming the prefix
    constants: Tuple[str, ...] = ()
    #: shard ownership group (see SHARD_* above): which dynstore process
    #: serves this family when DYN_STORE_SHARDS splits the keyspace
    shard: str = SHARD_CONTROL


_ALL: List[KeyFamily] = [
    KeyFamily(
        name="endpoints",
        pattern="{ns}/components/{component}/{endpoint}:{lease:x}",
        owner="runtime/component.py", lifecycle=LEASE,
        description="endpoint instance registrations — the service "
                    "discovery plane; key suffix is the worker's lease id "
                    "(= worker_id), so instances vanish with their lease",
        helpers=("endpoint_key", "endpoint_prefix")),
    KeyFamily(
        name="models",
        pattern="models/{model_type}/{name}[:i-{instance}]",
        owner="llm/remote.py", lifecycle=LEASE,
        description="model cards published by workers (chat template, "
                    "context length, runtime config) for frontends",
        prefix="models/", helpers=("model_key", "split_model_key"),
        constants=("MODEL_PREFIX",)),
    KeyFamily(
        name="metrics",
        pattern="metrics/{ns}/{component}/{worker_id:x}",
        owner="llm/metrics_aggregator.py", lifecycle=LEASE,
        description="per-worker ForwardPassMetrics snapshots (slots, KV "
                    "occupancy, hit rate) scraped by router/planner",
        prefix="metrics/", helpers=("metrics_key",),
        constants=("METRICS_PREFIX",), shard=SHARD_TELEMETRY),
    KeyFamily(
        name="metrics-stage",
        pattern="metrics_stage/{ns}/s{wid mod DYN_STAGE_SLICES:02x}/"
                "{component}/{worker_id:x}[/delta]",
        owner="llm/metrics_aggregator.py", lifecycle=LEASE,
        description="per-stage Prometheus registry snapshots merged "
                    "cluster-wide by the metrics aggregator (full "
                    "snapshot + coalesced since-last-full delta key); "
                    "the worker-stable slice segment lets a regional "
                    "aggregator read only its rendezvous-owned slices "
                    "per tick instead of scanning the fleet",
        prefix="metrics_stage/",
        helpers=("stage_key", "stage_delta_key", "stage_slice_prefix"),
        constants=("STAGE_PREFIX",), shard=SHARD_TELEMETRY),
    KeyFamily(
        name="metrics-store",
        pattern="metrics_stage/_store/store/0",
        owner="runtime/store_server.py", lifecycle=PERSISTENT,
        description="the store's OWN telemetry dump (per-op latency by "
                    "keyspace family, watch/lease/key gauges), written "
                    "into its KV by the server itself; dies with the "
                    "store process",
        prefix="metrics_stage/_store/", constants=("STORE_STAGE_PREFIX",),
        shard=SHARD_TELEMETRY),
    KeyFamily(
        name="fleet-soak",
        pattern="fleet/{ns}/beacon",
        owner="scripts/fleet_soak.py", lifecycle=PERSISTENT,
        description="fleet-soak watch fan-out beacon: the driver puts a "
                    "timestamped payload, every synthetic worker watches "
                    "the prefix and reports delivery lag",
        prefix="fleet/", helpers=("fleet_beacon_key",
                                  "fleet_beacon_prefix"),
        shard=SHARD_TELEMETRY),
    KeyFamily(
        name="fleet-models",
        pattern="fleet_models/{ns}/{model}",
        owner="fleet/registry.py", lifecycle=PERSISTENT,
        description="desired-state model registry: one record per served "
                    "model (card ref, component, chip shape, min/max "
                    "replicas, priority, tenant quota table) mutated by "
                    "`ctl fleet add/remove`, reconciled by the planner's "
                    "fleet plane and watched by fleet routers/frontends",
        prefix="fleet_models/",
        helpers=("fleet_model_key", "fleet_models_prefix"),
        constants=("FLEET_MODELS_PREFIX",)),
    KeyFamily(
        name="fleet-status",
        pattern="fleet_status/{ns}/{model}",
        owner="fleet/registry.py", lifecycle=LEASE,
        description="observed per-model state (replicas, target, "
                    "ready/booting/draining/off, chips, SLO burn) "
                    "published lease-bound by the reconciling planner; "
                    "rendered by GET /v1/models, dyntop and plannerctl",
        prefix="fleet_status/",
        helpers=("fleet_status_key", "fleet_status_prefix"),
        constants=("FLEET_STATUS_PREFIX",)),
    KeyFamily(
        name="mobility",
        pattern="mobility/{ns}/(prefetch|swap)/{component}"
                " | mobility/{ns}/wake/{model}",
        owner="fleet/mobility/keys.py", lifecycle=PERSISTENT,
        description="model-mobility control plane: per-component weight "
                    "prefetch hints (arbiter swap-group siblings + `ctl "
                    "fleet add --prewarm`), SIGUSR1-style swap commands "
                    "one worker of the component claims-by-delete, and "
                    "per-model last-wake records (path swap|cold, "
                    "seconds) read by /v1/models, dyntop and the soak "
                    "wake lane",
        prefix="mobility/",
        helpers=("mobility_prefetch_key", "mobility_prefix",
                 "mobility_swap_key", "mobility_wake_key",
                 "mobility_wake_prefix")),
    KeyFamily(
        name="faults",
        pattern="faults/{point}",
        owner="utils/faults.py", lifecycle=PERSISTENT,
        description="live fault-injection points (operator-written; value "
                    "is the fault spec) watched by every process",
        prefix="faults/", constants=("FAULTS_PREFIX",)),
    KeyFamily(
        name="overload",
        pattern="overload/{ns}/brownout",
        owner="utils/overload.py", lifecycle=LEASE,
        description="fleet-wide brownout level published by the brownout "
                    "controller, watched by frontends + routers",
        prefix="overload/", helpers=("brownout_key",),
        constants=("BROWNOUT_PREFIX",)),
    KeyFamily(
        name="traces",
        pattern="traces/{trace_id}/{span_id}",
        owner="utils/tracing.py", lifecycle=TTL,
        description="cross-process span sink (TTL-leased, rotated at "
                    "ttl/2) read by GET /v1/traces/{request_id}",
        prefix="traces/", helpers=("trace_store_key",),
        constants=("TRACE_STORE_PREFIX",), shard=SHARD_TRACES),
    KeyFamily(
        name="planner",
        pattern="planner/{ns}/(state|override|decisions/{seq:016d})",
        owner="planner/loop.py", lifecycle=PERSISTENT,
        description="autoscaler plane: lease-bound liveness state, "
                    "operator override/pause, decision audit log "
                    "(pruned by the loop itself)",
        prefix="planner/",
        helpers=("planner_prefix", "state_key", "override_key",
                 "decisions_prefix")),
    KeyFamily(
        name="kv-cluster",
        pattern="kv_cluster/{ns}/{component}/{worker_id:x}",
        owner="llm/kv_cluster/registry.py", lifecycle=LEASE,
        description="cluster-wide sealed-block registry: one lease-bound "
                    "record per worker (tier geometry + resident host/disk "
                    "hashes) watched by routers for cluster-hit scoring; "
                    "dead owners' records vanish with their lease",
        prefix="kv_cluster/", helpers=("cluster_key", "cluster_prefix"),
        constants=("KV_CLUSTER_PREFIX",), shard=SHARD_KV),
    KeyFamily(
        name="disagg-config",
        pattern="disagg/{ns}/{model}",
        owner="llm/disagg.py", lifecycle=PERSISTENT,
        description="disaggregation router thresholds, watched live by "
                    "decode workers (etcd-watched config in the "
                    "reference)",
        prefix="disagg/", helpers=("disagg_config_key",),
        constants=("DISAGG_CONFIG_PREFIX",)),
    KeyFamily(
        name="prefill-queue",
        pattern="{ns}.prefill[.batch]",
        owner="llm/disagg.py", lifecycle=QUEUE,
        description="per-priority remote-prefill work queues (interactive "
                    "keeps the legacy unsuffixed name)",
        helpers=("prefill_queue_name", "prefill_queue_names"),
        shard=SHARD_QUEUE),
    KeyFamily(
        name="prefill-cancel",
        pattern="{ns}.prefill/cancelled/{request_id}",
        owner="llm/disagg.py", lifecycle=TTL,
        description="cancellation tombstones letting prefill workers drop "
                    "dequeued jobs nobody waits for (TTL-leased)",
        helpers=("_cancel_key",), shard=SHARD_QUEUE),
    KeyFamily(
        name="regions",
        pattern="regions/{ns}/{agg_id:x}",
        owner="runtime/scale/regions.py", lifecycle=LEASE,
        description="hierarchical observer tree: one lease-bound record "
                    "per regional aggregator (pre-merged stage metrics + "
                    "ForwardPassMetrics of its rendezvous-owned workers), "
                    "read by fetch_stage_states / planner / SLO / dyntop "
                    "instead of the flat per-worker scrape; a dead "
                    "aggregator's record vanishes with its lease and the "
                    "surviving peers re-absorb its workers",
        prefix="regions/", helpers=("region_key", "regions_prefix"),
        constants=("REGIONS_PREFIX",), shard=SHARD_TELEMETRY),
    KeyFamily(
        name="incidents",
        pattern="incidents/{ns}/(beacon|bundle/{id})/...",
        owner="obs/incidents.py", lifecycle=TTL,
        description="coordinated incident capture: beacons (the "
                    "manifest every process watches — any trigger "
                    "freezes fleet-wide ring dumps) and per-process "
                    "flight-recorder dumps under the bundle prefix; "
                    "both expire with their DYN_INCIDENT_TTL lease",
        prefix="incidents/",
        helpers=("incident_beacon_key", "incident_beacon_prefix",
                 "incident_dump_key", "incident_dump_prefix"),
        constants=("INCIDENT_PREFIX",), shard=SHARD_TELEMETRY),
    KeyFamily(
        name="deployments",
        pattern="deploy/deployments/{ns}/{name}",
        owner="deploy/crd.py", lifecycle=PERSISTENT,
        description="DynamoDeployment specs (the CRD store), watched by "
                    "the operator reconcile loop",
        prefix="deploy/deployments/", helpers=("deploy_key",),
        constants=("DEPLOY_PREFIX",)),
    KeyFamily(
        name="deploy-status",
        pattern="deploy/status/{ns}/{name}",
        owner="deploy/operator.py", lifecycle=PERSISTENT,
        description="observed deployment state written back by the "
                    "operator (deleted when the deployment goes)",
        prefix="deploy/status/", helpers=("status_key",),
        constants=("STATUS_PREFIX",)),
    KeyFamily(
        name="deploy-artifacts",
        pattern="deploy/artifacts/{name}/{version:08d}[.json]",
        owner="deploy/artifacts.py", lifecycle=PERSISTENT,
        description="artifact descriptors (image digests, object-store "
                    "pointers) versioned per name",
        prefix="deploy/artifacts/", helpers=("descriptor_key",),
        constants=("ARTIFACT_PREFIX",)),
]

KEYSPACE: Dict[str, KeyFamily] = {f.name: f for f in _ALL}
if len(KEYSPACE) != len(_ALL):
    raise RuntimeError("duplicate keyspace family registration")

#: literal prefixes, longest first (so deploy/status/ wins over deploy/)
PREFIXES: List[Tuple[str, KeyFamily]] = sorted(
    ((f.prefix, f) for f in _ALL if f.prefix),
    key=lambda p: -len(p[0]))

HELPER_INDEX: Dict[str, KeyFamily] = {
    h: f for f in _ALL for h in f.helpers}
CONSTANT_INDEX: Dict[str, KeyFamily] = {
    c: f for f in _ALL for c in f.constants}


def family_for_literal(head: str) -> Optional[KeyFamily]:
    """The registered family a literal key head belongs to, if any."""
    for prefix, fam in PREFIXES:
        if head.startswith(prefix) or prefix.startswith(head):
            return fam
    return None


def families_for_prefix(prefix: str) -> List[str]:
    """Every family a ``get_prefix``/``watch_prefix`` over ``prefix``
    could touch — the sharded store client's fan-out set (a scan may
    span families: ``metrics_stage/`` covers both ``metrics-stage`` and
    ``metrics-store``). Falls back to the placeholder-led patterns like
    :func:`classify_key`; an unmatchable prefix returns ``["other"]``
    (routed to the default shard), and the EMPTY prefix scans every
    family."""
    if prefix == "":
        return [f.name for f in _ALL] + ["other"]
    out = [fam.name for p, fam in PREFIXES
           if p.startswith(prefix) or prefix.startswith(p)]
    if "/components/" in prefix:
        out.append("endpoints")
    if not out:
        out.append("other")
    return out


def classify_key(key: str) -> str:
    """Family name for a FULL key/queue name (the store's own per-op
    telemetry labels every ``dyn_store_op_seconds`` series with this).

    Unlike :func:`family_for_literal` (which accepts partial heads for the
    lint resolver), this requires a real prefix match, then falls back to
    the placeholder-led patterns the registry cannot express as literals:
    endpoint registrations (``{ns}/components/...``) and the per-namespace
    prefill queue/cancel names. Everything else is ``"other"`` — a growing
    ``other`` rate in the store dump means an unregistered keyspace.
    """
    for prefix, fam in PREFIXES:
        if key.startswith(prefix):
            return fam.name
    if "/components/" in key:
        return "endpoints"
    if ".prefill/cancelled/" in key:
        return "prefill-cancel"
    if key.endswith(".prefill") or key.endswith(".prefill.batch"):
        return "prefill-queue"
    return "other"


def render_markdown(wire_fields: Optional[Dict[str, str]] = None) -> str:
    """The generated body of docs/keyspace.md (store families + the wire
    control-header field registry — the two distributed-protocol
    surfaces gated by dynalint).

    ``wire_fields`` defaults to importing ``wire.WIRE_FIELDS`` — the lint
    rule passes its AST-extracted copy instead, so a full dynalint run
    never imports wire.py (and thus msgpack) on analysis-only machines."""
    if wire_fields is None:
        from .wire import WIRE_FIELDS as wire_fields

    out = [
        "# Keyspace & wire protocol registry",
        "",
        "<!-- GENERATED FILE — do not edit by hand. "
        "Regenerate: python -m dynamo_tpu.runtime.keyspace --write -->",
        "",
        "The two cross-process protocol surfaces, generated from their",
        "central registries and gated two-way by dynalint "
        "(`store-key-drift`,",
        "`wire-field-drift` — see [static analysis](static_analysis.md)):",
        "every producer/consumer call site must resolve to a registered",
        "entry, every entry must still be used, and this file must match",
        "the registries byte-for-byte.",
        "",
        "## Store keyspace (`dynamo_tpu/runtime/keyspace.py`)",
        "",
        "Lifecycle: **lease** keys vanish with their owner's session "
        "lease;",
        "**persistent** keys live until an explicit delete; **ttl** keys "
        "ride",
        "a short no-keepalive lease; **queue** names address dynstore "
        "work",
        "queues rather than KV keys.",
        "",
        "The **shard** column is the family's ownership group when the",
        "store is split across dynstore processes: a `DYN_STORE_SHARDS`",
        "token may name a group to route all of its families to one",
        "shard (see [observability](observability.md) § Scale "
        "plane).",
        "Unrouted families (and the `other` fallback) stay on the",
        "default store.",
        "",
        "| family | key pattern | owner | lifecycle | shard | "
        "description |",
        "|---|---|---|---|---|---|",
    ]
    for f in sorted(_ALL, key=lambda f: f.name):
        out.append(f"| `{f.name}` | `{f.pattern}` | {f.owner} | "
                   f"{f.lifecycle} | {f.shard} | {f.description} |")
    out.extend([
        "",
        f"{len(_ALL)} key families registered.",
        "",
        "## Wire control-header fields (`dynamo_tpu/runtime/wire.py`)",
        "",
        "Every field name that may appear in a two-part frame's control",
        "header. Producers/consumers must spell these through the",
        "registry constants — planes that drop unknown fields degrade",
        "gracefully, but a misspelled field is a silent protocol fork.",
        "",
        "| field | description |",
        "|---|---|",
    ])
    for name in sorted(wire_fields):
        out.append(f"| `{name}` | {wire_fields[name]} |")
    out.extend(["", f"{len(wire_fields)} wire fields registered.", ""])
    return "\n".join(out)


def _main(argv: List[str]) -> int:
    import os
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    target = os.path.join(repo, "docs", "keyspace.md")
    if "--write" in argv:
        with open(target, "w", encoding="utf-8") as f:
            f.write(render_markdown())
        print(f"wrote {target} ({len(KEYSPACE)} key families)")
    else:
        print(render_markdown())
    return 0


if __name__ == "__main__":          # pragma: no cover - trivial shell
    import sys
    sys.exit(_main(sys.argv[1:]))
