"""Per-instance circuit breaker for data-plane clients.

The :class:`Client` failover path used to keep a per-CALL ``failed`` set: an
instance that refused a connection was skipped for the rest of that one
request, then retried from scratch by the next — under churn every request
burned a connect timeout on the same dead worker. The breaker keeps
CROSS-request accounting per instance:

- ``closed``    — healthy, routable.
- ``open``      — >= ``threshold`` consecutive connect/exchange failures;
  not routable until ``cooldown`` seconds pass.
- ``half_open`` — cooldown elapsed; routable so the next request acts as the
  probe. Success closes the circuit, failure re-opens it (fresh cooldown).

Knobs (env, read at construction): ``DYN_CB_THRESHOLD`` (consecutive
failures to open, default 3; ``0`` disables the breaker), ``DYN_CB_COOLDOWN``
(seconds open before the half-open probe, default 5).

State per instance is exported on ``dyn_circuit_state`` (0 closed,
1 half-open, 2 open). Mirrors the reference's NATS-client reconnect-throttle
role; etcd-watch membership remains the authoritative live set — the breaker
only vetoes instances the watch still believes in.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List

from ..utils.knobs import env_float as _env_float

log = logging.getLogger("dynamo_tpu.circuit")

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class _Entry:
    __slots__ = ("failures", "opened_at")

    def __init__(self) -> None:
        self.failures = 0
        self.opened_at = 0.0        # 0 => never opened / currently closed


class InstanceBreaker:
    """Cross-request failure accounting for one Client's instance set."""

    def __init__(self, threshold: int = None, cooldown: float = None):
        self.threshold = int(_env_float("DYN_CB_THRESHOLD", 3)) \
            if threshold is None else threshold
        self.cooldown = _env_float("DYN_CB_COOLDOWN", 5.0) \
            if cooldown is None else cooldown
        self._entries: Dict[int, _Entry] = {}

    # ------------------------------------------------------------------
    def state(self, iid: int) -> str:
        e = self._entries.get(iid)
        if e is None or not e.opened_at:
            return CLOSED
        if time.monotonic() - e.opened_at >= self.cooldown:
            return HALF_OPEN
        return OPEN

    def allow(self, iid: int) -> bool:
        """May a new request be routed to this instance right now?"""
        if self.threshold <= 0:
            return True
        return self.state(iid) is not OPEN

    def filter(self, ids: List[int]) -> List[int]:
        """Routable subset. If the breaker would veto EVERY live instance,
        it stands down (returns ``ids`` unchanged): total unavailability
        must come from the membership plane, never from the breaker."""
        if self.threshold <= 0:
            return ids
        allowed = [i for i in ids if self.allow(i)]
        return allowed or ids

    # ------------------------------------------------------------------
    def record_failure(self, iid: int) -> None:
        if self.threshold <= 0:
            return
        e = self._entries.setdefault(iid, _Entry())
        was = self.state(iid)
        e.failures += 1
        if e.failures >= self.threshold or was is HALF_OPEN:
            # threshold crossed, or the half-open probe failed: (re)open
            e.opened_at = time.monotonic()
            if was is not OPEN:
                log.warning("instance %x circuit OPEN after %d consecutive "
                            "failures (cooldown %.1fs)", iid, e.failures,
                            self.cooldown)
                # breaker trip = incident trigger: freeze fleet rings
                # around the moment the instance went dark (no-op in
                # processes without an incident manager)
                from ..obs import incidents as _incidents

                _incidents.trigger("breaker_trip", instance=f"{iid:x}",
                                   failures=e.failures)
        self._export(iid)

    def record_success(self, iid: int) -> None:
        e = self._entries.get(iid)
        if e is None:
            return
        if e.opened_at:
            log.info("instance %x circuit closed (probe succeeded)", iid)
        e.failures = 0
        e.opened_at = 0.0
        self._export(iid)

    def forget(self, iid: int) -> None:
        """Instance deregistered: drop accounting + its exported series."""
        if self._entries.pop(iid, None) is not None:
            from ..utils.prometheus import stage_metrics

            stage_metrics().circuit_state.clear_label(1, f"{iid:x}")

    # ------------------------------------------------------------------
    def _export(self, iid: int) -> None:
        from ..utils.prometheus import stage_metrics

        stage_metrics().circuit_state.set(
            str(os.getpid()), f"{iid:x}",
            value=_STATE_VALUE[self.state(iid)])
