"""Framing + message codec shared by every dynamo_tpu TCP protocol.

Frame = 4-byte big-endian length || msgpack payload. One codec for the store
protocol, the request/data plane and the C++ implementations to come — a
single place defines the bytes on the wire.

The data plane additionally uses two-part messages: a small control header
(dict) plus an optional raw binary payload, packed as one msgpack array
[control, payload]. This mirrors the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs) so large tensors ride
untouched next to JSON-ish control data.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional, Tuple

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # 256 MB: KV block transfers ride this plane

# Optional span-context field on request control headers: [trace_id,
# parent_span_id]. Rides next to ``context_id`` so one request's spans
# stitch across processes (utils/tracing.py). Planes that drop unknown
# control fields (the native C parser) degrade to trace_id == context_id.
TRACE_KEY = "trace"

# Optional overload-priority field on request control headers
# ("interactive" | "batch", utils/overload.py). Absent => interactive —
# planes that drop unknown fields degrade to the protective default.
PRIORITY_KEY = "priority"


def attach_trace(control: dict) -> dict:
    """Stamp the ambient span context onto a request control header."""
    from ..utils.tracing import wire_context

    tw = wire_context()
    if tw is not None:
        control[TRACE_KEY] = tw
    return control


def extract_trace(control: dict, default_trace_id=None):
    """SpanContext from a control header (see utils.tracing.extract_wire)."""
    from ..utils.tracing import extract_wire

    return extract_wire(control.get(TRACE_KEY),
                        default_trace_id=default_trace_id)


def pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return struct.pack(">I", len(body)) + body


def pack_two_part(control: dict, payload: Optional[bytes] = None) -> bytes:
    return pack([control, payload])


def unpack_two_part(obj: Any) -> Tuple[dict, Optional[bytes]]:
    control, payload = obj
    return control, payload


class FrameReader:
    """Incremental frame decoder over an asyncio StreamReader.

    ``read()`` is CANCELLATION-SAFE at the frame level: a reader task
    cancelled between the length header and the body (e.g. the data plane's
    control watcher being torn down mid-frame) leaves the parsed length in
    ``_pending_len``, and the next ``read()`` resumes with the body instead
    of desynchronizing the stream. (StreamReader.readexactly itself only
    consumes bytes once all n are buffered, so cancelling it is safe.)"""

    def __init__(self, reader: asyncio.StreamReader):
        self._r = reader
        self._pending_len: Optional[int] = None

    async def read(self) -> Any:
        """Read one frame; raises asyncio.IncompleteReadError on EOF."""
        if self._pending_len is None:
            # unbounded-ok: read() is the framing PRIMITIVE — boundedness
            # is the caller's contract (deadline.wait_for on request paths,
            # connection-lifetime rx loops elsewhere)
            hdr = await self._r.readexactly(4)
            (n,) = struct.unpack(">I", hdr)
            if n > MAX_FRAME:
                raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
            self._pending_len = n
        # unbounded-ok: see header read above — callers bound read()
        body = await self._r.readexactly(self._pending_len)
        self._pending_len = None
        return msgpack.unpackb(body, raw=False)


async def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    writer.write(pack(obj))
    # unbounded-ok: drain parks only on TCP backpressure from a live peer;
    # a dead peer errors it, and request paths carry their own deadline
    await writer.drain()
