"""Framing + message codec shared by every dynamo_tpu TCP protocol.

Frame = 4-byte big-endian length || msgpack payload. One codec for the store
protocol, the request/data plane and the C++ implementations to come — a
single place defines the bytes on the wire.

The data plane additionally uses two-part messages: a small control header
(dict) plus an optional raw binary payload, packed as one msgpack array
[control, payload]. This mirrors the reference's TwoPartCodec
(lib/runtime/src/pipeline/network/codec/two_part.rs) so large tensors ride
untouched next to JSON-ish control data.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional, Tuple

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # 256 MB: KV block transfers ride this plane

# ---------------------------------------------------------------------------
# control-header field registry
#
# Every field name that may appear in a two-part frame's control header is
# declared HERE and spelled through these constants everywhere else — the
# ``wire-field-drift`` dynalint rule gates it two-way (a literal spelling
# in dataplane code fails the run, a constant nobody reads is stale) and
# docs/keyspace.md renders the table. One misspelled field between a
# producer and a consumer that "drops unknown fields gracefully" is a
# silent protocol fork; the registry makes the field surface reviewable.
# ---------------------------------------------------------------------------

# frame discriminator: request | prologue | data | part | end | sentinel |
# stop | kill | error
KIND_KEY = "kind"
# target endpoint name on request frames
ENDPOINT_KEY = "endpoint"
# request identity, stable across hops (trace_id defaults to it)
CONTEXT_ID_KEY = "context_id"
# payload content type: "bin" passes raw bytes through untouched
CTYPE_KEY = "ctype"
# request body arrives as a client-side stream of "part" frames
STREAMING_KEY = "streaming"
# absolute deadline (unix seconds) riding the envelope (runtime/deadline.py)
DEADLINE_KEY = "deadline"

# Optional span-context field on request control headers: [trace_id,
# parent_span_id]. Rides next to ``context_id`` so one request's spans
# stitch across processes (utils/tracing.py). Planes that drop unknown
# control fields (the native C parser) degrade to trace_id == context_id.
TRACE_KEY = "trace"

# Optional overload-priority field on request control headers
# ("interactive" | "batch", utils/overload.py). Absent => interactive —
# planes that drop unknown fields degrade to the protective default.
PRIORITY_KEY = "priority"

# Optional resume-attempt ordinal on request control headers (llm/resume.py
# mid-stream failover). Attempt N of a broken stream re-enters the plane
# under the SAME context_id with resume = N (first dispatch omits it /
# sends 0): a worker holding a still-active context of that id yields to
# the higher ordinal instead of answering the duplicate-context 409 — the
# original handler is a zombie whose client already gave up on it.
RESUME_KEY = "resume"

# error-frame fields (runtime/component.py error_control/error_from_control)
MESSAGE_KEY = "message"          # human-readable error text
CODE_KEY = "code"                # http-ish status carried by EngineError
STAGE_KEY = "stage"              # pipeline stage that shed/expired
REASON_KEY = "reason"            # machine reason (overload shed class etc.)
RETRY_AFTER_KEY = "retry_after"  # client backoff hint, seconds

#: field name -> description; the registry the drift gate + docs render.
#: (Plain literal dict on purpose: the lint rule reads it via AST, no
#: import of this module — and thus msgpack — at analysis time.)
WIRE_FIELDS = {
    "kind": "frame discriminator: request | prologue | data | part | end "
            "| sentinel | stop | kill | error",
    "endpoint": "target endpoint name on request frames",
    "context_id": "request identity, stable across hops; trace_id "
                  "defaults to it",
    "ctype": "payload content type ('bin' = raw bytes pass-through)",
    "streaming": "request body arrives as a stream of 'part' frames",
    "deadline": "absolute end-to-end deadline, unix seconds",
    "trace": "span context [trace_id, parent_span_id] for cross-process "
             "stitching",
    "priority": "overload class: interactive | batch (absent => "
                "interactive)",
    "resume": "mid-stream failover attempt ordinal; a higher ordinal "
              "supersedes an active context of the same id",
    "message": "error frame: human-readable text",
    "code": "error frame: http-ish status code",
    "stage": "error frame: pipeline stage that shed/expired the request",
    "reason": "error frame: machine-readable reason",
    "retry_after": "error frame: client backoff hint, seconds",
}


def attach_trace(control: dict) -> dict:
    """Stamp the ambient span context onto a request control header."""
    from ..utils.tracing import wire_context

    tw = wire_context()
    if tw is not None:
        control[TRACE_KEY] = tw
    return control


def extract_trace(control: dict, default_trace_id=None):
    """SpanContext from a control header (see utils.tracing.extract_wire)."""
    from ..utils.tracing import extract_wire

    return extract_wire(control.get(TRACE_KEY),
                        default_trace_id=default_trace_id)


def pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return struct.pack(">I", len(body)) + body


def pack_two_part(control: dict, payload: Optional[bytes] = None) -> bytes:
    return pack([control, payload])


def unpack_two_part(obj: Any) -> Tuple[dict, Optional[bytes]]:
    """Split a decoded two-part frame into (control, payload).

    Raises a typed ``ValueError`` on malformed frames (wrong arity, or a
    non-dict control header) instead of leaking a bare unpack
    ``TypeError`` into rx loops — a corrupt or hostile peer must surface
    as a protocol error the connection handlers already classify."""
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise ValueError(
            f"malformed two-part frame: expected [control, payload], "
            f"got {type(obj).__name__}"
            + (f" of length {len(obj)}"
               if isinstance(obj, (list, tuple)) else ""))
    control, payload = obj
    if not isinstance(control, dict):
        raise ValueError(f"malformed two-part frame: control header is "
                         f"{type(control).__name__}, expected dict")
    return control, payload


class FrameReader:
    """Incremental frame decoder over an asyncio StreamReader.

    ``read()`` is CANCELLATION-SAFE at the frame level: a reader task
    cancelled between the length header and the body (e.g. the data plane's
    control watcher being torn down mid-frame) leaves the parsed length in
    ``_pending_len``, and the next ``read()`` resumes with the body instead
    of desynchronizing the stream. (StreamReader.readexactly itself only
    consumes bytes once all n are buffered, so cancelling it is safe.)"""

    def __init__(self, reader: asyncio.StreamReader):
        self._r = reader
        self._pending_len: Optional[int] = None

    async def read(self) -> Any:
        """Read one frame; raises asyncio.IncompleteReadError on EOF."""
        if self._pending_len is None:
            # unbounded-ok: read() is the framing PRIMITIVE — boundedness
            # is the caller's contract (deadline.wait_for on request paths,
            # connection-lifetime rx loops elsewhere)
            hdr = await self._r.readexactly(4)
            (n,) = struct.unpack(">I", hdr)
            if n > MAX_FRAME:
                raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
            self._pending_len = n
        # unbounded-ok: see header read above — callers bound read()
        body = await self._r.readexactly(self._pending_len)
        self._pending_len = None
        return msgpack.unpackb(body, raw=False)


async def write_frame(writer: asyncio.StreamWriter, obj: Any) -> None:
    writer.write(pack(obj))
    # unbounded-ok: drain parks only on TCP backpressure from a live peer;
    # a dead peer errors it, and request paths carry their own deadline
    await writer.drain()
