"""Composable pipeline graph: generic Operator nodes over AsyncEngine.

An ``Operator`` owns both directions of one pipeline segment: it transforms
the request on the way *forward* (toward the engine) and the response
stream on the way *backward* (toward the caller) — the bidirectional node
shape of the reference's pipeline graph. Operators compose right-to-left
around a terminal engine:

    engine = compose(OpA(), OpB(), backend)     # A(B(backend))
    # request: A.forward -> B.forward -> backend
    # stream:  backend -> B.backward -> A.backward

``compose`` returns a plain AsyncEngine, so a composed pipeline drops into
every place an engine goes (HTTP service, endpoint server, another
pipeline). The LLM preprocessor/backend chain (llm/pipeline.py) is the
specialized, fused version of this shape; these nodes cover the general
case (custom middleware: routing, annotation, validation, recording).

Reference capability: lib/runtime/src/pipeline.rs:41-68 (ServiceFrontend →
Operator fwd/bwd edges → ServiceBackend), pipeline/nodes.rs.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Generic, TypeVar

from .engine import AsyncEngine, Context

In = TypeVar("In")
Out = TypeVar("Out")
NextIn = TypeVar("NextIn")
NextOut = TypeVar("NextOut")


class Operator(Generic[In, Out, NextIn, NextOut]):
    """One bidirectional pipeline segment."""

    async def forward(self, request: In, context: Context) -> NextIn:
        """Transform the request for the downstream node."""
        return request  # type: ignore[return-value]

    def backward(self, stream: AsyncIterator[NextOut], request: In,
                 context: Context) -> AsyncIterator[Out]:
        """Transform the downstream response stream for the upstream node.
        Default: pass-through."""
        return stream  # type: ignore[return-value]


class _OperatorEngine(AsyncEngine):
    def __init__(self, op: Operator, inner: AsyncEngine):
        self.op = op
        self.inner = inner

    async def generate(self, request, context: Context):
        fwd = await self.op.forward(request, context)
        stream = self.inner.generate(fwd, context)
        async for item in self.op.backward(stream, request, context):
            yield item


def compose(*nodes: Any) -> AsyncEngine:
    """``compose(op1, op2, ..., engine)``: wrap the terminal engine with
    operators right-to-left. A bare AsyncEngine in operator position is a
    segment boundary error."""
    if not nodes:
        raise ValueError("compose() needs at least a terminal engine")
    engine = nodes[-1]
    if not isinstance(engine, AsyncEngine):
        raise TypeError("last compose() argument must be an AsyncEngine")
    for op in reversed(nodes[:-1]):
        if not isinstance(op, Operator):
            raise TypeError(f"{op!r} is not an Operator")
        engine = _OperatorEngine(op, engine)
    return engine


class SegmentSink(AsyncEngine):
    """Terminal node adapting a plain async function
    ``fn(request, context) -> AsyncIterator`` into an engine (the
    reference's ServiceBackend over a closure engine)."""

    def __init__(self, fn):
        self.fn = fn

    async def generate(self, request, context: Context):
        async for item in self.fn(request, context):
            yield item
