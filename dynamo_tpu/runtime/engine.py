"""The universal engine abstraction.

Everything that turns a request into a stream of responses — the JAX engine,
the echo test engines, remote clients, routers — implements :class:`AsyncEngine`.
Mirrors the capability of the reference's ``AsyncEngine`` trait
(reference: lib/runtime/src/engine.rs:22-145): ``generate(SingleIn<Req>) ->
ManyOut<Resp>`` with a per-request context carrying ``id``, cooperative
``stop_generating`` and hard ``kill`` signals.

Idiomatic Python shape: ``generate()`` is an async function returning an async
iterator of responses; the context travels with the request.
"""

from __future__ import annotations

import asyncio
import contextlib
import uuid
from typing import Any, AsyncIterator, Awaitable, Callable, Generic, Optional, TypeVar

Req = TypeVar("Req")
Resp = TypeVar("Resp")


class Context:
    """Per-request lifecycle control.

    Carries the request id and two levels of cancellation:

    - ``stop_generating()`` — cooperative: the engine should finish the current
      step, emit what it has, and end the stream.
    - ``kill()`` — hard: the engine should drop the request immediately.

    Reference capability: ``AsyncEngineContext`` (lib/runtime/src/engine.rs:71-109).
    """

    __slots__ = ("id", "deadline", "priority", "resume_no", "_stopped",
                 "_killed", "_children")

    def __init__(self, id: Optional[str] = None,
                 deadline: Optional[float] = None,
                 priority: str = "interactive"):
        self.id: str = id or uuid.uuid4().hex
        # mid-stream failover attempt ordinal (llm/resume.py): attempt N of
        # a broken stream re-enters the plane under the SAME id with
        # resume_no = N, superseding a zombie context of a lower ordinal
        # at the worker's duplicate-context guard
        self.resume_no: int = 0
        # absolute wall-clock (time.time()) end-to-end deadline; rides the
        # wire envelope so every hop can refuse work nobody awaits anymore
        self.deadline: Optional[float] = deadline
        # overload-control class ("interactive" | "batch", utils/overload):
        # rides the wire envelope too — shedding and queue ordering at
        # every stage strictly prefer interactive
        self.priority: str = priority
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list["Context"] = []

    # -- signalling ---------------------------------------------------------
    def stop_generating(self) -> None:
        self._stopped.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()
        for c in self._children:
            c.kill()

    # -- queries ------------------------------------------------------------
    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed(self) -> None:
        await self._killed.wait()

    def child(self, id: Optional[str] = None) -> "Context":
        """A linked context: signals on self propagate to the child (the
        deadline is inherited — a sub-call cannot outlive its request)."""
        c = Context(id or self.id, deadline=self.deadline,
                    priority=self.priority)
        if self.is_killed:
            c.kill()
        elif self.is_stopped:
            c.stop_generating()
        self._children.append(c)
        return c


class AsyncEngine(Generic[Req, Resp]):
    """Single-in, many-out engine: one request => an async stream of responses."""

    async def generate(self, request: Req, context: Context) -> AsyncIterator[Resp]:
        raise NotImplementedError

    def __call__(self, request: Req, context: Optional[Context] = None):
        return self.generate(request, context or Context())


class FnEngine(AsyncEngine[Req, Resp]):
    """Wrap an async-generator function as an engine (the common case in tests
    and Python endpoint handlers)."""

    def __init__(self, fn: Callable[..., AsyncIterator[Resp]], name: str = "fn"):
        self._fn = fn
        self.name = name

    async def generate(self, request: Req, context: Context) -> AsyncIterator[Resp]:
        agen = self._fn(request, context)
        if isinstance(agen, Awaitable):
            agen = await agen
        async for item in agen:
            if context.is_killed:
                break
            yield item
            if context.is_stopped:
                break
        with contextlib.suppress(Exception):
            await agen.aclose()  # type: ignore[union-attr]


def engine_from_fn(fn: Callable[..., AsyncIterator[Resp]], name: str = "fn") -> FnEngine:
    return FnEngine(fn, name)


async def collect(stream: AsyncIterator[Resp]) -> list[Resp]:
    """Drain an engine stream into a list (test helper)."""
    return [item async for item in stream]


class EngineError(Exception):
    """An error produced by an engine before or during streaming; carries an
    optional http-ish status code so frontends can map it, plus the typed
    overload/deadline fields every failure response exposes uniformly:
    ``stage`` (which pipeline hop failed), ``reason`` (which rule fired)
    and ``retry_after`` (seconds — the 429/503 Retry-After hint). All three
    survive the wire (error-frame control fields) so a frontend's error
    body names the REMOTE stage that shed or expired the request."""

    def __init__(self, message: str, code: int = 500, *,
                 stage: Optional[str] = None, reason: Optional[str] = None,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.code = code
        self.stage = stage
        self.reason = reason
        self.retry_after = retry_after


Any_ = Any
