"""Scale plane: what keeps the coordination layer flat as the fleet grows.

Two parts (docs/observability.md § "Scale plane"):

- :mod:`regions` — the hierarchical observer tree. Regional aggregator
  daemons (``cli/aggregator.py``) each own a rendezvous-hashed slice of
  the fleet's workers, pre-merge their per-worker telemetry, and publish
  ONE lease-bound region record per tick; every observer (planner,
  SLO monitor, dyntop, ``fetch_stage_states``) reads R region records
  instead of N worker dumps, and falls back to the flat scrape when no
  aggregator is running (zero-config single-node behavior unchanged).
- :mod:`shards` — the store itself split by keyspace family.
  :class:`~.shards.ShardedStoreClient` routes every key-bearing call
  through ``keyspace.classify_key()`` to the owning dynstore process
  (static ``DYN_STORE_SHARDS`` map); a shard being down degrades only
  its families.
"""

from .rendezvous import rendezvous_owner, rendezvous_shares  # noqa: F401
from .shards import (  # noqa: F401
    ShardedStoreClient,
    make_store_client,
    parse_shard_map,
)
