"""Hierarchical observer tree: regional pre-merge of worker telemetry.

PR 9's fleet soak proved the flat observer path saturates first: every
observer (planner signal collector, SLO monitor, dyntop, ``/metrics``)
re-fetched and re-merged hundreds of per-worker ``metrics_stage/`` dumps
per tick, and the merge p50 degraded 0.3s → 2.8s before the store itself
knelt. The fix is a tree:

- **Regional aggregators** (``cli/aggregator.py`` daemons) each own a
  slice of the fleet — assignment is a rendezvous hash of the worker id
  over the live aggregator ids, so membership churn only re-homes the
  dead region's workers. Each tick an aggregator scrapes its owned
  workers' ``metrics_stage/`` dumps (resolving the full+delta overlay
  with the existing :func:`~dynamo_tpu.llm.metrics_aggregator.
  merge_stage_items` protocol) and their ForwardPassMetrics snapshots,
  pre-merges them per component with
  :func:`~dynamo_tpu.utils.prometheus.merge_state_dumps`, and publishes
  ONE lease-bound region record.
- **Readers** fetch R region records instead of N worker dumps:
  :func:`fetch_region_states` returns the same ``(component,
  state_dump)`` shape every existing consumer (quantiles, SLO burn,
  breaker state, shed totals) already eats, plus per-component worker
  ids and per-worker ForwardPassMetrics. When no fresh record exists
  the caller falls back to the flat scrape — single-node zero-config
  deployments never notice the plane exists.
- **Region death**: records are lease-bound, so a dead aggregator's
  record vanishes; the surviving peers (each watches the ``regions/``
  prefix) see the membership change and the rendezvous re-assignment
  absorbs the orphaned workers on their next tick. Readers skip records
  older than ``DYN_REGION_STALE`` seconds — a wedged (but lease-alive)
  aggregator degrades its region to invisible rather than serving
  frozen telemetry.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ...utils.knobs import env_float
from .rendezvous import rendezvous_owner

log = logging.getLogger("dynamo_tpu.scale.regions")

REGIONS_PREFIX = "regions/"


def regions_prefix(namespace: str) -> str:
    return f"{REGIONS_PREFIX}{namespace}/"


def region_key(namespace: str, agg_id: int) -> str:
    """One aggregator's record key; the suffix is its lease id (like an
    endpoint registration), so the record dies with the daemon."""
    return f"{REGIONS_PREFIX}{namespace}/{agg_id:x}"


def region_interval() -> float:
    return env_float("DYN_REGION_INTERVAL", 2.0, minimum=0.1)


def region_stale_s() -> float:
    """Age beyond which a region record is treated as dead (default
    3 publish intervals — one missed tick survives, a wedge does not)."""
    return env_float("DYN_REGION_STALE", 3.0 * region_interval(),
                     minimum=0.5)


@dataclass
class RegionRecord:
    """What one aggregator publishes per tick. ``components`` maps a
    component name to its pre-merged view::

        {"worker_ids": [int, ...],          # owned publishers
         "state": <merged registry state_dump>,
         "fpm": {"<wid:x>": <ForwardPassMetrics dict>, ...}}
    """

    agg_id: int
    seq: int
    ts: float                    # wall clock of the merge
    interval_s: float
    peers: int                   # live aggregators this one saw
    worker_count: int
    components: Dict[str, Dict] = field(default_factory=dict)
    merge_s: List[float] = field(default_factory=list)   # recent ticks

    def to_dict(self) -> Dict:
        return {"agg_id": self.agg_id, "seq": self.seq, "ts": self.ts,
                "interval_s": self.interval_s, "peers": self.peers,
                "worker_count": self.worker_count,
                "components": self.components,
                "merge_s": [round(v, 6) for v in self.merge_s]}

    @classmethod
    def from_dict(cls, d: Dict) -> "RegionRecord":
        return cls(agg_id=int(d["agg_id"]), seq=int(d.get("seq", 0)),
                   ts=float(d.get("ts", 0.0)),
                   interval_s=float(d.get("interval_s", 0.0)),
                   peers=int(d.get("peers", 1)),
                   worker_count=int(d.get("worker_count", 0)),
                   components=dict(d.get("components") or {}),
                   merge_s=list(d.get("merge_s") or ()))


@dataclass
class RegionStates:
    """One region-tree read, in every shape the flat consumers expect."""

    states: List[Tuple[str, Dict]]            # (component, state_dump)
    ids: Dict[str, Set[int]]                  # component -> worker ids
    fpm: Dict[str, Dict[int, Dict]]           # component -> wid -> dict
    meta: Dict                                # the dyntop "regions:" line

    @property
    def worker_count(self) -> int:
        return sum(len(v) for v in self.ids.values())

    def workers_for(self, component: str) -> Dict[int, object]:
        """One component's ForwardPassMetrics off the region records —
        the shared parse both the planner's collector and dyntop use
        (a malformed row skips that worker, never the read)."""
        from ...llm.kv_router.protocols import ForwardPassMetrics

        out: Dict[int, object] = {}
        for wid, d in (self.fpm.get(component) or {}).items():
            try:
                out[wid] = ForwardPassMetrics.from_dict(d)
            except Exception:  # noqa: BLE001 - one bad record must not
                # blind the whole component
                log.warning("malformed region fpm for %s/%x",
                            component, wid)
        return out


def _percentile(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(int(q * len(s)), len(s) - 1)]


async def fetch_region_states(store, namespace: str,
                              stale_s: Optional[float] = None,
                              now: Optional[float] = None
                              ) -> Optional[RegionStates]:
    """Read the region plane: None when no aggregator publishes a fresh
    record for this namespace (caller falls back to the flat scrape).
    Stale records are skipped — and if EVERY record is stale the whole
    read returns None rather than serving a frozen fleet.

    Staleness is skew-tolerant: the ``stale_s`` window compares a
    record against the FRESHEST record's timestamp (aggregator clocks
    vs each other — a single wedged aggregator goes invisible while its
    peers keep publishing), while the reader's own wall clock only
    backstops the all-aggregators-wedged case at a much coarser window
    (``10 x stale_s``, >= 60s) — so a reader host with modest clock
    skew cannot silently disable the whole region plane."""
    stale_s = region_stale_s() if stale_s is None else stale_s
    now = time.time() if now is None else now
    try:
        items = await store.get_prefix(regions_prefix(namespace))
    except Exception:  # noqa: BLE001 - region plane optional by design
        log.debug("region fetch failed; flat fallback", exc_info=True)
        return None
    records: List[RegionRecord] = []
    for key, value in items:
        try:
            records.append(RegionRecord.from_dict(
                json.loads(value.decode())))
        except Exception:  # noqa: BLE001 - one bad record must not blind
            log.warning("malformed region record at %s", key)
    max_ts = max((r.ts for r in records), default=0.0)
    wedge_s = max(10.0 * stale_s, 60.0)
    fresh = [r for r in records
             if max_ts - r.ts <= stale_s and now - r.ts <= wedge_s]
    if not fresh:
        return None
    states: List[Tuple[str, Dict]] = []
    ids: Dict[str, Set[int]] = {}
    fpm: Dict[str, Dict[int, Dict]] = {}
    merge_samples: List[float] = []
    per_region: List[Dict] = []
    for r in sorted(fresh, key=lambda r: r.agg_id):
        merge_samples.extend(r.merge_s)
        per_region.append({"agg_id": f"{r.agg_id:x}",
                           "workers": r.worker_count,
                           "age_s": round(max(now - r.ts, 0.0), 3),
                           "seq": r.seq})
        for comp, view in r.components.items():
            st = view.get("state")
            if st:
                states.append((comp, st))
            comp_ids = ids.setdefault(comp, set())
            for wid in view.get("worker_ids") or ():
                comp_ids.add(int(wid))
            comp_fpm = fpm.setdefault(comp, {})
            for widhex, d in (view.get("fpm") or {}).items():
                try:
                    comp_fpm[int(widhex, 16)] = d
                except ValueError:
                    continue
    meta = {
        "aggregators": len(fresh),
        "stale": len(records) - len(fresh),
        "workers": sum(r.worker_count for r in fresh),
        "workers_min": min((r.worker_count for r in fresh), default=0),
        "workers_max": max((r.worker_count for r in fresh), default=0),
        "merge_p50_s": _percentile(merge_samples, 0.50),
        "merge_p99_s": _percentile(merge_samples, 0.99),
        "age_max_s": max((x["age_s"] for x in per_region), default=0.0),
        "regions": per_region,
    }
    return RegionStates(states=states, ids=ids, fpm=fpm, meta=meta)


# ---------------------------------------------------------------------------
# the aggregator daemon core (cli/aggregator.py drives it)
# ---------------------------------------------------------------------------
class RegionalAggregator:
    """One node of the observer tree. Owns the rendezvous slice of the
    namespace's workers implied by the live aggregator membership (its
    peers' lease-bound ``regions/`` records, watched live), pre-merges
    their telemetry every ``interval`` seconds and publishes one region
    record under its own lease."""

    def __init__(self, store, namespace: str, agg_id: int, lease: int,
                 interval: Optional[float] = None,
                 merge_ring: int = 32):
        self.store = store
        self.namespace = namespace
        self.agg_id = agg_id
        self.lease = lease
        self.interval = region_interval() if interval is None else interval
        self._member = f"{agg_id:x}"
        self._peers: Set[str] = {self._member}
        self._seq = 0
        self._merge_ring = merge_ring
        self._merge_s: List[float] = []
        self._task: Optional[asyncio.Task] = None
        self.last_record: Optional[RegionRecord] = None

    # -- membership ----------------------------------------------------
    async def _on_peer(self, key: str, value: Optional[bytes],
                       deleted: bool) -> None:
        member = key.rsplit("/", 1)[-1]
        if deleted:
            if member != self._member:
                self._peers.discard(member)
                log.info("region peer %s died; %d aggregators remain "
                         "(orphans re-absorb next tick)", member,
                         len(self._peers))
        else:
            if member not in self._peers:
                log.info("region peer %s joined (%d aggregators)",
                         member, len(self._peers) + 1)
            self._peers.add(member)

    async def start(self) -> "RegionalAggregator":
        snapshot = await self.store.watch_prefix(
            regions_prefix(self.namespace), self._on_peer)
        for key, _value in snapshot:
            self._peers.add(key.rsplit("/", 1)[-1])
        return self

    def owns(self, worker_id: int) -> bool:
        """Ownership is rendezvous over the worker's stage SLICE (its
        worker-stable ``metrics_stage/`` sub-prefix), so the stage scan
        below can read exactly the owned slices and nothing else while
        ForwardPassMetrics filtering agrees with it."""
        from ...llm.metrics_aggregator import stage_slice_of

        return rendezvous_owner(stage_slice_of(worker_id),
                                sorted(self._peers)) == self._member

    def owned_slices(self) -> List[int]:
        from ...llm.metrics_aggregator import stage_slices

        members = sorted(self._peers)
        return [s for s in range(stage_slices())
                if rendezvous_owner(s, members) == self._member]

    # -- one tick ------------------------------------------------------
    async def tick(self) -> RegionRecord:
        from ...llm.metrics_aggregator import (METRICS_PREFIX,
                                               STAGE_PREFIX,
                                               merge_stage_items,
                                               split_stage_key,
                                               stage_base_key,
                                               stage_slice_prefix)
        from ...utils.prometheus import merge_state_dumps, stage_metrics

        t0 = time.perf_counter()
        ns_prefix = f"{STAGE_PREFIX}{self.namespace}/"
        # FPM scan FIRST: the round-trip also drains any pending peer-
        # membership watch deliveries on this connection (a peer's
        # ``regions/`` put strictly precedes our request on the wire), so
        # the slice-ownership computed below reflects the membership as
        # of this tick — the ordering the pre-slice code got implicitly
        # from awaiting the full stage scan before filtering
        fpm: Dict[str, Dict[str, Dict]] = {}
        fpm_raw: Dict[str, Dict[int, bytes]] = {}
        fpm_prefix = f"{METRICS_PREFIX}{self.namespace}/"
        for key, value in await self.store.get_prefix(fpm_prefix):
            comp, _, widhex = key[len(fpm_prefix):].partition("/")
            try:
                wid = int(widhex, 16)
            except ValueError:
                log.warning("malformed metrics key %s", key)
                continue
            # raw bytes only here: the ownership filter below runs before
            # any JSON decode, so each aggregator decodes its N/R share
            # of the fleet's payloads, not all N
            fpm_raw.setdefault(comp, {})[wid] = value
        # read ONLY the owned slices: each is a worker-stable sub-prefix
        # of the stage keyspace, so a region tick's store read (and the
        # JSON decode + full/delta overlay below, the expensive part) is
        # O(owned workers) — membership churn re-homes whole slices
        # without any publisher writing a new key
        comp_states: Dict[str, List[Dict]] = {}
        comp_ids: Dict[str, Set[int]] = {}
        owned_items = []
        # the slice reads are independent: fetch them concurrently (one
        # round-trip's latency, not owned-slice-count of them)
        slice_reads = await asyncio.gather(*(
            self.store.get_prefix(stage_slice_prefix(self.namespace, s))
            for s in self.owned_slices()))
        for items in slice_reads:
            for key, value in items:
                base = stage_base_key(key)
                comp, widhex = split_stage_key(base[len(ns_prefix):])
                try:
                    wid = int(widhex, 16)
                except ValueError:
                    log.warning("malformed stage key %s", key)
                    continue
                owned_items.append((key, value))
                # liveness must not depend on payload health: a live
                # worker mid-write still counts as a replica (same rule
                # as the flat collector)
                comp_ids.setdefault(comp, set()).add(wid)
        for base, (doc, metrics) in merge_stage_items(
                owned_items).items():
            comp, _widhex = split_stage_key(base[len(ns_prefix):])
            comp_states.setdefault(doc.get("component") or comp,
                                   []).append(metrics)
        for comp, rows in fpm_raw.items():
            for wid, value in rows.items():
                if not self.owns(wid):
                    continue
                try:
                    fpm.setdefault(comp, {})[f"{wid:x}"] = json.loads(
                        value.decode())
                except ValueError:
                    log.warning("malformed metrics payload for %s/%x",
                                comp, wid)
        components: Dict[str, Dict] = {}
        for comp in set(comp_ids) | set(fpm) | set(comp_states):
            components[comp] = {
                "worker_ids": sorted(comp_ids.get(comp, ())),
                "state": merge_state_dumps(comp_states.get(comp, ())),
                "fpm": fpm.get(comp, {}),
            }
        dt = time.perf_counter() - t0
        self._merge_s.append(dt)
        del self._merge_s[:-self._merge_ring]
        self._seq += 1
        record = RegionRecord(
            agg_id=self.agg_id, seq=self._seq, ts=time.time(),
            interval_s=self.interval, peers=len(self._peers),
            worker_count=sum(len(v) for v in comp_ids.values()),
            components=components, merge_s=list(self._merge_s))
        await self.store.put(
            region_key(self.namespace, self.agg_id),
            json.dumps(record.to_dict()).encode(), lease=self.lease)
        stage_metrics().region_merge.observe(value=dt)
        self.last_record = record
        return record

    # -- standing loop --------------------------------------------------
    async def run(self) -> None:
        from ...runtime.store_client import StoreError

        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except StoreError:
                log.warning("region tick skipped (store unreachable)")
            except Exception:
                log.exception("region tick failed")
            await asyncio.sleep(self.interval)

    def start_loop(self) -> None:
        from ...utils.aiotasks import spawn

        self._task = spawn(self.run(), name=f"region-{self._member}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
