"""Store sharding by keyspace family: one logical store, N dynstore procs.

``DYN_STORE_SHARDS`` declares a static shard map::

    DYN_STORE_SHARDS="telemetry=127.0.0.1:5001;traces=127.0.0.1:5002"

Each entry routes a comma-separated list of keyspace **family** names or
**shard group** names (the ``shard`` column of ``docs/keyspace.md`` —
``telemetry`` expands to metrics/metrics-stage/metrics-store/fleet-soak/
regions) to one dynstore address. Families not named anywhere (and the
``other`` fallback) stay on the default store every component is already
pointed at — so an empty/unset ``DYN_STORE_SHARDS`` is byte-identical to
the unsharded world.

:class:`ShardedStoreClient` exposes the full :class:`~dynamo_tpu.runtime.
store_client.StoreClient` surface and routes every key-bearing call
through :func:`~dynamo_tpu.runtime.keyspace.classify_key` to the owning
shard:

- ``put``/``get``/``create``/``delete`` and the ``q_*`` queue ops go to
  exactly one shard;
- ``get_prefix``/``watch_prefix`` resolve the prefix to its possible
  families (:func:`~dynamo_tpu.runtime.keyspace.families_for_prefix`)
  and fan out only when the scan genuinely spans shards, merging the
  results; a partially-failed fan-out returns what the live shards hold
  and counts ``dyn_store_shard_errors_total{shard}``;
- **leases** are session-wide: ``lease_grant`` grants on the default
  shard and mirrors the lease onto every other shard under the same id
  (the server's ``reuse`` grant — the same mechanism session replay
  uses), so one worker lease bounds its keys on every shard and each
  per-shard client keeps its own keepalive + reconnect + replay loop;
- a shard being DOWN degrades only its families: calls routed to it
  raise the same typed ``StoreError(code="conn_lost")`` the unsharded
  client raises, while every other family keeps serving. Losing the
  lease on ANY shard fires the composite ``on_lease_lost`` — liveness
  is all-or-nothing, a worker half-registered across shards must
  restart rather than zombie-serve.

Pub/sub subjects are an event plane, not keys: they stay on the default
shard.
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

from .. import keyspace
from ..store_client import ReconnectConfig, StoreClient, StoreError

log = logging.getLogger("dynamo_tpu.scale.shards")

WatchCallback = Callable[[str, Optional[bytes], bool], Awaitable[None]]


@dataclass(frozen=True)
class ShardSpec:
    """One dynstore process of the sharded store."""

    name: str          # "s0" (default) / "s1" / ... — the metric label
    host: str
    port: int


def _expand_token(token: str) -> List[str]:
    """A DYN_STORE_SHARDS token is a family name or a shard group name
    (which expands to every family registered under that group)."""
    token = token.strip()
    if token in keyspace.KEYSPACE:
        return [token]
    group = [f.name for f in keyspace.KEYSPACE.values()
             if f.shard == token]
    if group:
        return group
    raise ValueError(
        f"DYN_STORE_SHARDS names unknown family/group {token!r} "
        f"(families: {sorted(keyspace.KEYSPACE)}; groups: "
        f"{sorted({f.shard for f in keyspace.KEYSPACE.values()})})")


def parse_shard_map(raw: str, default_host: str, default_port: int
                    ) -> Tuple[List[ShardSpec], Dict[str, int]]:
    """``(specs, family->shard index)`` from the env syntax. Shard 0 is
    always the default store; entries sharing an address share a shard.
    A family routed twice is a config error, not a silent last-wins."""
    specs: List[ShardSpec] = [ShardSpec("s0", default_host, default_port)]
    addr_idx: Dict[Tuple[str, int], int] = {
        (default_host, default_port): 0}
    fam_map: Dict[str, int] = {}
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        names, _, addr = entry.partition("=")
        if not addr or ":" not in addr:
            raise ValueError(f"DYN_STORE_SHARDS entry {entry!r}: expected "
                             f"'<family|group>[,...]=host:port'")
        host, _, port_s = addr.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(f"DYN_STORE_SHARDS entry {entry!r}: "
                             f"malformed port {port_s!r}") from None
        idx = addr_idx.get((host, port))
        if idx is None:
            idx = len(specs)
            addr_idx[(host, port)] = idx
            specs.append(ShardSpec(f"s{idx}", host, port))
        for token in names.split(","):
            for fam in _expand_token(token):
                prev = fam_map.setdefault(fam, idx)
                if prev != idx:
                    raise ValueError(
                        f"DYN_STORE_SHARDS routes family {fam!r} to two "
                        f"shards (s{prev} and s{idx})")
    return specs, fam_map


def make_store_client(host: str, port: int,
                      reconnect: Optional[ReconnectConfig] = None,
                      shards_env: Optional[str] = None):
    """THE store-client constructor: a plain :class:`StoreClient` when
    ``DYN_STORE_SHARDS`` is unset/empty (zero-config single-store path,
    byte-identical behavior), a :class:`ShardedStoreClient` otherwise.
    ``host:port`` is always the default shard."""
    raw = os.environ.get("DYN_STORE_SHARDS", "") \
        if shards_env is None else shards_env
    if not raw.strip():
        return StoreClient(host, port, reconnect)
    specs, fam_map = parse_shard_map(raw, host, port)
    return ShardedStoreClient(specs, fam_map, reconnect)


class ShardedStoreClient:
    """N per-shard :class:`StoreClient` sessions behind the one-client
    API. See the module docstring for the routing/lease/degradation
    contract. ``clients`` is injectable for tests."""

    def __init__(self, specs: List[ShardSpec], fam_map: Dict[str, int],
                 reconnect: Optional[ReconnectConfig] = None,
                 clients: Optional[List] = None):
        if not specs:
            raise ValueError("sharded store needs at least the default "
                             "shard")
        self.specs = list(specs)
        self.fam_map = dict(fam_map)
        self.shards = (list(clients) if clients is not None else
                       [StoreClient(s.host, s.port, reconnect)
                        for s in specs])
        # the default shard answers for un-routed families and the
        # event/queue planes callers address without keys
        self.host, self.port = specs[0].host, specs[0].port
        self.reconnect = self.shards[0].reconnect \
            if hasattr(self.shards[0], "reconnect") else reconnect
        # primary lease id -> {shard idx -> that shard's lease id}
        # (ids match everywhere when the server honors ``reuse``; the
        # map absorbs servers that cannot)
        self._mirrors: Dict[int, Dict[int, int]] = {}
        self._lost_fired: Set[int] = set()
        self.on_lease_lost: Optional[Callable[[int], None]] = None
        self.on_session_replayed: Optional[Callable[[], None]] = None
        for i, sh in enumerate(self.shards):
            if hasattr(sh, "on_lease_lost"):
                sh.on_lease_lost = (
                    lambda lid, idx=i: self._shard_lease_lost(idx, lid))
            if hasattr(sh, "on_session_replayed"):
                sh.on_session_replayed = self._shard_replayed

    # -- identity ------------------------------------------------------
    @property
    def sharded(self) -> bool:
        return True

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    @property
    def shard_names(self) -> List[str]:
        return [s.name for s in self.specs]

    def describe(self) -> List[Dict]:
        """Operator-facing map: shard -> address + owned families."""
        owned: Dict[int, List[str]] = {}
        for fam, idx in sorted(self.fam_map.items()):
            owned.setdefault(idx, []).append(fam)
        return [{"shard": s.name, "addr": f"{s.host}:{s.port}",
                 "families": owned.get(i, ["<default>"] if i == 0 else [])}
                for i, s in enumerate(self.specs)]

    # -- routing -------------------------------------------------------
    def _idx_for_family(self, fam: str) -> int:
        return self.fam_map.get(fam, 0)

    def _idx_for_key(self, key: str) -> int:
        return self._idx_for_family(keyspace.classify_key(key))

    def _idxs_for_prefix(self, prefix: str) -> List[int]:
        idxs: List[int] = []
        for fam in keyspace.families_for_prefix(prefix):
            i = self._idx_for_family(fam)
            if i not in idxs:
                idxs.append(i)
        return idxs or [0]

    def _count_error(self, idx: int) -> None:
        from ...utils.prometheus import stage_metrics

        stage_metrics().store_shard_errors.inc(self.specs[idx].name)

    # -- lifecycle -----------------------------------------------------
    async def connect(self) -> "ShardedStoreClient":
        # all shards must answer at startup (a component half-connected
        # to its keyspace is worse than one that fails to boot — same
        # strictness as the single-store client); on partial failure the
        # survivors are closed so a caller's retry loop leaks nothing
        results = await asyncio.gather(
            *(sh.connect() for sh in self.shards),
            return_exceptions=True)
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            for sh, r in zip(self.shards, results):
                if not isinstance(r, BaseException):
                    try:
                        await sh.close()
                    except Exception:  # noqa: BLE001 - best-effort
                        log.debug("shard close failed during connect "
                                  "rollback", exc_info=True)
            raise errs[0]
        return self

    async def close(self) -> None:
        await asyncio.gather(*(sh.close() for sh in self.shards),
                             return_exceptions=True)

    async def wait_connected(self) -> None:
        for sh in self.shards:
            await sh.wait_connected()

    async def ping(self) -> bool:
        results = await asyncio.gather(*(sh.ping() for sh in self.shards),
                                       return_exceptions=True)
        return all(r is True for r in results)

    # -- leases --------------------------------------------------------
    def _shard_lease_lost(self, idx: int, shard_lid: int) -> None:
        primary = next((p for p, m in self._mirrors.items()
                        if m.get(idx) == shard_lid), shard_lid)
        if primary in self._lost_fired:
            return
        self._lost_fired.add(primary)
        log.warning("lease %x lost on shard %s; session liveness is gone",
                    primary, self.specs[idx].name)
        if self.on_lease_lost is not None:
            try:
                self.on_lease_lost(primary)
            except Exception:
                log.exception("on_lease_lost callback")

    def _shard_replayed(self) -> None:
        if self.on_session_replayed is not None:
            try:
                self.on_session_replayed()
            except Exception:
                log.exception("on_session_replayed callback")

    async def lease_grant(self, ttl: float = 5.0,
                          auto_keepalive: bool = True,
                          bind: bool = True) -> int:
        lid = await self.shards[0].lease_grant(
            ttl, auto_keepalive=auto_keepalive, bind=bind)
        mirrors = {0: lid}
        try:
            for i, sh in enumerate(self.shards[1:], 1):
                mirrors[i] = await sh.lease_grant(
                    ttl, auto_keepalive=auto_keepalive, reuse=lid,
                    bind=bind)
        except Exception:
            # half-granted liveness is worse than no lease: roll back
            for i, mid in mirrors.items():
                try:
                    await self.shards[i].lease_revoke(mid)
                except Exception:  # noqa: BLE001 - best-effort rollback
                    log.debug("lease rollback failed on %s",
                              self.specs[i].name)
            raise
        self._mirrors[lid] = mirrors
        return lid

    def _lease_on(self, idx: int, lease: Optional[int]) -> Optional[int]:
        if lease is None:
            return None
        return self._mirrors.get(lease, {}).get(idx, lease)

    async def lease_revoke(self, lease: int) -> None:
        mirrors = self._mirrors.pop(lease, {0: lease})
        err: Optional[Exception] = None
        for i, sh in enumerate(self.shards):
            mid = mirrors.get(i)
            if mid is None:
                continue
            try:
                await sh.lease_revoke(mid)
            except Exception as e:  # noqa: BLE001 - revoke every shard
                # first; a dead shard's mirror expires by TTL anyway
                log.debug("lease revoke failed on %s",
                          self.specs[i].name, exc_info=True)
                if i == 0:
                    err = e
        if err is not None:
            raise err

    # -- KV ------------------------------------------------------------
    async def put(self, key: str, value: bytes,
                  lease: Optional[int] = None) -> None:
        idx = self._idx_for_key(key)
        await self.shards[idx].put(key, value,
                                   lease=self._lease_on(idx, lease))

    async def create(self, key: str, value: bytes,
                     lease: Optional[int] = None,
                     or_validate: bool = False) -> bool:
        idx = self._idx_for_key(key)
        return await self.shards[idx].create(
            key, value, lease=self._lease_on(idx, lease),
            or_validate=or_validate)

    async def get(self, key: str) -> Optional[bytes]:
        return await self.shards[self._idx_for_key(key)].get(key)

    async def delete(self, key: str) -> bool:
        return await self.shards[self._idx_for_key(key)].delete(key)

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        idxs = self._idxs_for_prefix(prefix)
        if len(idxs) == 1:
            return await self.shards[idxs[0]].get_prefix(prefix)
        results = await asyncio.gather(
            *(self.shards[i].get_prefix(prefix) for i in idxs),
            return_exceptions=True)
        out: List[Tuple[str, bytes]] = []
        failed: List[int] = []
        for i, r in zip(idxs, results):
            if isinstance(r, BaseException):
                failed.append(i)
                self._count_error(i)
            else:
                out.extend(r)
        if failed and len(failed) == len(idxs):
            raise StoreError(
                f"get_prefix({prefix!r}): every owning shard failed",
                code="conn_lost")
        if failed:
            log.warning("get_prefix(%r): shard(s) %s down; serving the "
                        "surviving shards' slice", prefix,
                        [self.specs[i].name for i in failed])
        return sorted(out)

    async def get_prefix_on(self, idx: int, prefix: str
                            ) -> List[Tuple[str, bytes]]:
        """Read ONE shard's slice of a prefix (dyntop's per-shard store
        telemetry: every shard publishes its own self-dump under the
        same ``metrics_stage/_store/`` key)."""
        return await self.shards[idx].get_prefix(prefix)

    async def watch_prefix(self, prefix: str, callback: WatchCallback
                           ) -> List[Tuple[str, bytes]]:
        idxs = self._idxs_for_prefix(prefix)
        if len(idxs) == 1:
            return await self.shards[idxs[0]].watch_prefix(prefix,
                                                           callback)
        snapshots = await asyncio.gather(
            *(self.shards[i].watch_prefix(prefix, callback)
              for i in idxs))
        return sorted(x for snap in snapshots for x in snap)

    # -- pub/sub (event plane: default shard) --------------------------
    async def subscribe(self, subject: str, callback) -> int:
        return await self.shards[0].subscribe(subject, callback)

    async def publish(self, subject: str, payload: bytes) -> int:
        return await self.shards[0].publish(subject, payload)

    # -- queues --------------------------------------------------------
    async def q_push(self, queue: str, payload: bytes) -> int:
        return await self.shards[self._idx_for_key(queue)].q_push(
            queue, payload)

    async def q_pull(self, queue: str) -> Tuple[int, bytes]:
        # unbounded-ok: delegates to the owning shard's q_pull, whose
        # parked wait already survives reconnects and rejects on close
        return await self.shards[self._idx_for_key(queue)].q_pull(queue)

    async def q_ack(self, queue: str, msg_id: int) -> None:
        await self.shards[self._idx_for_key(queue)].q_ack(queue, msg_id)

    async def q_len(self, queue: str) -> int:
        return await self.shards[self._idx_for_key(queue)].q_len(queue)
