"""Rendezvous (highest-random-weight) hashing: stable worker→owner maps.

The observer tree assigns each worker to exactly one regional aggregator
by rendezvous hash over the live aggregator ids. The property that makes
this the right tool (vs modulo or a ring with few vnodes): when the
member set changes, ONLY the keys owned by the departed member move (a
join steals an even ~1/(n+1) slice from everyone) — so an aggregator
crash re-homes its workers without reshuffling anyone else's region, and
the per-region merged histograms stay continuous for every unaffected
worker.

Pure, stdlib-only, deterministic across processes and Python runs
(sha1, not ``hash()`` — PYTHONHASHSEED must not partition the fleet
differently per process).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Sequence


def _weight(worker_id: int, member: str) -> int:
    h = hashlib.sha1(f"{worker_id:x}\x00{member}".encode())
    return int.from_bytes(h.digest()[:8], "big")


def rendezvous_owner(worker_id: int,
                     members: Sequence[str]) -> Optional[str]:
    """The member that owns ``worker_id`` — highest hash weight wins,
    ties broken by member name so every process agrees. None when the
    member set is empty."""
    best: Optional[str] = None
    best_w = -1
    for m in members:
        w = _weight(worker_id, m)
        if w > best_w or (w == best_w and (best is None or m < best)):
            best, best_w = m, w
    return best


def rendezvous_shares(worker_ids: Iterable[int],
                      members: Sequence[str]) -> Dict[str, List[int]]:
    """Partition ``worker_ids`` across ``members``: {member: owned ids}.
    Every member appears in the result (possibly with an empty slice)."""
    out: Dict[str, List[int]] = {m: [] for m in members}
    if not members:
        return out
    for wid in worker_ids:
        owner = rendezvous_owner(wid, members)
        out[owner].append(wid)
    return out
