"""Worker lifecycle: signal-driven graceful shutdown over a cancellation
token tree.

``Worker.execute(main)`` is the process entry used by every long-running
binary: it installs SIGINT/SIGTERM handlers that cancel the root
``CancellationToken``; the app receives the token (and usually hands child
tokens to its runtimes/endpoints). On cancellation the worker FIRST makes
itself invisible — endpoint registrations deregister via lease revoke
(``DistributedRuntime.prepare_drain``) so the watch plane stops routing new
work here, and queue-pull loops see the ``draining`` flag — then lets
in-flight streams run to completion for up to ``grace`` seconds
(``DYN_DRAIN_TIMEOUT``), cooperatively stops any stragglers (short flush
window), and hard-kills the rest. A second signal skips the grace period.

Reference capability: lib/runtime/src/worker.rs:60-99,182 (Worker::execute
+ ctrl-c → CancellationToken tree) and the ControlMessage Stop/Kill
semantics of engine.rs:71-85.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Awaitable, Callable, List, Optional

log = logging.getLogger("dynamo_tpu.worker")


class CancellationToken:
    """Hierarchical cancellation: cancelling a parent cancels all children
    (children cancelling does not propagate up) — the same tree shape the
    reference hangs off its runtime/lease/endpoint layers."""

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._children: List["CancellationToken"] = []
        self._callbacks: List[Callable[[], None]] = []
        self.parent = parent
        if parent is not None:
            parent._children.append(self)
            if parent.cancelled:
                self._event.set()

    def child(self) -> "CancellationToken":
        return CancellationToken(self)

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        if self._event.is_set():
            return
        self._event.set()
        for cb in self._callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001 - callbacks must not stop fanout
                log.exception("cancellation callback failed")
        for c in self._children:
            c.cancel()

    def on_cancel(self, cb: Callable[[], None]) -> None:
        """Register a sync callback; fires immediately if already cancelled."""
        if self.cancelled:
            cb()
        else:
            self._callbacks.append(cb)

    async def wait(self) -> None:
        await self._event.wait()


class Worker:
    """Process shell: runs an async app under a root cancellation token with
    signal-driven graceful shutdown.

        async def app(token):
            drt = await DistributedRuntime(...).connect()
            worker.add_runtime(drt)
            ...
            await token.wait()          # serve until shutdown

        Worker().execute(app)
    """

    def __init__(self, grace: Optional[float] = None):
        if grace is None:
            # drain budget: how long in-flight streams get to finish after
            # SIGTERM before the cooperative stop escalates to kill
            import os
            try:
                grace = float(os.environ.get("DYN_DRAIN_TIMEOUT", 10.0))
            except ValueError:
                grace = 10.0
        self.grace = grace
        self.token = CancellationToken()
        self._runtimes: List[object] = []
        self._signals = 0
        self._force = False   # second signal: skip the grace window

    def add_runtime(self, drt) -> None:
        """Runtimes registered here get their in-flight requests stopped
        (then killed) and their connections closed during shutdown."""
        self._runtimes.append(drt)

    # ------------------------------------------------------------------
    def _on_signal(self) -> None:
        self._signals += 1
        if self._signals == 1:
            log.info("shutdown signal: draining (grace %.1fs); "
                     "signal again to skip", self.grace)
            self.token.cancel()
        else:
            log.warning("second signal: hard shutdown")
            self._force = True
            for drt in self._runtimes:
                for ctx in list(getattr(drt, "_active", {}).values()):
                    ctx.kill()

    async def _run(self, app: Callable[[CancellationToken], Awaitable]) -> None:
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._on_signal)
            except (NotImplementedError, RuntimeError):
                pass   # non-main thread / platform without signal support
        app_task = asyncio.create_task(app(self.token))
        cancel_wait = asyncio.create_task(self.token.wait())
        try:
            done, _ = await asyncio.wait(
                {app_task, cancel_wait},
                return_when=asyncio.FIRST_COMPLETED)
            if app_task in done and not self.token.cancelled:
                # app returned (or raised) on its own, no shutdown signal
                cancel_wait.cancel()
                await app_task
                return
            # a cancelled token ALWAYS takes the shutdown path — even if
            # the app task completed in the same event-loop pass (the
            # documented 'await token.wait(); return' app pattern does),
            # in-flight requests must still be drained and leases revoked
            await self._shutdown(app_task)
        finally:
            cancel_wait.cancel()

    async def _shutdown(self, app_task: asyncio.Task) -> None:
        # flight-recorder heartbeat over the whole drain: a drain that
        # outlives grace*1.25 (natural window + flush window + slack) is a
        # wedged stream, and the watchdog turns it into a stall:drain span
        from ..obs import flightrec as _flightrec

        _flightrec.hb_begin("worker.drain", stall="drain",
                            budget=self.grace * 1.25 + 1.0)
        try:
            await self._shutdown_inner(app_task)
        finally:
            _flightrec.hb_end("worker.drain")

    async def _shutdown_inner(self, app_task: asyncio.Task) -> None:
        # 0. become invisible FIRST: deregister endpoints (lease revoke) so
        # the watch plane routes new work elsewhere, and flag draining so
        # queue-pull loops stop taking jobs — all before any stream is
        # disturbed.
        for drt in self._runtimes:
            prepare = getattr(drt, "prepare_drain", None)
            if prepare is not None:
                try:
                    await prepare()
                except Exception:  # noqa: BLE001 - drain is best-effort
                    log.exception("prepare_drain failed")
        # 1. natural drain: being deregistered, no NEW work arrives — let
        # in-flight streams run to completion within the drain budget
        # (clients get their full responses, not truncations)
        def active() -> int:
            return sum(len(getattr(drt, "_active", {}))
                       for drt in self._runtimes)

        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.grace
        while loop.time() < deadline and not self._force and active():
            await asyncio.sleep(0.05)
        # 2. budget spent: cooperatively stop the stragglers (engines
        # flush what they have and end their streams cleanly) and give
        # them a short flush window
        for drt in self._runtimes:
            for ctx in list(getattr(drt, "_active", {}).values()):
                ctx.stop_generating()
        flush_deadline = loop.time() + min(1.0, self.grace)
        while loop.time() < flush_deadline and not self._force and active():
            await asyncio.sleep(0.05)
        # 3. kill whatever is left
        for drt in self._runtimes:
            for ctx in list(getattr(drt, "_active", {}).values()):
                ctx.kill()
        # 4. close runtimes (revokes leases => endpoints deregister)
        for drt in self._runtimes:
            close = getattr(drt, "close", None)
            if close is not None:
                try:
                    await close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    log.exception("runtime close failed")
        app_task.cancel()
        try:
            await app_task
        except asyncio.CancelledError:
            pass
        except Exception:
            # the app coroutine failed BEFORE shutdown and nobody awaited
            # it yet — this reap is the last chance to see why
            log.exception("app task failed")

    def execute(self, app: Callable[[CancellationToken], Awaitable]) -> None:
        asyncio.run(self._run(app))
