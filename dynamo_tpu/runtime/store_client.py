"""Async client for dynstore (KV/lease/watch + pub/sub + queues).

One connection multiplexes everything: request/reply by id, plus pushed
frames routed to watch/subscription/queue callbacks. The API mirrors what the
runtime layers need (component registration, endpoint discovery, KV events,
prefill queue) — the union of the reference's etcd + NATS client surfaces
(lib/runtime/src/transports/{etcd,nats}.rs) behind one handle.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional, Tuple

from .wire import FrameReader, write_frame

log = logging.getLogger("dynamo_tpu.store.client")

WatchCallback = Callable[[str, Optional[bytes], bool], Awaitable[None]]
MsgCallback = Callable[[str, bytes], Awaitable[None]]


class StoreError(RuntimeError):
    """Error reply from the store (or transport loss).

    ``code`` is the machine-readable classification ("lease_not_found",
    "conn_lost", or "" for anything else). Branch on it, never on the
    human-readable text — a reworded server message must not silently flip
    terminal-vs-transient handling (ADVICE r4). Servers predating the
    ``code`` wire field get a legacy substring fallback at construction.
    """

    def __init__(self, msg: str, code: str = ""):
        super().__init__(msg)
        if not code:  # prebuilt/old server: classify by the known phrases
            low = msg.lower()
            if "lease not found" in low:
                code = "lease_not_found"
            elif "connection" in low:
                code = "conn_lost"
        self.code = code


class StoreClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 4222):
        self.host, self.port = host, port
        self._reader: Optional[FrameReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_cbs: Dict[int, WatchCallback] = {}
        self._sub_cbs: Dict[int, MsgCallback] = {}
        self._rx_task: Optional[asyncio.Task] = None
        self._push_q: "asyncio.Queue[Dict[str, Any]]" = asyncio.Queue()
        self._push_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: List[asyncio.Task] = []
        # fired (sync, on the loop) when a kept-alive lease is discovered
        # lost — liveness is gone, the owner should shut down/restart
        self.on_lease_lost: Optional[Callable[[int], None]] = None
        self._send_lock = asyncio.Lock()
        self.closed = asyncio.Event()

    # ------------------------------------------------------------------
    async def connect(self) -> "StoreClient":
        reader, writer = await asyncio.open_connection(self.host, self.port)
        self._reader = FrameReader(reader)
        self._writer = writer
        self._rx_task = asyncio.create_task(self._rx_loop(), name="store-rx")
        self._push_task = asyncio.create_task(self._push_loop(),
                                              name="store-push")
        return self

    async def close(self) -> None:
        for t in self._keepalive_tasks:
            t.cancel()
        if self._rx_task:
            self._rx_task.cancel()
        if self._push_task:
            self._push_task.cancel()
        if self._writer:
            self._writer.close()
        self.closed.set()

    async def _rx_loop(self) -> None:
        try:
            while True:
                msg = await self._reader.read()
                if "push" in msg:
                    # NEVER await user callbacks here: a callback that issues
                    # a store call would deadlock the rx loop (the reply is
                    # read by this very loop). FIFO queue keeps event order.
                    self._push_q.put_nowait(msg)
                else:
                    fut = self._pending.pop(msg.get("id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        StoreError("connection lost", code="conn_lost"))
            self._pending.clear()
            self.closed.set()

    async def _push_loop(self) -> None:
        try:
            while True:
                await self._handle_push(await self._push_q.get())
        except asyncio.CancelledError:
            pass

    async def _handle_push(self, msg: Dict[str, Any]) -> None:
        kind = msg["push"]
        try:
            if kind == "watch":
                cb = self._watch_cbs.get(msg["watch_id"])
                if cb:
                    await cb(msg["key"], msg.get("value"), msg["deleted"])
            elif kind == "msg":
                cb = self._sub_cbs.get(msg["sub_id"])
                if cb:
                    await cb(msg["subject"], msg["payload"])
        except Exception:
            log.exception("push handler failed")

    async def _call(self, op: str, **kw) -> Dict[str, Any]:
        rid = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            await write_frame(self._writer, {"op": op, "id": rid, **kw})
        reply = await fut
        if not reply.get("ok", False):
            raise StoreError(reply.get("error", "store error"),
                             code=reply.get("code", ""))
        return reply

    # -- KV -------------------------------------------------------------
    async def put(self, key: str, value: bytes,
                  lease: Optional[int] = None) -> None:
        await self._call("put", key=key, value=value, lease=lease)

    async def create(self, key: str, value: bytes,
                     lease: Optional[int] = None,
                     or_validate: bool = False) -> bool:
        r = await self._call("create", key=key, value=value, lease=lease,
                             or_validate=or_validate)
        return r.get("created", True)

    async def get(self, key: str) -> Optional[bytes]:
        r = await self._call("get", key=key)
        return r["value"] if r["found"] else None

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        r = await self._call("get_prefix", prefix=prefix)
        return [(k, v) for k, v in r["items"]]

    async def delete(self, key: str) -> bool:
        r = await self._call("delete", key=key)
        return r["deleted"]

    # -- leases ----------------------------------------------------------
    async def lease_grant(self, ttl: float = 5.0,
                          auto_keepalive: bool = True) -> int:
        r = await self._call("lease_grant", ttl=ttl)
        lease = r["lease"]
        if auto_keepalive:
            self._keepalive_tasks.append(asyncio.create_task(
                self._keepalive_loop(lease, ttl), name=f"lease-{lease}"))
        return lease

    def _fire_lease_lost(self, lease: int, why: str) -> None:
        # liveness is gone: registrations expire(d) server-side, so a
        # worker that kept serving would be an unroutable zombie. Mirror
        # the reference (etcd.rs:55-76 — lease loss cancels the worker's
        # token): notify so the shell can shut down for a clean restart.
        log.warning("lease %x lost (%s); keepalive stopping", lease, why)
        if self.on_lease_lost is not None:
            try:
                self.on_lease_lost(lease)
            except Exception:
                log.exception("on_lease_lost callback")

    async def _keepalive_loop(self, lease: int, ttl: float) -> None:
        try:
            while True:
                await asyncio.sleep(ttl / 3)
                try:
                    await self._call("lease_keepalive", lease=lease)
                except StoreError as e:
                    if e.code == "lease_not_found":
                        # expired server-side (e.g. after loop starvation)
                        self._fire_lease_lost(lease, str(e))
                        return
                    if e.code == "conn_lost":
                        # this client has ONE connection and no reconnect:
                        # once it is gone every renewal will fail and the
                        # lease WILL expire — that is a lease loss
                        self._fire_lease_lost(lease, str(e))
                        return
                    # other server hiccup (version skew, transient): the
                    # lease may still be alive — keep trying rather than
                    # orphaning a healthy lease
                    log.debug("lease %x keepalive error (retrying): %s",
                              lease, e)
                except (ConnectionError, OSError,
                        asyncio.IncompleteReadError) as e:
                    # transport died mid-call — same terminal state
                    self._fire_lease_lost(lease, f"{type(e).__name__}: {e}")
                    return
        except asyncio.CancelledError:
            pass

    async def lease_revoke(self, lease: int) -> None:
        await self._call("lease_revoke", lease=lease)

    # -- watches ---------------------------------------------------------
    async def watch_prefix(self, prefix: str, callback: WatchCallback
                           ) -> List[Tuple[str, bytes]]:
        """Start watching; returns the current snapshot; callback fires on
        every subsequent put/delete under the prefix."""
        wid = next(self._ids)
        self._watch_cbs[wid] = callback
        r = await self._call("watch", watch_id=wid, prefix=prefix)
        return [(k, v) for k, v in r["items"]]

    # -- pub/sub ---------------------------------------------------------
    async def subscribe(self, subject: str, callback: MsgCallback) -> int:
        sid = next(self._ids)
        self._sub_cbs[sid] = callback
        await self._call("subscribe", sub_id=sid, subject=subject)
        return sid

    async def publish(self, subject: str, payload: bytes) -> int:
        r = await self._call("publish", subject=subject, payload=payload)
        return r["delivered"]

    # -- queues -----------------------------------------------------------
    async def q_push(self, queue: str, payload: bytes) -> int:
        r = await self._call("q_push", queue=queue, payload=payload)
        return r["msg_id"]

    async def q_pull(self, queue: str) -> Tuple[int, bytes]:
        """Blocks until a message is available; must q_ack when done."""
        r = await self._call("q_pull", queue=queue)
        return r["msg_id"], r["payload"]

    async def q_ack(self, queue: str, msg_id: int) -> None:
        await self._call("q_ack", queue=queue, msg_id=msg_id)

    async def q_len(self, queue: str) -> int:
        return (await self._call("q_len", queue=queue))["len"]

    async def ping(self) -> bool:
        return (await self._call("ping")).get("pong", False)
