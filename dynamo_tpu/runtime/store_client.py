"""Async client for dynstore (KV/lease/watch + pub/sub + queues).

One connection multiplexes everything: request/reply by id, plus pushed
frames routed to watch/subscription/queue callbacks. The API mirrors what the
runtime layers need (component registration, endpoint discovery, KV events,
prefill queue) — the union of the reference's etcd + NATS client surfaces
(lib/runtime/src/transports/{etcd,nats}.rs) behind one handle.

Connection loss is survivable: pending calls fail fast with ``StoreError``
(code ``conn_lost``), then a reconnect loop with exponential backoff
(``DYN_STORE_RECONNECT_*``) re-establishes the **session** — leases are
re-granted under their original ids (the server's ``reuse`` grant), lease-
bound keys (endpoint/model registrations, metrics snapshots) are re-put,
prefix watches re-arm with a snapshot diff that synthesizes the put/delete
events missed during the outage, pub/sub subjects re-subscribe, and blocked
``q_pull`` loops resume. Only when the window is exhausted (or the server
cannot preserve a lease id) does ``on_lease_lost`` fire — the etcd-style
"liveness is truly gone, restart me" signal.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Set, Tuple

from ..obs import flightrec as _flightrec
from ..utils import faults
from .wire import FrameReader, write_frame

log = logging.getLogger("dynamo_tpu.store.client")

WatchCallback = Callable[[str, Optional[bytes], bool], Awaitable[None]]
MsgCallback = Callable[[str, bytes], Awaitable[None]]


class StoreError(RuntimeError):
    """Error reply from the store (or transport loss).

    ``code`` is the machine-readable classification ("lease_not_found",
    "conn_lost", or "" for anything else). Branch on it, never on the
    human-readable text — a reworded server message must not silently flip
    terminal-vs-transient handling (ADVICE r4). Servers predating the
    ``code`` wire field get a legacy substring fallback at construction.
    """

    def __init__(self, msg: str, code: str = ""):
        super().__init__(msg)
        if not code:  # prebuilt/old server: classify by the known phrases
            low = msg.lower()
            if "lease not found" in low:
                code = "lease_not_found"
            elif "connection" in low:
                code = "conn_lost"
        self.code = code


def _env_num(name: str, default: float, cast=float):
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return default


@dataclass
class ReconnectConfig:
    """Backoff schedule for store reconnects. ``attempts`` tries, sleeping
    ``base * 2^n`` capped at ``max_delay`` between them (defaults span
    ~8 s — comfortably above a store restart, below a lease TTL deluge)."""

    enabled: bool = True
    attempts: int = 10
    base: float = 0.05
    max_delay: float = 2.0

    @classmethod
    def from_env(cls) -> "ReconnectConfig":
        raw = os.environ.get("DYN_STORE_RECONNECT", "1").strip().lower()
        return cls(
            enabled=raw not in ("0", "false", "no", "off"),
            attempts=_env_num("DYN_STORE_RECONNECT_ATTEMPTS", 10, int),
            base=_env_num("DYN_STORE_RECONNECT_BASE", 0.05),
            max_delay=_env_num("DYN_STORE_RECONNECT_MAX", 2.0))


@dataclass
class _WatchState:
    """Per-watch replay state: the prefix, the last-known key set (updated
    in push order), and — during a replay — the keys real events touched
    since re-arm (so stale snapshot-diff synthetics are skipped)."""

    prefix: str
    known: Dict[str, bytes] = field(default_factory=dict)
    touched: Optional[Set[str]] = None


class StoreClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 4222,
                 reconnect: Optional[ReconnectConfig] = None):
        self.host, self.port = host, port
        self.reconnect = reconnect or ReconnectConfig.from_env()
        self._reader: Optional[FrameReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_cbs: Dict[int, WatchCallback] = {}
        self._watch_state: Dict[int, _WatchState] = {}
        self._sub_cbs: Dict[int, MsgCallback] = {}
        self._sub_subjects: Dict[int, str] = {}
        self._rx_task: Optional[asyncio.Task] = None
        self._push_q: "asyncio.Queue[Dict[str, Any]]" = asyncio.Queue()
        self._push_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: List[asyncio.Task] = []
        self._reconnect_task: Optional[asyncio.Task] = None
        # session state replayed on reconnect
        self._session_leases: Dict[int, float] = {}      # lease -> ttl
        self._lease_puts: Dict[str, Tuple[bytes, int]] = {}
        # fired (sync, on the loop) when a kept-alive lease is discovered
        # UNRECOVERABLY lost — reconnect/replay exhausted or the server
        # couldn't preserve the id; the owner should shut down/restart
        self.on_lease_lost: Optional[Callable[[int], None]] = None
        # fired (sync) after each successful session replay
        self.on_session_replayed: Optional[Callable[[], None]] = None
        self._send_lock = asyncio.Lock()
        self._gen = 0            # connection generation
        self._closing = False    # deliberate close() (or terminal failure)
        self._connected = asyncio.Event()
        self.closed = asyncio.Event()

    # ------------------------------------------------------------------
    async def connect(self) -> "StoreClient":
        await self._open_transport()
        self._push_task = asyncio.create_task(self._push_loop(),
                                              name="store-push")
        self._connected.set()
        return self

    async def _open_transport(self) -> None:
        await faults.fire("store.connect")
        # bounded: a blackholed store must not park the reconnect loop
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), 10.0)
        self._reader = FrameReader(reader)
        self._writer = writer
        self._gen += 1
        self._rx_task = asyncio.create_task(self._rx_loop(self._gen),
                                            name=f"store-rx-{self._gen}")

    async def close(self) -> None:
        self._closing = True
        for t in self._keepalive_tasks:
            t.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._rx_task:
            self._rx_task.cancel()
        if self._push_task:
            self._push_task.cancel()
        if self._writer:
            self._writer.close()
        self._fail_pending()
        self.closed.set()

    # ------------------------------------------------------------------
    def _fail_pending(self, why: str = "connection lost") -> None:
        """Reject every in-flight call NOW — a dead connection must fail
        fast, not hang callers forever (even with reconnect disabled)."""
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(StoreError(why, code="conn_lost"))

    def _conn_lost(self, gen: int, why: str) -> None:
        if gen != self._gen:
            return            # stale rx loop of an already-replaced transport
        self._connected.clear()
        self._fail_pending()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                # transport already torn down under us — reconnect (or
                # closed.set below) is the real recovery path either way
                log.debug("writer close failed in _conn_lost",
                          exc_info=True)
        if self._closing or not self.reconnect.enabled:
            self.closed.set()
            return
        log.warning("store connection lost (%s); reconnecting", why)
        _flightrec.note_event("store.conn_lost", why=why)
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.create_task(
                self._reconnect_loop(), name="store-reconnect")

    async def _rx_loop(self, gen: int) -> None:
        try:
            while True:
                # unbounded-ok: the rx loop lives exactly as long as the
                # connection; loss paths reject all pending futures below
                msg = await self._reader.read()
                if "push" in msg:
                    # NEVER await user callbacks here: a callback that issues
                    # a store call would deadlock the rx loop (the reply is
                    # read by this very loop). FIFO queue keeps event order.
                    self._push_q.put_nowait(msg)
                else:
                    fut = self._pending.pop(msg.get("id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except asyncio.CancelledError:
            self._fail_pending()
            self.closed.set()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                OSError) as e:
            self._conn_lost(gen, f"{type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 - ANY rx death must not orphan
            log.exception("store rx loop died")
            self._conn_lost(gen, f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------------
    # reconnect + session re-establishment
    # ------------------------------------------------------------------
    async def wait_connected(self) -> None:
        """Block until the session is (re-)established; raises StoreError
        when the client is closed or the reconnect window is exhausted."""
        while not self._connected.is_set():
            if self.closed.is_set():
                raise StoreError("connection lost (store unreachable)",
                                 code="conn_lost")
            conn = asyncio.ensure_future(self._connected.wait())
            dead = asyncio.ensure_future(self.closed.wait())
            try:
                # unbounded-ok: bounded by the reconnect window — the loop
                # always sets either _connected or closed
                await asyncio.wait({conn, dead},
                                   return_when=asyncio.FIRST_COMPLETED)
            finally:
                conn.cancel()
                dead.cancel()

    async def _reconnect_loop(self) -> None:
        from ..utils.prometheus import stage_metrics

        stage = stage_metrics()
        cfg = self.reconnect
        delay = cfg.base
        try:
            for attempt in range(1, cfg.attempts + 1):
                await asyncio.sleep(delay)
                delay = min(delay * 2, cfg.max_delay)
                stage.store_reconnects.inc("attempt")
                try:
                    await self._open_transport()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 - ANY failure is one
                    log.info("store reconnect attempt %d/%d failed: %s",
                             attempt, cfg.attempts, e)   # more attempt, not
                    continue                             # a dead loop
                try:
                    await self._replay_session()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 - e.g. a malformed
                    # server reply must burn an attempt, never kill the loop
                    log.warning("session replay failed (attempt %d/%d): %s",
                                attempt, cfg.attempts, e)
                    if self._writer is not None:
                        self._writer.close()
                    continue
                stage.store_reconnects.inc("ok")
                log.info("store session re-established (attempt %d)",
                         attempt)
                _flightrec.note_event("store.reconnected", attempt=attempt)
                self._connected.set()
                if self.on_session_replayed is not None:
                    try:
                        self.on_session_replayed()
                    except Exception:
                        log.exception("on_session_replayed callback")
                return
            stage.store_reconnects.inc("fail")
            log.error("store reconnect window exhausted (%d attempts); "
                      "session is dead", cfg.attempts)
        finally:
            # whatever path exits this task — exhaustion, cancellation, a
            # bug — it must NEVER leave waiters parked between states:
            # either the session is up or the client is terminally closed
            if not self._connected.is_set() and not self.closed.is_set():
                self._closing = True
                self.closed.set()   # wakes wait_connected()/q_pull loops

    async def _replay_session(self) -> None:
        """Re-establish session state on a fresh transport: leases first
        (identity), then their keys, then watches (+ missed-event diff),
        then pub/sub. Runs before ``_connected`` is set."""
        from ..utils.prometheus import stage_metrics

        stage = stage_metrics()
        # 1. leases: re-grant under the ORIGINAL id so worker identity
        # (worker_id == lease, endpoint key suffixes) survives
        for lid, ttl in list(self._session_leases.items()):
            r = await self._call("lease_grant", ttl=ttl, reuse=lid,
                                 _replay=True)
            if r["lease"] != lid:
                # server couldn't preserve the id (e.g. native store without
                # reuse support): this lease's identity is gone for good
                try:
                    await self._call("lease_revoke", lease=r["lease"],
                                     _replay=True)
                except StoreError:
                    pass
                self._session_leases.pop(lid, None)
                for key in [k for k, (_, lse) in self._lease_puts.items()
                            if lse == lid]:
                    self._lease_puts.pop(key, None)
                self._fire_lease_lost(
                    lid, "lease id could not be re-granted on reconnect")
                continue
            stage.lease_regrants.inc()
        # 2. lease-bound keys (registrations/metrics): the store may have
        # restarted empty, or expired them during the outage — re-put
        for key, (value, lease) in list(self._lease_puts.items()):
            if lease in self._session_leases:
                await self._call("put", key=key, value=value, lease=lease,
                                 _replay=True)
                stage.session_replays.inc("put")
        # 3. watches: re-arm under the same watch_id, then diff the fresh
        # snapshot against the last-known state so deletes (and puts) that
        # happened during the outage are synthesized for the callback
        for wid, ws in list(self._watch_state.items()):
            ws.touched = set()
            r = await self._call("watch", watch_id=wid, prefix=ws.prefix,
                                 _replay=True)
            snapshot = {k: v for k, v in r["items"]}
            for key in ws.known:
                if key not in snapshot:
                    self._push_q.put_nowait(
                        {"push": "watch", "watch_id": wid, "key": key,
                         "value": None, "deleted": True, "synthetic": True})
            for key, value in snapshot.items():
                if ws.known.get(key) != value:
                    self._push_q.put_nowait(
                        {"push": "watch", "watch_id": wid, "key": key,
                         "value": value, "deleted": False,
                         "synthetic": True})
            self._push_q.put_nowait({"push": "_watch_replay_done",
                                     "watch_id": wid})
            stage.session_replays.inc("watch")
        # 4. pub/sub subjects
        for sid, subject in list(self._sub_subjects.items()):
            await self._call("subscribe", sub_id=sid, subject=subject,
                             _replay=True)
            stage.session_replays.inc("subscribe")
        # q_pull loops resume themselves via wait_connected()

    # ------------------------------------------------------------------
    async def _push_loop(self) -> None:
        try:
            while True:
                await self._handle_push(await self._push_q.get())
        except asyncio.CancelledError:
            pass

    async def _handle_push(self, msg: Dict[str, Any]) -> None:
        kind = msg["push"]
        try:
            if kind == "watch":
                wid = msg["watch_id"]
                key, value = msg["key"], msg.get("value")
                deleted = msg["deleted"]
                ws = self._watch_state.get(wid)
                if ws is not None:
                    if msg.get("synthetic"):
                        # skip synthetics superseded by a real event that
                        # arrived since the re-arm (ordering race), and
                        # no-op diffs
                        if ws.touched is not None and key in ws.touched:
                            return
                        if deleted and key not in ws.known:
                            return
                        if not deleted and ws.known.get(key) == value:
                            return
                    elif ws.touched is not None:
                        ws.touched.add(key)
                    if deleted:
                        ws.known.pop(key, None)
                    else:
                        ws.known[key] = value
                cb = self._watch_cbs.get(wid)
                if cb:
                    await cb(key, value, deleted)
            elif kind == "_watch_replay_done":
                ws = self._watch_state.get(msg["watch_id"])
                if ws is not None:
                    ws.touched = None
            elif kind == "msg":
                cb = self._sub_cbs.get(msg["sub_id"])
                if cb:
                    await cb(msg["subject"], msg["payload"])
        except Exception:
            log.exception("push handler failed")

    async def _call(self, op: str, _replay: bool = False, **kw
                    ) -> Dict[str, Any]:
        try:
            await faults.fire("store.call")
        except (ConnectionError, RuntimeError) as e:
            # injected faults surface EXACTLY like real transport loss at
            # this layer — callers are contracted to see StoreError only
            raise StoreError(f"connection lost: {e}",
                             code="conn_lost") from e
        if self._writer is None or self._writer.is_closing() or (
                not self._connected.is_set() and not _replay):
            # fail fast — callers that prefer to block ride
            # wait_connected(); hanging forever is never an option
            raise StoreError("connection lost (store disconnected)",
                             code="conn_lost")
        rid = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            async with self._send_lock:
                # unbounded-ok: drain stalls only on TCP backpressure from
                # the store; bounded by the connection's own lifetime
                # dynalint: ok(await-holding-lock) the send lock EXISTS to
                # serialize request frames on the one store socket; a stall
                # is TCP backpressure from the store, and connection loss
                # rejects every waiter via _fail_pending
                await write_frame(self._writer, {"op": op, "id": rid, **kw})
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            self._pending.pop(rid, None)
            raise StoreError(f"connection lost: {e}",
                             code="conn_lost") from e
        reply = await fut
        if not reply.get("ok", False):
            raise StoreError(reply.get("error", "store error"),
                             code=reply.get("code", ""))
        return reply

    # -- KV -------------------------------------------------------------
    async def put(self, key: str, value: bytes,
                  lease: Optional[int] = None) -> None:
        await self._call("put", key=key, value=value, lease=lease)
        if lease is not None and lease in self._session_leases:
            # lease-bound state is liveness state: remember it for replay
            self._lease_puts[key] = (value, lease)

    async def create(self, key: str, value: bytes,
                     lease: Optional[int] = None,
                     or_validate: bool = False) -> bool:
        r = await self._call("create", key=key, value=value, lease=lease,
                             or_validate=or_validate)
        return r.get("created", True)

    async def get(self, key: str) -> Optional[bytes]:
        r = await self._call("get", key=key)
        return r["value"] if r["found"] else None

    async def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        r = await self._call("get_prefix", prefix=prefix)
        return [(k, v) for k, v in r["items"]]

    async def delete(self, key: str) -> bool:
        r = await self._call("delete", key=key)
        self._lease_puts.pop(key, None)
        return r["deleted"]

    # -- leases ----------------------------------------------------------
    async def lease_grant(self, ttl: float = 5.0,
                          auto_keepalive: bool = True,
                          reuse: Optional[int] = None,
                          bind: bool = True) -> int:
        """Grant a lease; ``reuse`` asks the server for a SPECIFIC id —
        how a sharded store mirrors one session lease onto every shard
        (and how session replay preserves identity). A server that
        cannot honor it returns its own id; the caller must check.
        ``bind=False`` grants an orphan lease that survives this
        connection's death and expires only by TTL — for keys that must
        outlive their producer (incident bundles, trace spans)."""
        kw = {"ttl": ttl}
        if reuse is not None:
            kw["reuse"] = int(reuse)
        if not bind:
            kw["bind"] = False
        r = await self._call("lease_grant", **kw)
        lease = r["lease"]
        if auto_keepalive:
            # kept-alive leases are SESSION leases: re-granted (same id)
            # and re-keyed by the replay after a reconnect
            self._session_leases[lease] = ttl
            self._keepalive_tasks.append(asyncio.create_task(
                self._keepalive_loop(lease, ttl), name=f"lease-{lease}"))
        return lease

    def _fire_lease_lost(self, lease: int, why: str) -> None:
        # liveness is gone: registrations expire(d) server-side, so a
        # worker that kept serving would be an unroutable zombie. Mirror
        # the reference (etcd.rs:55-76 — lease loss cancels the worker's
        # token): notify so the shell can shut down for a clean restart.
        log.warning("lease %x lost (%s); keepalive stopping", lease, why)
        _flightrec.note_event("store.lease_lost", lease=f"{lease:x}",
                              why=why)
        self._session_leases.pop(lease, None)
        if self.on_lease_lost is not None:
            try:
                self.on_lease_lost(lease)
            except Exception:
                log.exception("on_lease_lost callback")

    async def _await_session(self, lease: int) -> bool:
        """Keepalive helper: block for the reconnect+replay to finish.
        True => the lease survived (continue keepalives); False => it is
        lost (and lease_lost has fired)."""
        try:
            await self.wait_connected()
        except StoreError:
            if lease in self._session_leases:
                self._fire_lease_lost(
                    lease, "store unreachable (reconnect exhausted)")
            return False
        # replay fired lease_lost itself if the id couldn't be preserved
        return lease in self._session_leases

    async def _keepalive_loop(self, lease: int, ttl: float) -> None:
        try:
            while True:
                await asyncio.sleep(ttl / 3)
                try:
                    # bounded reply wait: a STALLED-but-open connection
                    # (SIGSTOP'd store, blackholed traffic — no EOF, no
                    # RST) must read as a loss before the lease silently
                    # expires server-side. Dropping the transport routes
                    # recovery through the normal reconnect path.
                    try:
                        await asyncio.wait_for(
                            self._call("lease_keepalive", lease=lease),
                            ttl)
                    except asyncio.TimeoutError:
                        log.warning("lease %x keepalive stalled >%.1fs; "
                                    "dropping store connection", lease, ttl)
                        if self._writer is not None:
                            self._writer.close()   # rx loop => _conn_lost
                        raise StoreError("keepalive stalled",
                                         code="conn_lost") from None
                except StoreError as e:
                    recoverable = (self.reconnect.enabled
                                   and not self._closing)
                    if e.code == "lease_not_found":
                        if lease not in self._session_leases:
                            # deliberately revoked between beats (drain /
                            # swap identity handoff) — not a loss
                            return
                        if recoverable and not self._connected.is_set():
                            # replay in flight: the re-grant hasn't landed
                            if not await self._await_session(lease):
                                return
                            continue
                        # expired server-side (e.g. after loop starvation)
                        self._fire_lease_lost(lease, str(e))
                        return
                    if e.code == "conn_lost":
                        if recoverable:
                            # reconnect+replay preserves the lease id; only
                            # an exhausted window is a true loss
                            if not await self._await_session(lease):
                                return
                            continue
                        self._fire_lease_lost(lease, str(e))
                        return
                    # other server hiccup (version skew, transient): the
                    # lease may still be alive — keep trying rather than
                    # orphaning a healthy lease
                    log.debug("lease %x keepalive error (retrying): %s",
                              lease, e)
                except (ConnectionError, OSError,
                        asyncio.IncompleteReadError) as e:
                    # transport died mid-call — same terminal state
                    self._fire_lease_lost(lease, f"{type(e).__name__}: {e}")
                    return
        except asyncio.CancelledError:
            pass

    async def lease_revoke(self, lease: int) -> None:
        self._session_leases.pop(lease, None)
        # a deliberate revoke must also stop the lease's keepalive loop:
        # an orphaned beat would see lease_not_found on a healthy
        # connection and fire on_lease_lost — fatal to a process that
        # revoked one identity to adopt another (model-mobility swap)
        for t in self._keepalive_tasks:
            if t.get_name() == f"lease-{lease}":
                t.cancel()
        self._keepalive_tasks = [t for t in self._keepalive_tasks
                                 if not t.done()
                                 and t.get_name() != f"lease-{lease}"]
        for key in [k for k, (_, lse) in self._lease_puts.items()
                    if lse == lease]:
            self._lease_puts.pop(key, None)
        await self._call("lease_revoke", lease=lease)

    # -- watches ---------------------------------------------------------
    async def watch_prefix(self, prefix: str, callback: WatchCallback
                           ) -> List[Tuple[str, bytes]]:
        """Start watching; returns the current snapshot; callback fires on
        every subsequent put/delete under the prefix. The watch survives
        reconnects: it re-arms and synthesizes events missed meanwhile."""
        wid = next(self._ids)
        self._watch_cbs[wid] = callback
        ws = _WatchState(prefix)
        ws.touched = set()      # events racing registration beat the merge
        self._watch_state[wid] = ws
        r = await self._call("watch", watch_id=wid, prefix=prefix)
        for k, v in r["items"]:
            if k not in ws.touched:
                ws.known[k] = v
        ws.touched = None
        return [(k, v) for k, v in r["items"]]

    # -- pub/sub ---------------------------------------------------------
    async def subscribe(self, subject: str, callback: MsgCallback) -> int:
        sid = next(self._ids)
        self._sub_cbs[sid] = callback
        self._sub_subjects[sid] = subject
        await self._call("subscribe", sub_id=sid, subject=subject)
        return sid

    async def publish(self, subject: str, payload: bytes) -> int:
        r = await self._call("publish", subject=subject, payload=payload)
        return r["delivered"]

    # -- queues -----------------------------------------------------------
    async def q_push(self, queue: str, payload: bytes) -> int:
        r = await self._call("q_push", queue=queue, payload=payload)
        return r["msg_id"]

    async def q_pull(self, queue: str) -> Tuple[int, bytes]:
        """Blocks until a message is available; must q_ack when done. The
        pull survives reconnects: a parked pull rejected by connection loss
        re-issues itself once the session is re-established (the old
        server-side waiter requeued any unacked message — at-least-once)."""
        while True:
            try:
                r = await self._call("q_pull", queue=queue)
                return r["msg_id"], r["payload"]
            except StoreError as e:
                if (e.code != "conn_lost" or not self.reconnect.enabled
                        or self._closing):
                    raise
                await self.wait_connected()

    async def q_ack(self, queue: str, msg_id: int) -> None:
        await self._call("q_ack", queue=queue, msg_id=msg_id)

    async def q_len(self, queue: str) -> int:
        return (await self._call("q_len", queue=queue))["len"]

    async def ping(self) -> bool:
        return (await self._call("ping")).get("pong", False)
