"""ctypes binding for the native (C++) data-plane server.

``DYNAMO_TPU_DATAPLANE=native`` makes :class:`DistributedRuntime` serve its
endpoints through ``native/build/libdynamo_dataplane.so``: connection
accept, frame parsing, write buffering and stop/kill demultiplexing run on
a native epoll thread, and only request EXECUTION crosses into Python —
the C side calls back with (stream id, endpoint, payload), the handler's
response items are packed here and queued back through ``dp_send``.

The Python asyncio server (component.py ``_serve_conn``) keeps identical
wire semantics and remains the test fixture; this module re-implements the
request-runner contract (prologue, error-before-stream, data/sentinel
frames, duplicate-context guard, streaming request parts) against the C
ABI. Reference capability: lib/runtime/src/pipeline/network ingress +
tcp/server.rs — the reference's native response plane.
"""

from __future__ import annotations

import asyncio
import ctypes
import json
import logging
import os
from typing import Any, Dict, Optional

from .wire import CODE_KEY, KIND_KEY, MESSAGE_KEY, pack
from .engine import Context, EngineError
from ..utils.aiotasks import spawn

log = logging.getLogger("dynamo_tpu.native_dataplane")

_REQUEST_CB = ctypes.CFUNCTYPE(None, ctypes.c_int64, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint8),
                               ctypes.c_uint64, ctypes.c_int,
                               ctypes.c_int64)
_PART_CB = ctypes.CFUNCTYPE(None, ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_uint8),
                            ctypes.c_uint64, ctypes.c_int)
_CONTROL_CB = ctypes.CFUNCTYPE(None, ctypes.c_int64, ctypes.c_int)

_STOP, _KILL, _GONE = 0, 1, 2


def _load_lib() -> ctypes.CDLL:
    from .store_server import build_native

    build_dir = build_native("build/libdynamo_dataplane.so")
    lib = ctypes.CDLL(os.path.join(build_dir, "libdynamo_dataplane.so"))
    lib.dp_start.restype = ctypes.c_void_p
    lib.dp_start.argtypes = [ctypes.c_char_p, ctypes.c_int, _REQUEST_CB,
                             _PART_CB, _CONTROL_CB]
    lib.dp_port.restype = ctypes.c_int
    lib.dp_port.argtypes = [ctypes.c_void_p]
    lib.dp_send.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    lib.dp_end.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dp_backlog.restype = ctypes.c_int64
    lib.dp_backlog.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dp_stop.argtypes = [ctypes.c_void_p]
    return lib


class NativeDataPlane:
    """One per process (like the asyncio data-plane server)."""

    HIGH_WATER = 8 * 1024 * 1024   # pause the producer above this backlog

    def __init__(self, drt):
        self.drt = drt          # handlers + active-context registry live here
        self.lib = _load_lib()
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.handle: Optional[int] = None
        self.port: int = 0
        self._contexts: Dict[int, Context] = {}
        self._part_queues: Dict[int, asyncio.Queue] = {}
        self._run_tasks: set = set()    # in-flight handler tasks (spawn)
        # keep callback objects alive for the lifetime of the server
        self._cb_request = _REQUEST_CB(self._on_request)
        self._cb_part = _PART_CB(self._on_part)
        self._cb_control = _CONTROL_CB(self._on_control)

    def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self.loop = asyncio.get_running_loop()
        self.handle = self.lib.dp_start(host.encode(), port,
                                        self._cb_request, self._cb_part,
                                        self._cb_control)
        if not self.handle:
            raise RuntimeError("native data plane failed to start")
        self.port = self.lib.dp_port(self.handle)
        return self.port

    def stop(self) -> None:
        if self.handle:
            self.lib.dp_stop(self.handle)
            self.handle = None

    # ------------------------------------------------------------------
    # C-thread callbacks: copy data out, hop onto the asyncio loop
    # ------------------------------------------------------------------
    def _on_request(self, sid, endpoint, ctx_id, ctype, payload, length,
                    streaming, resume):
        data = ctypes.string_at(payload, length) if length else b""
        self.loop.call_soon_threadsafe(
            self._begin, sid, (endpoint or b"").decode(),
            (ctx_id or b"").decode() or None, (ctype or b"").decode(),
            data, bool(streaming), int(resume))

    def _on_part(self, sid, data, length, is_end):
        chunk = ctypes.string_at(data, length) if length else b""
        self.loop.call_soon_threadsafe(self._deliver_part, sid,
                                       chunk, bool(is_end))

    def _on_control(self, sid, kind):
        self.loop.call_soon_threadsafe(self._control, sid, kind)

    # ------------------------------------------------------------------
    def _send(self, sid: int, control: Dict[str, Any],
              payload: Optional[bytes]) -> None:
        if not self.handle:
            return   # server stopped with streams in flight: drop
        frame = pack([control, payload])
        buf = (ctypes.c_uint8 * len(frame)).from_buffer_copy(frame)
        self.lib.dp_send(self.handle, sid, buf, len(frame))

    def _end(self, sid: int) -> None:
        if self.handle:
            self.lib.dp_end(self.handle, sid)

    def _backlog(self, sid: int) -> int:
        if not self.handle:
            return 0
        return max(0, self.lib.dp_backlog(self.handle, sid))

    def _deliver_part(self, sid: int, chunk: bytes, is_end: bool) -> None:
        q = self._part_queues.get(sid)
        if q is not None:
            q.put_nowait(None if is_end else chunk)

    def _control(self, sid: int, kind: int) -> None:
        ctx = self._contexts.get(sid)
        if ctx is not None:
            if kind == _KILL:
                ctx.kill()
            else:       # stop, or client gone mid-stream
                ctx.stop_generating()
        if kind in (_KILL, _GONE):
            # a handler blocked on request parts must unblock: the client
            # can never send the 'end' frame now
            self._deliver_part(sid, b"", True)

    # ------------------------------------------------------------------
    def _begin(self, sid: int, endpoint: str, ctx_id: Optional[str],
               ctype: str, payload: bytes, streaming: bool,
               resume: int = 0) -> None:
        if streaming:
            # register the part queue NOW: part/end callbacks already queued
            # behind this one on the loop must find it (the _run coroutine
            # itself only starts a loop tick later)
            self._part_queues[sid] = asyncio.Queue()
        # the Context too: a stop/kill/disconnect control queued right
        # behind this callback must find it, or the control is lost and the
        # handler runs to completion against a dead client
        ctx = Context(ctx_id)
        ctx.resume_no = resume
        self._contexts[sid] = ctx
        # retained handle: _run catches transport errors itself, but a bug
        # BEFORE its try (or a cancelled loop) must still surface instead
        # of vanishing with the dropped task
        spawn(self._run(sid, endpoint, ctx, ctype, payload, streaming),
              name=f"native-dp-run-{sid}", store=self._run_tasks)

    async def _run(self, sid: int, endpoint: str, ctx: Context,
                   ctype: str, payload: bytes, streaming: bool) -> None:
        drt = self.drt

        def reject(code, message):
            self._part_queues.pop(sid, None)
            self._contexts.pop(sid, None)
            self._send(sid, {KIND_KEY: "error", CODE_KEY: code,
                             MESSAGE_KEY: message}, None)
            self._end(sid)

        handler = drt._handlers.get(endpoint)
        if handler is None:
            reject(404, f"no endpoint {endpoint!r}")
            return
        # the _begin-created Context uses ctx.id == wire ctx_id (or a fresh
        # one); a duplicate in-flight id is a stale-retry double delivery —
        # unless it carries a higher resume ordinal (llm/resume.py): then
        # the active context is a zombie whose stream broke client-side,
        # and the resume attempt supersedes it (same semantics as the
        # asyncio server's guard in component.py)
        stale = drt._active.get(ctx.id)
        if stale is not None:
            if ctx.resume_no > stale.resume_no:
                log.warning("context %s superseded by resume attempt %d "
                            "(stale attempt %d killed)", ctx.id,
                            ctx.resume_no, stale.resume_no)
                stale.kill()
                del drt._active[ctx.id]
            else:
                reject(409, f"context {ctx.id} is already executing "
                            f"(duplicate delivery)")
                return
        request: Any
        try:
            if ctype == "bin":
                request = payload
            else:
                request = json.loads(payload.decode()) if payload else None
        except (ValueError, UnicodeDecodeError) as e:
            reject(400, f"malformed request payload: {e}")
            return
        drt._active[ctx.id] = ctx
        from ..utils.logging_ext import request_id_var
        from ..utils.tracing import (SpanContext, current_span_var,
                                     get_tracer)
        rid_token = request_id_var.set(ctx.id)
        # the C parser drops the control's trace field, so the server span
        # stitches by trace_id == context id (parent linkage is lost on
        # this plane; see docs/observability.md)
        tracer = get_tracer()
        srv_span = tracer.start_span(f"rpc:{endpoint}",
                                     parent=SpanContext(ctx.id, None),
                                     context_id=ctx.id)
        span_token = current_span_var.set(srv_span.context()) \
            if srv_span is not None else None

        if streaming:
            from .component import StreamingRequest

            q = self._part_queues[sid]

            async def parts_gen():
                while True:
                    chunk = await q.get()
                    if chunk is None:
                        return
                    yield chunk

            request = StreamingRequest(meta=request, parts=parts_gen())

        srv_status = "error"
        try:
            from .component import drive_handler_stream

            async def send(control, payload):
                self._send(sid, control, payload)
                # backpressure: the asyncio path awaited writer.drain();
                # here the native write buffer is polled so a slow client
                # cannot grow it without bound. A killed/stopped context must
                # break out — a stalled-but-connected client would otherwise
                # pin this handler (and its engine slot) forever.
                while self._backlog(sid) > self.HIGH_WATER:
                    if ctx.is_killed or ctx.is_stopped:
                        raise ConnectionResetError(
                            "stream cancelled while backpressured")
                    await asyncio.sleep(0.005)

            if await drive_handler_stream(handler(request, ctx), send):
                srv_status = "ok"
        except Exception as e:  # noqa: BLE001 - transport-level failure
            try:
                self._send(sid, {KIND_KEY: "error", MESSAGE_KEY: str(e),
                                 CODE_KEY: 500}, None)
            except Exception:
                # stream already torn down native-side: the error frame
                # has no one to reach
                log.debug("error frame undeliverable (stream %d gone)",
                          sid, exc_info=True)
        finally:
            drt._active.pop(ctx.id, None)
            self._contexts.pop(sid, None)
            self._part_queues.pop(sid, None)
            if span_token is not None:
                current_span_var.reset(span_token)
            tracer.finish(srv_span, status=srv_status)
            request_id_var.reset(rid_token)
            self._end(sid)
