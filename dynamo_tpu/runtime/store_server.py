"""dynstore — the coordination plane in one service.

Provides, over one TCP protocol (wire.py frames):

- **KV with leases + prefix watches** (the etcd role): put/get/get_prefix/
  create/delete; leases with TTL + keepalive; keys bound to a lease vanish
  when it expires; watchers get pushed put/delete events.
- **Pub/sub** (the NATS core role): subject-based fanout.
- **Work queues** (the JetStream role): push/pull-with-ack; unacked messages
  return to the queue when their consumer's connection dies.

Single asyncio process, all state in memory owned by one task group — the
discovery/config/event/queue planes of SURVEY §1/L0 collapsed into one
deployable binary.

Two implementations share this wire protocol:
- this Python server (the reference implementation and test fixture), and
- the production C++ server (native/dynstore.cpp, epoll event loop), spawned
  by :class:`NativeStoreServer`.
Set ``DYNAMO_TPU_STORE=native`` to make ``StoreServer`` resolve to the
native implementation everywhere (tests included).

Ops (client -> server): {op, id, ...} -> reply {id, ok, ...}; pushed
server -> client frames carry {push: "watch"|"msg"|"queue", ...}.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from ..utils.prometheus import LATENCY_BUCKETS_FAST, Registry
from .keyspace import classify_key
from .wire import FrameReader, write_frame

log = logging.getLogger("dynamo_tpu.store")

DEFAULT_TTL = 5.0

# sentinel: an op handler parked the request; the reply is pushed later
DEFER = object()

#: where the server publishes its own telemetry dump (into its own KV —
#: the one store key no client writes; family ``metrics-store`` in
#: runtime/keyspace.py, fetched by metrics_aggregator.fetch_stage_states)
SELF_STAGE_KEY = "metrics_stage/_store/store/0"


class StoreStats:
    """The store's self-observability registry: per-op latency labeled by
    keyspace *family* (via :func:`~.keyspace.classify_key`, so the series
    vocabulary is drift-gated with the keyspace registry for free), plus
    watch/lease/connection gauges, per-family resident keys/bytes, queue
    depths, and watch fan-out volume. Published on the ordinary
    stage-metrics merge path every ``DYN_STORE_METRICS_INTERVAL`` seconds
    so ``/metrics``, the aggregator and ``dyntop`` see the store like any
    other component."""

    def __init__(self) -> None:
        r = Registry()
        self.registry = r
        self.op_seconds = r.histogram(
            "dyn_store_op_seconds",
            "Store op handler latency by op and keyspace family "
            "(q_pull measures the immediate-dequeue path; parked pulls "
            "are not ops, they are waits)", ("op", "family"),
            buckets=LATENCY_BUCKETS_FAST)
        self.watches = r.gauge(
            "dyn_store_watches", "Registered prefix watches", ())
        self.leases = r.gauge(
            "dyn_store_leases", "Live leases", ())
        self.conns = r.gauge(
            "dyn_store_conns", "Open client connections", ())
        self.keys = r.gauge(
            "dyn_store_keys", "Resident keys by keyspace family",
            ("family",))
        self.bytes = r.gauge(
            "dyn_store_bytes", "Resident value bytes by keyspace family",
            ("family",))
        self.queue_depth = r.gauge(
            "dyn_store_queue_depth",
            "Undelivered work-queue messages by queue family", ("family",))
        self.watch_fanout = r.counter(
            "dyn_store_watch_fanout_total",
            "Watch events pushed to watchers (one put/delete fans out to "
            "every matching watch)", ())
        self.fanout_drops = r.counter(
            "dyn_store_fanout_drops_total",
            "Connections dropped because their push outbox overflowed "
            "(defunct consumer — the fan-out they missed died with them)",
            ())


@dataclass
class _KeyVal:
    value: bytes
    lease: Optional[int] = None
    family: str = "other"


@dataclass
class _Lease:
    id: int
    ttl: float
    expires: float
    keys: Set[str] = field(default_factory=set)
    # owning connection (process liveness binding): when it dies the lease
    # expires immediately — unless a reconnecting client re-adopts the
    # lease id first (session re-establishment)
    owner: Optional["_Conn"] = None


@dataclass
class _QueueMsg:
    id: int
    payload: bytes


class _Conn:
    _ids = itertools.count(1)

    def __init__(self, writer: asyncio.StreamWriter,
                 stats: Optional[StoreStats] = None):
        self.id = next(_Conn._ids)
        self.writer = writer
        self.stats = stats
        self.watches: Dict[int, str] = {}          # watch_id -> prefix
        self.subs: Dict[int, str] = {}             # sub_id -> subject
        self.leases: Set[int] = set()
        self.pulling: Dict[str, List[int]] = {}    # queue -> pending pull ids
        self.unacked: Dict[Tuple[str, int], _QueueMsg] = {}
        self._send_lock = asyncio.Lock()
        # detached push: an ordered per-connection outbox drained by one
        # pump task, so a watcher/subscriber that stops reading its socket
        # blocks only its own pump — never the put/publish that notified it
        self._outbox: "asyncio.Queue[Any]" = asyncio.Queue()
        self._pump_task: Optional[asyncio.Task] = None

    OUTBOX_LIMIT = 4096   # frames; beyond this the consumer is defunct

    async def push(self, obj: Any) -> None:
        async with self._send_lock:
            # dynalint: ok(await-holding-lock) per-connection frame
            # serialization is the lock's purpose; a consumer that stops
            # reading hits the OUTBOX_LIMIT path and is dropped
            await write_frame(self.writer, obj)

    def push_nowait(self, obj: Any) -> None:
        """Enqueue a push frame, preserving per-connection order, without
        awaiting the (possibly stalled) socket."""
        if self._outbox.qsize() >= self.OUTBOX_LIMIT:
            if self.stats is not None:
                self.stats.fanout_drops.inc()
            self.writer.close()   # defunct consumer: drop the connection
            return
        self._outbox.put_nowait(obj)
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())

    async def _pump(self) -> None:
        try:
            while not self._outbox.empty():
                obj = self._outbox.get_nowait()
                async with self._send_lock:
                    # dynalint: ok(await-holding-lock) the pump contends
                    # only with reply writes on THIS connection; a stalled
                    # socket blocks its own pump, and the defunct-consumer
                    # limit closes the connection
                    await write_frame(self.writer, obj)
        # dynalint: ok(swallowed-exception) broken pipe: the reader loop
        # reaps the connection, and logging per lost frame would spam on
        # every ordinary client drop
        except Exception:
            pass


class StoreServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._kv: Dict[str, _KeyVal] = {}
        self._leases: Dict[int, _Lease] = {}
        # fresh lease ids start at boot wall-clock millis: a RESTARTED
        # store must never hand out an id a pre-restart client still holds
        # in its session — that client's reuse-grant would otherwise adopt
        # the fresh grantee's lease and give it two owners. Monotonic
        # across restarts as long as boots are >1ms apart and a single
        # boot grants fewer leases than milliseconds it was down.
        self._lease_ids = itertools.count(int(time.time() * 1000))
        self._watchers: Dict[int, Tuple[_Conn, int, str]] = {}  # gid -> (conn, wid, prefix)
        self._watch_gids = itertools.count(1)
        self._subs: Dict[str, Dict[int, Tuple[_Conn, int]]] = {}  # subject -> gid -> (conn, sid)
        self._sub_gids = itertools.count(1)
        self._queues: Dict[str, Deque[_QueueMsg]] = {}
        self._queue_waiters: Dict[str, Deque[Tuple[_Conn, int]]] = {}
        self._queue_msg_ids = itertools.count(1)
        self._server: Optional[asyncio.base_events.Server] = None
        self._reaper: Optional[asyncio.Task] = None
        self._conns: set = set()
        # self-observability: per-op latency/family accounting + the
        # periodic dump into our own KV (0 = keep recording, never publish)
        self.stats = StoreStats()
        raw_interval = os.environ.get("DYN_STORE_METRICS_INTERVAL", "")
        try:
            self._stats_interval = float(raw_interval) if raw_interval \
                else 2.0
        except ValueError:
            log.warning("ignoring malformed DYN_STORE_METRICS_INTERVAL=%r",
                        raw_interval)
            self._stats_interval = 2.0
        self._stats_task: Optional[asyncio.Task] = None
        self._fam_keys: Dict[str, int] = {}
        self._fam_bytes: Dict[str, int] = {}
        self._fam_cache: Dict[str, str] = {}   # key -> family (bounded)

    # ------------------------------------------------------------------
    async def start(self) -> int:
        self._server = await asyncio.start_server(self._serve, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._reaper = asyncio.create_task(self._reap_leases())
        if self._stats_interval > 0:
            self._stats_task = asyncio.create_task(self._publish_stats())
        return self.port

    async def stop(self) -> None:
        if self._stats_task:
            self._stats_task.cancel()
        if self._reaper:
            self._reaper.cancel()
        if self._server:
            self._server.close()
            # force-close live connections: 3.12's wait_closed waits for
            # every handler, and a client that never disconnects (or a test
            # that leaked one) would park shutdown forever
            for conn in list(self._conns):
                try:
                    conn.writer.close()
                # dynalint: ok(swallowed-exception) force-closing leaked
                # client sockets at shutdown; nothing can act on a close()
                # failure and wait_closed() below is the real gate
                except Exception:
                    pass
            await self._server.wait_closed()

    async def _reap_leases(self) -> None:
        while True:
            await asyncio.sleep(0.2)
            now = time.monotonic()
            for lid, lease in list(self._leases.items()):
                if lease.expires < now:
                    await self._expire_lease(lid)

    async def _expire_lease(self, lid: int) -> None:
        lease = self._leases.pop(lid, None)
        if lease is None:
            return
        for key in list(lease.keys):
            if key in self._kv and self._kv[key].lease == lid:
                self._kv_del(key)
                await self._notify_watchers(key, None)

    # -- per-family residency accounting --------------------------------
    def _family(self, key: str) -> str:
        fam = self._fam_cache.get(key)
        if fam is None:
            if len(self._fam_cache) >= 65536:
                self._fam_cache.clear()
            fam = self._fam_cache[key] = classify_key(key)
        return fam

    def _kv_set(self, key: str, value: bytes,
                lease: Optional[int]) -> None:
        old = self._kv.get(key)
        fam = old.family if old is not None else self._family(key)
        if old is None:
            self._fam_keys[fam] = self._fam_keys.get(fam, 0) + 1
        else:
            self._fam_bytes[fam] = self._fam_bytes.get(fam, 0) \
                - len(old.value)
        self._fam_bytes[fam] = self._fam_bytes.get(fam, 0) + len(value)
        self._kv[key] = _KeyVal(value, lease, fam)

    def _kv_del(self, key: str) -> Optional[_KeyVal]:
        kv = self._kv.pop(key, None)
        if kv is not None:
            self._fam_keys[kv.family] = self._fam_keys.get(kv.family, 1) - 1
            self._fam_bytes[kv.family] = self._fam_bytes.get(
                kv.family, len(kv.value)) - len(kv.value)
        return kv

    # ------------------------------------------------------------------
    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer, self.stats)
        self._conns.add(conn)
        fr = FrameReader(reader)
        try:
            while True:
                # unbounded-ok: server op loop; lives as long as the client
                msg = await fr.read()
                try:
                    reply = await self._dispatch(conn, msg)
                except Exception as e:  # noqa: BLE001 - op failure => error reply
                    reply = {"id": msg.get("id"), "ok": False, "error": str(e)}
                if reply is not None:
                    await conn.push(reply)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._conns.discard(conn)
            await self._cleanup(conn)
            writer.close()

    async def _cleanup(self, conn: _Conn) -> None:
        for gid in [g for g, (c, _, _) in self._watchers.items() if c is conn]:
            del self._watchers[gid]
        for subject in list(self._subs):
            self._subs[subject] = {g: v for g, v in self._subs[subject].items()
                                   if v[0] is not conn}
        # a dead consumer's unacked queue messages go back to the queue head
        for (qname, _mid), m in list(conn.unacked.items()):
            self._queues.setdefault(qname, collections.deque()).appendleft(m)
            await self._kick_queue(qname)
        conn.unacked.clear()
        for qname, pulls in conn.pulling.items():
            w = self._queue_waiters.get(qname)
            if w:
                self._queue_waiters[qname] = collections.deque(
                    (c, rid) for c, rid in w if c is not conn)
        # leases owned by this connection expire immediately (process death)
        # — unless a reconnecting client already re-adopted the lease id
        # (half-open TCP: the new connection can land before the old one's
        # EOF is observed; adoption transferred ownership away from us)
        for lid in list(conn.leases):
            lease = self._leases.get(lid)
            if lease is not None and lease.owner is conn:
                await self._expire_lease(lid)

    # ------------------------------------------------------------------
    async def _dispatch(self, conn: _Conn, m: Dict[str, Any]) -> Optional[Dict]:
        op = m["op"]
        rid = m.get("id")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            return {"id": rid, "ok": False, "error": f"unknown op {op!r}"}
        key = m.get("key") or m.get("prefix") or m.get("queue")
        t0 = time.perf_counter()
        out = await fn(conn, m)
        if out is DEFER:
            # a parked pull is a wait, not an op — recording its setup
            # time would drown the real dequeue-path latency
            return None
        self.stats.op_seconds.observe(
            op, self._family(key) if key else "none",
            value=time.perf_counter() - t0)
        if out is None:
            out = {}
        out.setdefault("id", rid)
        out.setdefault("ok", True)
        return out

    # -- KV -------------------------------------------------------------
    async def _op_put(self, conn, m):
        key, value = m["key"], m["value"]
        lease = m.get("lease")
        if lease is not None and lease not in self._leases:
            return {"ok": False, "error": "lease not found",
                    "code": "lease_not_found"}
        self._kv_set(key, value, lease)
        if lease is not None:
            self._leases[lease].keys.add(key)
        await self._notify_watchers(key, value)
        return {}

    async def _op_create(self, conn, m):
        """Create-if-absent (atomic); optionally validate existing value."""
        key = m["key"]
        existing = self._kv.get(key)
        if existing is not None:
            if m.get("or_validate") and existing.value == m["value"]:
                return {"created": False}
            return {"ok": False, "error": "key exists"}
        return await self._op_put(conn, m) or {"created": True}

    async def _op_get(self, conn, m):
        kv = self._kv.get(m["key"])
        return {"value": kv.value if kv else None, "found": kv is not None}

    async def _op_get_prefix(self, conn, m):
        pfx = m["prefix"]
        return {"items": [[k, v.value] for k, v in sorted(self._kv.items())
                          if k.startswith(pfx)]}

    async def _op_delete(self, conn, m):
        key = m["key"]
        kv = self._kv_del(key)
        if kv is not None:
            if kv.lease in self._leases:
                self._leases[kv.lease].keys.discard(key)
            await self._notify_watchers(key, None)
        return {"deleted": kv is not None}

    async def _notify_watchers(self, key: str, value: Optional[bytes]) -> None:
        # detached delivery: the put/delete must not block on any watcher's
        # socket; per-connection order is preserved by the outbox pump
        fanned = 0
        for conn, wid, prefix in list(self._watchers.values()):
            if key.startswith(prefix):
                fanned += 1
                conn.push_nowait({"push": "watch", "watch_id": wid,
                                  "key": key, "value": value,
                                  "deleted": value is None})
        if fanned:
            self.stats.watch_fanout.inc(amount=fanned)

    # -- leases ----------------------------------------------------------
    async def _op_lease_grant(self, conn, m):
        ttl = float(m.get("ttl", DEFAULT_TTL))
        # bind=False grants an ORPHAN lease: no owning connection, expires
        # only by TTL. For data meant to outlive its producer — incident
        # beacons/ring dumps, trace spans (a crashed worker's black box
        # must survive the crash that made it interesting).
        bind = bool(m.get("bind", True))
        reuse = m.get("reuse")
        if reuse is not None:
            # session re-establishment: a reconnecting client re-grants its
            # previous lease ID so identity derived from it (worker_id,
            # endpoint keys) survives a store/connection restart. If the
            # lease still exists (expiry hasn't caught up, or a half-open
            # old connection holds it) the new connection ADOPTS it —
            # etcd-style: leases belong to sessions, not TCP connections.
            lid = int(reuse)
            lease = self._leases.get(lid)
            if lease is not None:
                old = lease.owner
                if old is not None and old is not conn:
                    old.leases.discard(lid)
                lease.owner = conn if bind else None
                lease.ttl = ttl
                lease.expires = time.monotonic() + ttl
                if bind:
                    conn.leases.add(lid)
                return {"lease": lid, "ttl": ttl}
        else:
            lid = next(self._lease_ids)
            # a restarted store's counter restarts too: never collide with
            # ids re-granted by reconnecting clients
            while lid in self._leases:
                lid = next(self._lease_ids)
        self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl,
                                   owner=conn if bind else None)
        if bind:
            conn.leases.add(lid)
        return {"lease": lid, "ttl": ttl}

    async def _op_lease_keepalive(self, conn, m):
        lease = self._leases.get(m["lease"])
        if lease is None:
            return {"ok": False, "error": "lease not found",
                    "code": "lease_not_found"}
        lease.expires = time.monotonic() + lease.ttl
        return {}

    async def _op_lease_revoke(self, conn, m):
        await self._expire_lease(m["lease"])
        return {}

    # -- watches ---------------------------------------------------------
    async def _op_watch(self, conn, m):
        """Register a prefix watch; current state is returned inline so the
        caller starts from a consistent snapshot."""
        wid = m["watch_id"]
        prefix = m["prefix"]
        gid = next(self._watch_gids)
        self._watchers[gid] = (conn, wid, prefix)
        conn.watches[wid] = prefix
        items = [[k, v.value] for k, v in sorted(self._kv.items())
                 if k.startswith(prefix)]
        return {"items": items}

    # -- pub/sub ---------------------------------------------------------
    async def _op_subscribe(self, conn, m):
        sid, subject = m["sub_id"], m["subject"]
        gid = next(self._sub_gids)
        self._subs.setdefault(subject, {})[gid] = (conn, sid)
        conn.subs[sid] = subject
        return {}

    async def _op_publish(self, conn, m):
        subject, payload = m["subject"], m["payload"]
        targets = list(self._subs.get(subject, {}).values())
        for c, sid in targets:
            c.push_nowait({"push": "msg", "sub_id": sid,
                           "subject": subject, "payload": payload})
        return {"delivered": len(targets)}

    # -- work queues ------------------------------------------------------
    async def _op_q_push(self, conn, m):
        qname = m["queue"]
        msg = _QueueMsg(next(self._queue_msg_ids), m["payload"])
        self._queues.setdefault(qname, collections.deque()).append(msg)
        await self._kick_queue(qname)
        return {"msg_id": msg.id}

    async def _op_q_pull(self, conn, m):
        """Pull one message; blocks server-side by parking the request until
        a message arrives. Message must be acked or it requeues on disconnect."""
        qname = m["queue"]
        q = self._queues.setdefault(qname, collections.deque())
        if q:
            msg = q.popleft()
            conn.unacked[(qname, msg.id)] = msg
            return {"msg_id": msg.id, "payload": msg.payload}
        self._queue_waiters.setdefault(qname, collections.deque()).append(
            (conn, m.get("id")))
        conn.pulling.setdefault(qname, []).append(m.get("id"))
        return DEFER  # reply pushed by _kick_queue when a message arrives

    async def _op_q_ack(self, conn, m):
        conn.unacked.pop((m["queue"], m["msg_id"]), None)
        return {}

    async def _op_q_len(self, conn, m):
        q = self._queues.get(m["queue"])
        return {"len": len(q) if q else 0}

    async def _kick_queue(self, qname: str) -> None:
        q = self._queues.get(qname)
        waiters = self._queue_waiters.get(qname)
        while q and waiters:
            conn, rid = waiters.popleft()
            if conn.writer.is_closing():
                continue
            msg = q.popleft()
            conn.unacked[(qname, msg.id)] = msg
            try:
                await conn.push({"id": rid, "ok": True, "msg_id": msg.id,
                                 "payload": msg.payload})
            # dynalint: ok(swallowed-exception) the handler IS the
            # recovery: the message is requeued for the next kick and the
            # broken connection is reaped by its own reader loop
            except Exception:
                q.appendleft(msg)
                conn.unacked.pop((qname, msg.id), None)

    # -- misc -------------------------------------------------------------
    async def _op_ping(self, conn, m):
        return {"pong": True}

    # -- self-observability ------------------------------------------------
    def _refresh_gauges(self) -> None:
        s = self.stats
        s.watches.set(value=len(self._watchers))
        s.leases.set(value=len(self._leases))
        s.conns.set(value=len(self._conns))
        for fam, n in self._fam_keys.items():
            s.keys.set(fam, value=n)
            s.bytes.set(fam, value=self._fam_bytes.get(fam, 0))
        depths: Dict[str, int] = {}
        for qname, q in self._queues.items():
            fam = self._family(qname)
            depths[fam] = depths.get(fam, 0) + len(q)
        for fam, d in depths.items():
            s.queue_depth.set(fam, value=d)

    async def _publish_stats(self) -> None:
        """Refresh the self-telemetry dump under :data:`SELF_STAGE_KEY` —
        a direct write into our own KV (with ordinary watch fan-out), so
        the stage-metrics merge path picks the store up like any worker.
        The key dies with the process; a restarted store republishes
        within one interval."""
        while True:
            await asyncio.sleep(self._stats_interval)
            try:
                self._refresh_gauges()
                payload = json.dumps({
                    "component": "store",
                    "metrics": self.stats.registry.state_dump(),
                }).encode()
                self._kv_set(SELF_STAGE_KEY, payload, None)
                await self._notify_watchers(SELF_STAGE_KEY, payload)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("store self-metrics publish failed")


# ----------------------------------------------------------------------
# native (C++) implementation: same protocol, spawned as a subprocess
# ----------------------------------------------------------------------

def native_build_dir() -> str:
    import os

    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def build_native(target: str = "") -> str:
    """Build the native binaries with make (no-op when up to date). Returns
    the build directory. A missing toolchain is only an error when the
    requested artifacts are not already present (deployment images may ship
    prebuilt binaries without a compiler)."""
    import os
    import shutil
    import subprocess

    ndir = native_build_dir()
    wanted = ([target] if target
              else ["build/dynstore", "build/libdynamo_kv.so"])
    prebuilt = all(os.path.exists(os.path.join(ndir, t)) for t in wanted)
    if shutil.which("make") is None or shutil.which("g++") is None:
        if prebuilt:
            return os.path.join(ndir, "build")
        raise RuntimeError("native store requested but make/g++ not found "
                           "and no prebuilt binaries present")
    cmd = ["make", "-C", ndir] + ([target] if target else [])
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(f"native build failed:\n{r.stdout}\n{r.stderr}")
    return os.path.join(ndir, "build")


class NativeStoreServer:
    """Spawns the C++ dynstore (native/dynstore.cpp) — same ``start()/stop()/
    port`` surface as the asyncio server so it drops into every fixture."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._proc: Optional[asyncio.subprocess.Process] = None

    async def start(self) -> int:
        # build off-loop: the first build is a multi-second g++ run and must
        # not stall live coroutines (lease keepalives use sub-second TTLs)
        bdir = await asyncio.to_thread(build_native, "build/dynstore")
        binary = f"{bdir}/dynstore"
        self._proc = await asyncio.create_subprocess_exec(
            binary, "--host", self.host, "--port", str(self.port),
            stdout=asyncio.subprocess.PIPE)
        line = await asyncio.wait_for(self._proc.stdout.readline(), 10.0)
        text = line.decode().strip()  # "dynstore listening on H:P"
        if "listening on" not in text:
            raise RuntimeError(f"native dynstore failed to start: {text!r}")
        self.port = int(text.rsplit(":", 1)[1])
        return self.port

    async def stop(self) -> None:
        if self._proc and self._proc.returncode is None:
            self._proc.terminate()
            try:
                await asyncio.wait_for(self._proc.wait(), 5.0)
            except asyncio.TimeoutError:
                self._proc.kill()
                await self._proc.wait()


PyStoreServer = StoreServer

import os as _os  # noqa: E402

if _os.environ.get("DYNAMO_TPU_STORE") == "native":
    StoreServer = NativeStoreServer  # type: ignore[misc]


async def main(host: str = "0.0.0.0", port: int = 4222) -> None:
    srv = StoreServer(host, port)
    p = await srv.start()
    log.info("dynstore listening on %s:%s", host, p)
    print(f"dynstore listening on {host}:{p}", flush=True)
    while True:
        await asyncio.sleep(3600)


if __name__ == "__main__":
    import argparse

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(prog="dynstore")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=4222)
    env_impl = _os.environ.get("DYNAMO_TPU_STORE", "auto")
    if env_impl not in ("auto", "python", "native"):
        # argparse validates choices only for CLI-supplied values, not
        # defaults — a typo'd env var must not silently run the wrong store
        ap.error(f"DYNAMO_TPU_STORE={env_impl!r} "
                 f"(expected auto|python|native)")
    ap.add_argument("--impl", choices=("auto", "python", "native"),
                    default=env_impl,
                    help="auto = C++ dynstore when it builds/ships, "
                         "falling back to the asyncio fixture")
    a = ap.parse_args()
    if a.impl == "native":
        StoreServer = NativeStoreServer  # type: ignore[misc]
    elif a.impl == "auto":
        try:
            build_native("build/dynstore")
            StoreServer = NativeStoreServer  # type: ignore[misc]
        except RuntimeError:
            log.info("native dynstore unavailable; using asyncio server")
    elif a.impl == "python":
        StoreServer = PyStoreServer  # type: ignore[misc]
    asyncio.run(main(host=a.host, port=a.port))
