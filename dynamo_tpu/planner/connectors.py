"""How decisions become replicas: local process spawn/drain, Kube patch.

- :class:`LocalConnector` — the ``sdk/serve`` shape: scale-up spawns worker
  processes (``python -m dynamo_tpu.cli.worker`` by default) with TPU chips
  granted by the :class:`~..sdk.allocator.TpuAllocator`; scale-down sends
  SIGTERM so the Worker shell runs PR 2's graceful drain (``prepare_drain``
  deregisters BEFORE streams stop; in-flight requests complete). The
  connector never SIGKILLs — a stuck drain is the Worker shell's own
  escalation to handle. It only drains workers IT spawned (it cannot signal
  processes it does not own); externally started baseline workers are the
  floor it scales down to.
- :class:`KubeConnector` — patches replica counts through the operator
  plane: ``crd`` mode read-modify-writes ``spec.services[pool].replicas``
  on the DynamoDeployment resource (the reconciler does the rest), and
  ``deployment`` mode patches the child ``apps/v1`` Deployment directly.
  Works against :class:`~..deploy.rest_api.RestKubeApi` (a real apiserver)
  and :class:`~..deploy.kube.FakeKubeApi` (tests) identically.
- :class:`NullConnector` — observes-only (also what dry-run effectively
  does, but dry-run still records what WOULD have been applied).
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..sdk.allocator import Allocation, AllocationError, TpuAllocator

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class PoolSpec:
    """How the local connector builds a worker for one pool."""

    component: str                      # store component the pool serves as
    chips: int = 0                      # TPU chips per replica (0 = CPU)
    engine: str = "echo"
    # worker binary: cli.worker for decode-shaped pools; cli.prefill_worker
    # (queue-pull, no endpoint, no --engine/--component flags) for prefill
    module: str = "dynamo_tpu.cli.worker"
    extra_args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class _Owned:
    proc: subprocess.Popen
    alloc: Optional[Allocation]
    log_path: str
    started_at: float


class NullConnector:
    """No actuation — a planner that only watches and records."""

    name = "none"

    async def apply(self, pool: str, target: int, decision) -> None:
        log.info("null connector: would set %s -> %d replicas", pool, target)

    async def close(self) -> None:
        pass


class LocalConnector:
    """Spawn/drain local worker processes to meet per-pool targets."""

    name = "local"

    def __init__(self, store: str, namespace: str,
                 pools: Dict[str, PoolSpec],
                 total_chips: int = 4, platform: str = "cpu",
                 cwd: Optional[str] = None, logdir: Optional[str] = None,
                 argv_builder=None, boot_grace: float = 60.0):
        self.store = store
        self.namespace = namespace
        self.pools = dict(pools)
        self.allocator = TpuAllocator(total_chips, platform)
        self.cwd = cwd or os.getcwd()
        self.logdir = logdir or tempfile.mkdtemp(prefix="dyn_planner_")
        self.owned: Dict[str, List[_Owned]] = {p: [] for p in pools}
        self._spawned = 0
        self._argv_builder = argv_builder or self._default_argv
        self._reapers: List[asyncio.Task] = []
        # externally started workers seen per pool (first-apply estimate,
        # revised down if they die) — what lets us count our own BOOTING
        # workers as pending capacity instead of re-spawning every tick
        self._external: Dict[str, int] = {}
        # how long a spawned worker may count as "booting": bounds how long
        # a stale external estimate can wedge scale-up (set >= worst-case
        # worker bring-up; engine weight loads can take minutes)
        self.boot_grace = boot_grace
        # model-mobility swap-wakes in flight per BENEFICIARY pool
        # (monotonic issue times). A swap-wake is incoming capacity — the
        # spawn loop must not double-provision it — but it is NOT a
        # process boot: the worker already exists with an old started_at,
        # so routing it through the pending-boot arithmetic (which gates
        # on process age) would either miscount it or wedge. Tracked
        # separately and pruned by the same boot_grace age cap.
        self._swapping: Dict[str, List[float]] = {}

    # ------------------------------------------------------------------
    def _default_argv(self, pool: str, spec: PoolSpec) -> List[str]:
        if spec.module.endswith("prefill_worker"):
            return [sys.executable, "-m", spec.module,
                    "--store", self.store,
                    "--namespace", self.namespace,
                    "--advertise-host", "127.0.0.1", *spec.extra_args]
        return [sys.executable, "-m", spec.module,
                "--engine", spec.engine, "--store", self.store,
                "--namespace", self.namespace,
                "--component", spec.component,
                "--advertise-host", "127.0.0.1",
                "--metrics-interval", "0.25", *spec.extra_args]

    # ------------------------------------------------------------------
    # dynamic pool membership (the fleet plane adds/removes model pools
    # while the planner runs)
    def set_pool(self, pool: str, spec: PoolSpec) -> None:
        self.pools[pool] = spec
        self.owned.setdefault(pool, [])

    async def remove_pool(self, pool: str) -> None:
        """A model left the registry: gracefully drain every worker this
        connector owns in its pool, then forget the spec. Externally
        started workers are (as ever) not ours to signal."""
        for o in self.live_owned(pool):
            await self._drain(o, pool)
        self.pools.pop(pool, None)

    def live_owned(self, pool: str) -> List[_Owned]:
        """Owned workers still running (reaps exited ones' allocations)."""
        alive = []
        for o in self.owned.get(pool, []):
            if o.proc.poll() is None:
                alive.append(o)
            elif o.alloc is not None:
                self.allocator.release(o.alloc)
                o.alloc = None
        self.owned[pool] = alive
        return alive

    def _spawn(self, pool: str, spec: PoolSpec) -> None:
        try:
            alloc = self.allocator.allocate_handle(spec.chips, service=pool)
        except AllocationError as e:
            log.warning("planner scale-up of %s blocked: %s", pool, e)
            raise
        env = {**os.environ, **alloc.env, **spec.env}
        self._spawned += 1
        path = os.path.join(self.logdir,
                            f"{pool}-{self._spawned}.log")
        logf = open(path, "wb")
        try:
            proc = subprocess.Popen(self._argv_builder(pool, spec),
                                    cwd=self.cwd, env=env, stdout=logf,
                                    stderr=subprocess.STDOUT)
        finally:
            logf.close()   # the child holds its own copy of the fd
        self.owned[pool].append(
            _Owned(proc, alloc, path, time.monotonic()))
        log.info("planner spawned %s worker pid=%d (log %s)", pool,
                 proc.pid, path)

    async def _drain(self, o: _Owned, pool: str) -> None:
        """SIGTERM -> Worker shell graceful drain. NEVER kill -9: the shell
        owns escalation (stop, then kill) inside its own drain budget."""
        if o.proc.poll() is None:
            log.info("planner draining %s worker pid=%d", pool, o.proc.pid)
            o.proc.send_signal(signal.SIGTERM)

        async def reap():
            await asyncio.to_thread(o.proc.wait)
            if o.alloc is not None:
                self.allocator.release(o.alloc)
                o.alloc = None

        # prune finished reapers so a standing daemon's list stays bounded
        self._reapers = [t for t in self._reapers if not t.done()]
        self._reapers.append(asyncio.create_task(reap()))

    # ------------------------------------------------------------------
    # model mobility: in-place weight swap instead of spawn + drain
    async def swap_pool(self, store, namespace: str, from_pool: str,
                        from_component: str, payload: Dict) -> int:
        """Issue one SIGUSR1-style swap command: a worker of
        ``from_component`` should overwrite its weights in place with
        ``payload["model"]``'s and re-register under that model's
        component. ``store`` is the fleet plane's async store client
        (this connector's own ``self.store`` is just an address string).
        The command key holds a single claim-by-delete record, so at
        most one swap per donor component is in flight at a time — a
        still-pending command from an earlier tick is left alone and 0
        is returned (the plane falls back to plain spawn/drain for the
        remainder). Returns the number of swaps issued (0 or 1)."""
        import json as _json

        from ..fleet.mobility.keys import mobility_swap_key
        key = mobility_swap_key(namespace, from_component)
        if await store.get(key):
            return 0
        await store.put(key, _json.dumps(payload).encode())
        self.note_swap(from_pool, payload["model"])
        return 1

    def note_swap(self, from_pool: str, to_pool: str) -> None:
        """Accounting for one issued swap: move the donor pool's oldest
        owned process record to the beneficiary (the process keeps
        running and will serve the new component — draining
        ``from_pool`` later must not SIGTERM a worker that left it, and
        its chip allocation now belongs to ``to_pool``), and mark the
        wake in flight so ``apply`` neither spawns over it nor counts it
        as a pending process boot."""
        alive = self.live_owned(from_pool)
        if alive:
            moved = min(alive, key=lambda o: o.started_at)
            self.owned[from_pool].remove(moved)
            self.owned.setdefault(to_pool, []).append(moved)
        # else: an externally started worker swaps away; from_pool's
        # registered count drops on its own and apply's external
        # estimate revises itself down (ext = min(ext, current))
        self._swapping.setdefault(to_pool, []).append(time.monotonic())

    def _live_swaps(self, pool: str) -> int:
        """Swap-wakes still plausibly in flight for ``pool`` (age-capped
        by boot_grace so a failed swap cannot suppress spawns forever)."""
        now = time.monotonic()
        keep = [t for t in self._swapping.get(pool, ())
                if now - t < self.boot_grace]
        if keep:
            self._swapping[pool] = keep
        else:
            self._swapping.pop(pool, None)
        return len(keep)

    # ------------------------------------------------------------------
    async def apply(self, pool: str, target: int, decision) -> None:
        spec = self.pools.get(pool)
        if spec is None:
            log.warning("planner: no local pool spec for %r", pool)
            return
        current = decision.current
        alive = self.live_owned(pool)
        if current >= target:
            # capacity arrived (a swap landed and re-registered, or a
            # plain boot finished): in-flight wake markers are spent
            self._swapping.pop(pool, None)
        if target > current:
            # pending = owned processes alive but not yet registered (still
            # booting). Spawning target-current every tick would overshoot
            # the clamp whenever boot time exceeds the decision cadence.
            ext = self._external.get(pool)
            if ext is None:
                ext = max(current - len(alive), 0)
            ext = min(ext, current)     # externals that died stop counting
            self._external[pool] = ext
            owned_registered = max(current - ext, 0)
            # two independent bounds on "booting": the registration
            # arithmetic (exact while the external estimate holds) and the
            # boot-grace age cap (self-healing when an external died while
            # an owned worker was registered — the estimate can't tell
            # those apart and would otherwise wedge scale-up forever)
            now = time.monotonic()
            # swap-wakes are counted OUTSIDE the boot arithmetic: the
            # swapping worker is an old process (never "young") whose
            # registration is still under its old pool, so without the
            # separate ledger the spawn loop would double-provision
            # every swap with a cold boot
            swapping = self._live_swaps(pool)
            young = sum(1 for o in alive
                        if now - o.started_at < self.boot_grace)
            pending = min(
                max(len(alive) - swapping - owned_registered, 0), young)
            for _ in range(target - current - pending - swapping):
                try:
                    self._spawn(pool, spec)
                except AllocationError:
                    break       # out of chips: partial scale-up, retried
                                # naturally on the next evaluation
        elif target < current:
            # newest-first: baseline (externally started / oldest) workers
            # are the last to go, and never workers we don't own.
            # Replicas leaving by swap (note_swap already moved their
            # ownership to the beneficiary) are part of the shrink — do
            # not SIGTERM extra workers to cover them.
            swap_out = getattr(decision, "swap_out", 0)
            shrink = min(max(current - target - swap_out, 0), len(alive))
            victims = sorted(alive, key=lambda o: -o.started_at)[:shrink]
            if shrink < current - target - swap_out:
                log.info("planner: %s scale-down to %d limited to %d owned "
                         "worker(s); externally started workers are not "
                         "drainable from here", pool, target, shrink)
            for o in victims:
                await self._drain(o, pool)

    async def close(self, drain: bool = True) -> None:
        for pool in list(self.owned):
            for o in self.live_owned(pool):
                if drain:
                    await self._drain(o, pool)
                else:
                    o.proc.terminate()
        for t in self._reapers:
            try:
                await asyncio.wait_for(t, timeout=30.0)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                t.cancel()
        self._reapers.clear()


class KubeConnector:
    """Patch replica counts through the Kubernetes plane."""

    name = "kube"

    def __init__(self, api, deployment: str, kube_namespace: str = "default",
                 mode: str = "crd",
                 service_for_pool: Optional[Dict[str, str]] = None,
                 crd_api_version: str = "dynamo.tpu/v1alpha1"):
        if mode not in ("crd", "deployment"):
            raise ValueError(f"KubeConnector mode {mode!r}")
        self.api = api
        self.deployment = deployment
        self.kube_namespace = kube_namespace
        self.mode = mode
        # pool -> CRD service name / child Deployment suffix. Defaults to
        # the pool name itself (the manifests lowercase service names).
        self.service_for_pool = dict(service_for_pool or {})
        self.crd_api_version = crd_api_version

    def _service(self, pool: str) -> str:
        return self.service_for_pool.get(pool, pool).lower()

    def set_pool(self, pool: str, spec) -> None:
        """Fleet-plane hook: map a model pool onto its CRD service name
        (the PoolSpec's component; the reconciler owns the rest)."""
        self.service_for_pool.setdefault(
            pool, getattr(spec, "component", pool))

    async def remove_pool(self, pool: str) -> None:
        """A model left the registry: patch its service to zero replicas
        (the registry contract — 'the planner's next tick drains the
        pool') and drop the mapping. A missing resource is fine: the
        deployment may never have been reconciled."""
        try:
            await asyncio.to_thread(self._apply_sync, pool, 0)
        except RuntimeError:
            log.info("fleet pool %s: no kube resource to drain", pool)
        self.service_for_pool.pop(pool, None)

    def _apply_sync(self, pool: str, target: int) -> None:
        svc = self._service(pool)
        if self.mode == "crd":
            # read-modify-write the full object: a partial spec would be
            # taken as a spec REPLACE by the fake api (and SSA field
            # stripping on a real one), wiping sibling services' replicas.
            # The carried resourceVersion makes a concurrent editor a
            # clean conflict instead of a lost update.
            obj = self.api.get("DynamoDeployment", self.kube_namespace,
                               self.deployment)
            if obj is None:
                raise RuntimeError(
                    f"DynamoDeployment {self.deployment} not found in "
                    f"{self.kube_namespace}")
            obj.setdefault("apiVersion", self.crd_api_version)
            obj.setdefault("kind", "DynamoDeployment")
            services = obj.setdefault("spec", {}).setdefault("services", {})
            services.setdefault(svc, {})["replicas"] = int(target)
            self.api.apply(obj)
        else:
            name = f"{self.deployment}-{svc}"
            obj = self.api.get("Deployment", self.kube_namespace, name)
            if obj is None:
                raise RuntimeError(f"Deployment {name} not found in "
                                   f"{self.kube_namespace}")
            obj.setdefault("spec", {})["replicas"] = int(target)
            self.api.apply(obj)

    async def apply(self, pool: str, target: int, decision) -> None:
        # the REST adapter is sync urllib: keep the control loop unblocked
        await asyncio.to_thread(self._apply_sync, pool, target)

    async def close(self) -> None:
        pass
