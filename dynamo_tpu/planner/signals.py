"""What the planner observes: per-pool load/SLA signals from the store.

Everything the decision engine consumes is collapsed into one
:class:`PoolSignals` snapshot per pool, assembled from planes that already
exist:

- live replica count      — the endpoint registration prefix (lease-bound,
  so dead workers vanish with their lease);
- slot/KV occupancy       — per-worker ForwardPassMetrics snapshots under
  ``metrics/`` (the aggregator's scrape source, read directly);
- prefill queue depth     — the shared dynstore work queue's ``q_len``;
- TTFT / ITL percentiles  — the per-stage latency histograms workers publish
  under ``metrics_stage/`` (PR 1), merged across processes;
- circuit-breaker state   — ``dyn_circuit_state`` series in the same dumps
  (instances any observer currently sees OPEN).

The collector is store-only (no data-plane client, no DistributedRuntime
needed beyond a StoreClient), so the planner can run anywhere the store is
reachable — including inside the frontend or as its own binary.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..llm.disagg import prefill_queue_names
from ..llm.metrics_aggregator import STAGE_PREFIX, fetch_worker_metrics
from ..runtime.component import endpoint_prefix
from ..utils.overload import admission_depth_total, shed_totals

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class PoolSignals:
    """One pool's observation snapshot — the decision engine's whole input."""

    pool: str                       # "decode" | "prefill" (or any component)
    replicas: int = 0               # live registered instances
    active_slots: float = 0.0       # sum of request_active_slots
    total_slots: float = 0.0        # sum of request_total_slots
    queue_depth: float = 0.0        # prefill queue len / requests waiting
    kv_active: float = 0.0
    kv_total: float = 0.0
    ttft_p90: Optional[float] = None
    itl_p90: Optional[float] = None
    breaker_open: int = 0           # instances some observer sees OPEN
    worker_ids: List[int] = field(default_factory=list)
    # SLO pressure (utils/slo.py): worst error-budget burn per declared
    # objective across windows — burn > 1 means the budget is being spent
    # faster than sustainable, i.e. direct scale-up pressure. Empty when
    # no DYN_SLO_* objectives are configured.
    slo_burn: Dict[str, float] = field(default_factory=dict)
    # overload plane (utils/overload.py): demand the fleet REJECTED.
    # shed_rate is admission rejects + queue sheds per second across the
    # fleet — backlog gauges alone go blind exactly when shedding keeps
    # the queues bounded, so policies must scale on rejected demand too.
    shed_rate: float = 0.0
    # in-flight requests currently held by admission controllers
    admission_depth: float = 0.0
    # scale-from-zero pressure (fleet/model pools only): requests observed
    # for this model while NO replica served it (the frontend's
    # model-labelled 404s). A scaled-to-zero pool has no queue and no
    # occupancy — unserved demand is its only wake signal.
    unserved: float = 0.0

    @property
    def slo_pressure(self) -> float:
        """The single worst burn across objectives (0 = within budget)."""
        return max(self.slo_burn.values(), default=0.0)

    @property
    def occupancy(self) -> float:
        """Batch occupancy 0..1+ (echo/overcommitted engines can exceed 1)."""
        return self.active_slots / self.total_slots if self.total_slots \
            else 0.0

    @property
    def kv_utilization(self) -> float:
        return self.kv_active / self.kv_total if self.kv_total else 0.0

    @property
    def healthy_replicas(self) -> int:
        """Replicas the breaker is not currently vetoing."""
        return max(self.replicas - self.breaker_open, 0)

    def to_dict(self) -> Dict:
        d = asdict(self)
        d["occupancy"] = round(self.occupancy, 4)
        d["kv_utilization"] = round(self.kv_utilization, 4)
        return d


# ---------------------------------------------------------------------------
# histogram quantiles over published stage-metric state dumps
# ---------------------------------------------------------------------------
def quantile_from_states(states: Iterable[Tuple[str, Dict]], metric: str,
                         q: float) -> Optional[float]:
    """Estimate the ``q``-quantile of a histogram metric across every
    published state dump (all label series merged). Linear interpolation
    inside the winning bucket, bounded by its edges; None when no samples.
    """
    buckets: Optional[List[float]] = None
    counts: Optional[List[int]] = None
    total = 0
    for _component, dump in states:
        st = dump.get(metric)
        if not st or st.get("kind") != "histogram":
            continue
        b = list(st.get("buckets") or ())
        if buckets is None:
            buckets, counts = b, [0] * len(b)
        elif b != buckets:
            continue    # mixed bucket layouts: skip rather than lie
        for series in st.get("series", {}).values():
            c = series.get("counts") or []
            for i in range(min(len(c), len(counts))):
                counts[i] += c[i]
            total += int(series.get("total", 0))
    if not total or buckets is None:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            lo = buckets[i - 1] if i else 0.0
            hi = buckets[i]
            frac = (rank - (cum - c)) / c if c else 1.0
            return lo + (hi - lo) * frac
    # rank landed in +Inf (observations above the last bucket): the last
    # edge is the honest lower bound
    return buckets[-1]


def filter_states_by_model(states: Iterable[Tuple[str, Dict]],
                           model: str) -> List[Tuple[str, Dict]]:
    """Project one round of ``(component, state_dump)`` pairs down to a
    single model: every metric carrying a ``model`` label keeps only that
    model's series; label-less metrics pass through untouched. This is
    what makes TTFT/ITL quantiles and SLO burn *model-scoped* for fleet
    pools — the histograms were per-model all along (the ``model`` label
    exists since PR 1), the readers just merged them."""
    out: List[Tuple[str, Dict]] = []
    for comp, dump in states:
        nd: Dict = {}
        for name, st in dump.items():
            labels = (list(st.get("labels") or ())
                      if isinstance(st, dict) else [])
            if "model" not in labels:
                nd[name] = st
                continue
            pos = labels.index("model")
            series = {k: v for k, v in (st.get("series") or {}).items()
                      if (k.split("\x1f") + [""])[pos] == model}
            nd[name] = {**st, "series": series}
        out.append((comp, nd))
    return out


def model_request_count(states: Iterable[Tuple[str, Dict]], model: str,
                        status: str = "404") -> float:
    """Cumulative ``dyn_http_requests_total`` count for one (model,
    status) across every frontend dump — the scale-from-zero wake
    counter (frontends label a 404 with the model name when the model is
    fleet-registered, so the label set stays bounded)."""
    total = 0.0
    for _component, dump in states:
        st = dump.get("dyn_http_requests_total")
        if not st or st.get("kind") != "counter":
            continue
        labels = list(st.get("labels") or ())
        try:
            m_pos = labels.index("model")
            s_pos = labels.index("status")
        except ValueError:
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if (len(parts) > max(m_pos, s_pos) and parts[m_pos] == model
                    and parts[s_pos] == status):
                total += val
    return total


def model_parked_count(states: Iterable[Tuple[str, Dict]],
                       model: str) -> float:
    """Cumulative queue-until-boot parks for one model
    (``dyn_queue_until_boot_total{model,outcome="parked"}``): a parked
    request never produced the 404 the wake signal was built on, so the
    unserved-demand delta must count it too or parking would starve the
    very boot it waits for."""
    total = 0.0
    for _component, dump in states:
        st = dump.get("dyn_queue_until_boot_total")
        if not st or st.get("kind") != "counter":
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if len(parts) >= 2 and parts[0] == model \
                    and parts[1] == "parked":
                total += val
    return total


def open_instance_ids(states: Iterable[Tuple[str, Dict]]) -> Set[str]:
    """Hex instance ids at least one observer's exported
    ``dyn_circuit_state`` series currently marks OPEN (value 2) — shared
    between the planner's breaker signal and dyntop's breaker column."""
    open_ids: Set[str] = set()
    for _component, dump in states:
        st = dump.get("dyn_circuit_state")
        if not st or st.get("kind") != "gauge":
            continue
        labels = list(st.get("labels") or ())
        try:
            pos = labels.index("instance")
        except ValueError:
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if len(parts) > pos and val == 2:
                open_ids.add(parts[pos])
    return open_ids


def breaker_open_instances(states: Iterable[Tuple[str, Dict]],
                           worker_ids: Iterable[int]) -> int:
    """Instances in ``worker_ids`` some observer currently sees OPEN."""
    return len(open_instance_ids(states) & {f"{w:x}" for w in worker_ids})


class SignalCollector:
    """Assembles :class:`PoolSignals` for each configured pool from one
    round of store reads. ``pools`` maps a pool name to the component whose
    workers make it up (e.g. ``{"decode": "backend", "prefill": "prefill"}``).
    """

    def __init__(self, store, namespace: str, pools: Dict[str, str],
                 endpoint: str = "generate"):
        from ..utils.slo import SloMonitor

        self.store = store
        self.namespace = namespace
        self.pools = dict(pools)
        self.endpoint = endpoint
        # which path fed the last collect(): "region" when live regional
        # aggregators' pre-merged records served the scrape, "flat" when
        # the per-worker prefix scan did (plannerctl reports this)
        self.last_source = "flat"
        # SLO burn monitor over the same stage dumps: its gauges land on
        # the planner's stage registry (published with the dyn_planner_*
        # series), its breach log feeds PoolSignals.slo_burn
        self.slo = SloMonitor()
        # fleet mode: pool name -> model name. A model pool's latency/SLO
        # signals are computed over filter_states_by_model (its own
        # histogram series), and unserved-request wake pressure is
        # tracked for scale-from-zero.
        self.pool_models: Dict[str, str] = {}
        # per-model monitors observe WITHOUT exporting (the gauge has no
        # model label; the global monitor above owns the exported series)
        self._model_slo: Dict[str, "SloMonitor"] = {}
        self._unserved_prev: Dict[str, float] = {}
        # shed-rate derivation: cumulative fleet shed counters from the
        # last collect, differentiated against the wall between ticks
        self._shed_prev: Optional[Tuple[float, float]] = None

    def forget_pool(self, pool: str) -> None:
        """Drop a removed fleet pool's accumulated state. Without this a
        model removed and later re-added under the same name would
        compute burn deltas against pre-removal snapshots, and rings for
        never-returning models would accumulate for the planner's
        lifetime."""
        self.pool_models.pop(pool, None)
        self._model_slo.pop(pool, None)
        self._unserved_prev.pop(pool, None)

    async def live_instances(self, component: str,
                             known: Iterable[int] = ()) -> List[int]:
        """Live worker ids of one component: endpoint registrations
        (decode-shaped workers) unioned with ``known`` — ids the caller
        already holds from the lease-bound metrics and stage-metrics
        planes. Queue-pull prefill workers register no endpoint at all, so
        counting endpoints alone would read the prefill pool as permanently
        empty (never scaled down, spurious scale-ups forever)."""
        ids = set(known)
        prefix = endpoint_prefix(self.namespace, component, self.endpoint)
        for key, _value in await self.store.get_prefix(prefix):
            try:
                ids.add(int(key.rsplit(":", 1)[1], 16))
            except ValueError:
                log.warning("malformed endpoint key %s", key)
        return sorted(ids)

    async def _fetch_stage(self) -> Tuple[List[Tuple[str, Dict]],
                                          Dict[str, Set[int]],
                                          Optional["object"]]:
        """One scan of the namespace's stage-metrics prefix yielding the
        ``(component, state_dump)`` pairs (quantiles, breaker state), the
        per-component worker-id sets (liveness), and — when the region
        plane served the read — the per-component ForwardPassMetrics
        maps, sparing the per-pool ``metrics/`` scans too. The dumps are
        multi-KB, so fetching them once per tick instead of 1+P times
        matters on a standing daemon; at fleet scale the regional
        aggregators' R pre-merged records replace the N-worker scan
        entirely (flat fallback when no fresh region exists)."""
        from ..llm.metrics_aggregator import (merge_stage_items,
                                              split_stage_key,
                                              stage_base_key)
        from ..runtime.scale.regions import fetch_region_states

        regional = await fetch_region_states(self.store, self.namespace)
        if regional is not None:
            self.last_source = "region"
            return (regional.states,
                    {c: set(ids) for c, ids in regional.ids.items()},
                    regional)
        self.last_source = "flat"
        states: List[Tuple[str, Dict]] = []
        ids: Dict[str, Set[int]] = {}
        prefix = f"{STAGE_PREFIX}{self.namespace}/"
        items = list(await self.store.get_prefix(prefix))
        valid: Dict[str, str] = {}   # base_key -> component
        for key, _value in items:
            base = stage_base_key(key)
            comp, widhex = split_stage_key(base[len(prefix):])
            try:
                wid = int(widhex, 16)
            except ValueError:
                log.warning("malformed stage key %s", key)
                continue
            valid[base] = comp
            if not key.endswith("/delta"):
                # count the replica even if its payload is corrupt — a
                # live worker mid-write must not read as a missing one
                ids.setdefault(comp, set()).add(wid)
        # full+delta overlay: the ONE protocol implementation lives in
        # metrics_aggregator.merge_stage_items
        for base, (d, metrics) in merge_stage_items(items).items():
            if base in valid:
                states.append((d.get("component") or valid[base], metrics))
        return states, ids, None

    def _shed_rate(self, stage_states) -> float:
        total = shed_totals(stage_states)
        now = time.monotonic()
        rate = 0.0
        if self._shed_prev is not None:
            dt = now - self._shed_prev[0]
            if dt > 0:
                # max(0): a restarted frontend resets its counters
                rate = max(0.0, (total - self._shed_prev[1]) / dt)
        self._shed_prev = (now, total)
        return rate

    async def collect(self) -> Dict[str, PoolSignals]:
        stage_states, stage_ids, regional = await self._fetch_stage()
        if self.slo.objectives:
            self.slo.observe(stage_states)
        slo_burn = self.slo.max_burn()
        shed_rate = self._shed_rate(stage_states)
        admission_depth = admission_depth_total(stage_states)
        model_share = self._model_shed_share()
        prefill_q = 0
        for qname in prefill_queue_names(self.namespace):
            try:
                prefill_q += await self.store.q_len(qname)
            except Exception:  # noqa: BLE001 - queue plane optional
                pass
        out: Dict[str, PoolSignals] = {}
        for pool, component in self.pools.items():
            if regional is not None:
                workers = regional.workers_for(component)
            else:
                workers = await fetch_worker_metrics(
                    self.store, self.namespace, component)
            ids = await self.live_instances(
                component,
                known=set(workers) | stage_ids.get(component, set()))
            s = PoolSignals(pool=pool, replicas=len(ids), worker_ids=ids)
            for m in workers.values():
                s.active_slots += m.request_active_slots
                s.total_slots += m.request_total_slots
                s.kv_active += m.kv_active_blocks
                s.kv_total += m.kv_total_blocks
                s.queue_depth += m.num_requests_waiting
            if pool == "prefill":
                # the shared remote-prefill queue is THE prefill backlog.
                # TTFT/ITL are end-to-end serving SLOs recorded by the
                # frontend/decode side — attributing them to the prefill
                # pool would ratchet prefill replicas up for a latency
                # problem more prefill workers cannot fix; its SLA lever
                # is the queue depth above.
                s.queue_depth += prefill_q
            else:
                model = self.pool_models.get(pool)
                # model pools read their OWN latency series; the
                # single-pool shape keeps the all-series merge
                scoped = (filter_states_by_model(stage_states, model)
                          if model else stage_states)
                s.ttft_p90 = quantile_from_states(
                    scoped, "llm_ttft_seconds", 0.90)
                s.itl_p90 = quantile_from_states(
                    scoped, "llm_inter_token_seconds", 0.90)
                # end-to-end SLO burn is serving-side pressure, same
                # attribution rule as ttft/itl above (more prefill
                # replicas can't fix a decode-side latency breach)
                s.slo_burn = (self._model_burn(pool, model, scoped)
                              if model else dict(slo_burn))
                # rejected demand is serving-side pressure too: admission
                # and worker-queue sheds are absorbed by the decode fleet
                # (model pools get their even share — see above)
                share = model_share if model else 1.0
                s.shed_rate = shed_rate * share
                s.admission_depth = admission_depth * share
                if model:
                    s.unserved = self._unserved_delta(
                        pool, model, stage_states, s.replicas)
            s.breaker_open = breaker_open_instances(stage_states, ids)
            out[pool] = s
        return out

    def _model_shed_share(self) -> float:
        """Fleet mode: sheds happen pre-body (no model label), so the
        fleet-wide shed rate cannot be attributed to one model — but
        handing every model pool the FULL rate would let one model's
        storm inflate every pool's demand N-fold. Each model pool gets
        an even 1/N share: total scale-up pressure stays the true fleet
        total, no pool sees phantom demand beyond its share. Classic
        (non-fleet) pools keep full attribution."""
        n = sum(1 for p in self.pools
                if p in self.pool_models and p != "prefill")
        return 1.0 / n if n else 1.0

    def _model_burn(self, pool: str, model: str,
                    scoped_states) -> Dict[str, float]:
        """Per-model SLO burn: a private monitor per model pool fed the
        model-filtered dumps (same DYN_SLO_* objectives, no gauge export
        — the exported series stays the fleet aggregate)."""
        from ..utils.slo import SloMonitor

        mon = self._model_slo.get(pool)
        if mon is None:
            mon = self._model_slo[pool] = SloMonitor(registry_gauge=None)
        if not mon.objectives:
            return {}
        mon.observe(scoped_states)
        return mon.max_burn()

    def _unserved_delta(self, pool: str, model: str, stage_states,
                        replicas: int) -> float:
        """Requests that 404'd on — or were parked at ingress waiting
        for — this model since the last tick, counted only while the
        pool is at zero replicas (once a replica serves, stale 404s from
        the boot race must not keep inflating demand)."""
        total = (model_request_count(stage_states, model, "404")
                 + model_parked_count(stage_states, model))
        prev = self._unserved_prev.get(pool)
        self._unserved_prev[pool] = total
        if replicas > 0 or prev is None:
            return 0.0
        return max(total - prev, 0.0)


def fake_signals(pool: str, **kw) -> PoolSignals:
    """Test/chaos helper: a PoolSignals with keyword overrides."""
    return PoolSignals(pool=pool, **kw)
