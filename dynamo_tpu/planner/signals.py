"""What the planner observes: per-pool load/SLA signals from the store.

Everything the decision engine consumes is collapsed into one
:class:`PoolSignals` snapshot per pool, assembled from planes that already
exist:

- live replica count      — the endpoint registration prefix (lease-bound,
  so dead workers vanish with their lease);
- slot/KV occupancy       — per-worker ForwardPassMetrics snapshots under
  ``metrics/`` (the aggregator's scrape source, read directly);
- prefill queue depth     — the shared dynstore work queue's ``q_len``;
- TTFT / ITL percentiles  — the per-stage latency histograms workers publish
  under ``metrics_stage/`` (PR 1), merged across processes;
- circuit-breaker state   — ``dyn_circuit_state`` series in the same dumps
  (instances any observer currently sees OPEN).

The collector is store-only (no data-plane client, no DistributedRuntime
needed beyond a StoreClient), so the planner can run anywhere the store is
reachable — including inside the frontend or as its own binary.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..llm.disagg import prefill_queue_names
from ..llm.metrics_aggregator import STAGE_PREFIX, fetch_worker_metrics
from ..runtime.component import endpoint_prefix
from ..utils.overload import admission_depth_total, shed_totals

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class PoolSignals:
    """One pool's observation snapshot — the decision engine's whole input."""

    pool: str                       # "decode" | "prefill" (or any component)
    replicas: int = 0               # live registered instances
    active_slots: float = 0.0       # sum of request_active_slots
    total_slots: float = 0.0        # sum of request_total_slots
    queue_depth: float = 0.0        # prefill queue len / requests waiting
    kv_active: float = 0.0
    kv_total: float = 0.0
    ttft_p90: Optional[float] = None
    itl_p90: Optional[float] = None
    breaker_open: int = 0           # instances some observer sees OPEN
    worker_ids: List[int] = field(default_factory=list)
    # SLO pressure (utils/slo.py): worst error-budget burn per declared
    # objective across windows — burn > 1 means the budget is being spent
    # faster than sustainable, i.e. direct scale-up pressure. Empty when
    # no DYN_SLO_* objectives are configured.
    slo_burn: Dict[str, float] = field(default_factory=dict)
    # overload plane (utils/overload.py): demand the fleet REJECTED.
    # shed_rate is admission rejects + queue sheds per second across the
    # fleet — backlog gauges alone go blind exactly when shedding keeps
    # the queues bounded, so policies must scale on rejected demand too.
    shed_rate: float = 0.0
    # in-flight requests currently held by admission controllers
    admission_depth: float = 0.0

    @property
    def slo_pressure(self) -> float:
        """The single worst burn across objectives (0 = within budget)."""
        return max(self.slo_burn.values(), default=0.0)

    @property
    def occupancy(self) -> float:
        """Batch occupancy 0..1+ (echo/overcommitted engines can exceed 1)."""
        return self.active_slots / self.total_slots if self.total_slots \
            else 0.0

    @property
    def kv_utilization(self) -> float:
        return self.kv_active / self.kv_total if self.kv_total else 0.0

    @property
    def healthy_replicas(self) -> int:
        """Replicas the breaker is not currently vetoing."""
        return max(self.replicas - self.breaker_open, 0)

    def to_dict(self) -> Dict:
        d = asdict(self)
        d["occupancy"] = round(self.occupancy, 4)
        d["kv_utilization"] = round(self.kv_utilization, 4)
        return d


# ---------------------------------------------------------------------------
# histogram quantiles over published stage-metric state dumps
# ---------------------------------------------------------------------------
def quantile_from_states(states: Iterable[Tuple[str, Dict]], metric: str,
                         q: float) -> Optional[float]:
    """Estimate the ``q``-quantile of a histogram metric across every
    published state dump (all label series merged). Linear interpolation
    inside the winning bucket, bounded by its edges; None when no samples.
    """
    buckets: Optional[List[float]] = None
    counts: Optional[List[int]] = None
    total = 0
    for _component, dump in states:
        st = dump.get(metric)
        if not st or st.get("kind") != "histogram":
            continue
        b = list(st.get("buckets") or ())
        if buckets is None:
            buckets, counts = b, [0] * len(b)
        elif b != buckets:
            continue    # mixed bucket layouts: skip rather than lie
        for series in st.get("series", {}).values():
            c = series.get("counts") or []
            for i in range(min(len(c), len(counts))):
                counts[i] += c[i]
            total += int(series.get("total", 0))
    if not total or buckets is None:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            lo = buckets[i - 1] if i else 0.0
            hi = buckets[i]
            frac = (rank - (cum - c)) / c if c else 1.0
            return lo + (hi - lo) * frac
    # rank landed in +Inf (observations above the last bucket): the last
    # edge is the honest lower bound
    return buckets[-1]


def open_instance_ids(states: Iterable[Tuple[str, Dict]]) -> Set[str]:
    """Hex instance ids at least one observer's exported
    ``dyn_circuit_state`` series currently marks OPEN (value 2) — shared
    between the planner's breaker signal and dyntop's breaker column."""
    open_ids: Set[str] = set()
    for _component, dump in states:
        st = dump.get("dyn_circuit_state")
        if not st or st.get("kind") != "gauge":
            continue
        labels = list(st.get("labels") or ())
        try:
            pos = labels.index("instance")
        except ValueError:
            continue
        for skey, val in st.get("series", {}).items():
            parts = skey.split("\x1f")
            if len(parts) > pos and val == 2:
                open_ids.add(parts[pos])
    return open_ids


def breaker_open_instances(states: Iterable[Tuple[str, Dict]],
                           worker_ids: Iterable[int]) -> int:
    """Instances in ``worker_ids`` some observer currently sees OPEN."""
    return len(open_instance_ids(states) & {f"{w:x}" for w in worker_ids})


class SignalCollector:
    """Assembles :class:`PoolSignals` for each configured pool from one
    round of store reads. ``pools`` maps a pool name to the component whose
    workers make it up (e.g. ``{"decode": "backend", "prefill": "prefill"}``).
    """

    def __init__(self, store, namespace: str, pools: Dict[str, str],
                 endpoint: str = "generate"):
        from ..utils.slo import SloMonitor

        self.store = store
        self.namespace = namespace
        self.pools = dict(pools)
        self.endpoint = endpoint
        # SLO burn monitor over the same stage dumps: its gauges land on
        # the planner's stage registry (published with the dyn_planner_*
        # series), its breach log feeds PoolSignals.slo_burn
        self.slo = SloMonitor()
        # shed-rate derivation: cumulative fleet shed counters from the
        # last collect, differentiated against the wall between ticks
        self._shed_prev: Optional[Tuple[float, float]] = None

    async def live_instances(self, component: str,
                             known: Iterable[int] = ()) -> List[int]:
        """Live worker ids of one component: endpoint registrations
        (decode-shaped workers) unioned with ``known`` — ids the caller
        already holds from the lease-bound metrics and stage-metrics
        planes. Queue-pull prefill workers register no endpoint at all, so
        counting endpoints alone would read the prefill pool as permanently
        empty (never scaled down, spurious scale-ups forever)."""
        ids = set(known)
        prefix = endpoint_prefix(self.namespace, component, self.endpoint)
        for key, _value in await self.store.get_prefix(prefix):
            try:
                ids.add(int(key.rsplit(":", 1)[1], 16))
            except ValueError:
                log.warning("malformed endpoint key %s", key)
        return sorted(ids)

    async def _fetch_stage(self) -> Tuple[List[Tuple[str, Dict]],
                                          Dict[str, Set[int]]]:
        """One scan of the namespace's stage-metrics prefix yielding BOTH
        the ``(component, state_dump)`` pairs (quantiles, breaker state)
        and the per-component worker-id sets (liveness) — the dumps are
        multi-KB, so fetching them once per tick instead of 1+P times
        matters on a standing daemon."""
        from ..llm.metrics_aggregator import (merge_stage_items,
                                              stage_base_key)

        states: List[Tuple[str, Dict]] = []
        ids: Dict[str, Set[int]] = {}
        prefix = f"{STAGE_PREFIX}{self.namespace}/"
        items = list(await self.store.get_prefix(prefix))
        valid: Dict[str, str] = {}   # base_key -> component
        for key, _value in items:
            base = stage_base_key(key)
            comp, _, widhex = base[len(prefix):].partition("/")
            try:
                wid = int(widhex, 16)
            except ValueError:
                log.warning("malformed stage key %s", key)
                continue
            valid[base] = comp
            if not key.endswith("/delta"):
                # count the replica even if its payload is corrupt — a
                # live worker mid-write must not read as a missing one
                ids.setdefault(comp, set()).add(wid)
        # full+delta overlay: the ONE protocol implementation lives in
        # metrics_aggregator.merge_stage_items
        for base, (d, metrics) in merge_stage_items(items).items():
            if base in valid:
                states.append((d.get("component") or valid[base], metrics))
        return states, ids

    def _shed_rate(self, stage_states) -> float:
        total = shed_totals(stage_states)
        now = time.monotonic()
        rate = 0.0
        if self._shed_prev is not None:
            dt = now - self._shed_prev[0]
            if dt > 0:
                # max(0): a restarted frontend resets its counters
                rate = max(0.0, (total - self._shed_prev[1]) / dt)
        self._shed_prev = (now, total)
        return rate

    async def collect(self) -> Dict[str, PoolSignals]:
        stage_states, stage_ids = await self._fetch_stage()
        if self.slo.objectives:
            self.slo.observe(stage_states)
        slo_burn = self.slo.max_burn()
        shed_rate = self._shed_rate(stage_states)
        admission_depth = admission_depth_total(stage_states)
        prefill_q = 0
        for qname in prefill_queue_names(self.namespace):
            try:
                prefill_q += await self.store.q_len(qname)
            except Exception:  # noqa: BLE001 - queue plane optional
                pass
        out: Dict[str, PoolSignals] = {}
        for pool, component in self.pools.items():
            workers = await fetch_worker_metrics(self.store, self.namespace,
                                                 component)
            ids = await self.live_instances(
                component,
                known=set(workers) | stage_ids.get(component, set()))
            s = PoolSignals(pool=pool, replicas=len(ids), worker_ids=ids)
            for m in workers.values():
                s.active_slots += m.request_active_slots
                s.total_slots += m.request_total_slots
                s.kv_active += m.kv_active_blocks
                s.kv_total += m.kv_total_blocks
                s.queue_depth += m.num_requests_waiting
            if pool == "prefill":
                # the shared remote-prefill queue is THE prefill backlog.
                # TTFT/ITL are end-to-end serving SLOs recorded by the
                # frontend/decode side — attributing them to the prefill
                # pool would ratchet prefill replicas up for a latency
                # problem more prefill workers cannot fix; its SLA lever
                # is the queue depth above.
                s.queue_depth += prefill_q
            else:
                s.ttft_p90 = quantile_from_states(
                    stage_states, "llm_ttft_seconds", 0.90)
                s.itl_p90 = quantile_from_states(
                    stage_states, "llm_inter_token_seconds", 0.90)
                # end-to-end SLO burn is serving-side pressure, same
                # attribution rule as ttft/itl above (more prefill
                # replicas can't fix a decode-side latency breach)
                s.slo_burn = dict(slo_burn)
                # rejected demand is serving-side pressure too: admission
                # and worker-queue sheds are absorbed by the decode fleet
                s.shed_rate = shed_rate
                s.admission_depth = admission_depth
            s.breaker_open = breaker_open_instances(stage_states, ids)
            out[pool] = s
        return out


def fake_signals(pool: str, **kw) -> PoolSignals:
    """Test/chaos helper: a PoolSignals with keyword overrides."""
    return PoolSignals(pool=pool, **kw)
