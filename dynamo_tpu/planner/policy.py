"""How the planner decides: policies + the damped decision engine.

Two pluggable policies turn a :class:`~.signals.PoolSignals` snapshot into a
raw replica proposal:

- :class:`LoadPolicy` — threshold + hysteresis on queue depth / batch
  occupancy / KV utilization. Scale-up triggers above the high-water marks,
  scale-down only when EVERY signal is below the (lower) low-water marks —
  the gap between the bands is the hysteresis that keeps a borderline load
  from flapping the fleet. Breaker-open instances do not count as capacity.
- :class:`SlaPolicy` — target TTFT and ITL. Required replicas are
  interpolated from a :class:`~.profile.ProfileTable` (how much concurrency
  one replica sustains within the targets, measured by the profile sweep);
  a measured p90 above target additionally forces at least one step up
  (NetKV's point: instance-count decisions must be metric-driven).

:class:`PlannerCore` wraps a policy with the production damping every real
autoscaler needs — per-pool min/max clamps, separate scale-up/scale-down
cooldowns, consecutive-agreement flap damping for scale-down, operator
overrides, pause — and emits one :class:`Decision` record per pool per
evaluation (held decisions included, with the suppression reason). The core
is synchronous and deterministic: tests feed it synthetic metric series and
a fake clock.
"""

from __future__ import annotations

import logging
import math
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from .signals import PoolSignals

log = logging.getLogger("dynamo_tpu.planner")

SCALE_UP, SCALE_DOWN, HOLD = "scale_up", "scale_down", "hold"


@dataclass
class Decision:
    """One pool's outcome for one evaluation — published to the store under
    ``planner/`` whether or not it actuates (dry-run publishes identically).
    """

    pool: str
    current: int                    # observed live replicas
    proposed: int                   # policy's raw proposal
    target: int                     # after override + clamps + damping
    action: str                     # scale_up | scale_down | hold
    reason: str                     # the policy's (or override's) rationale
    policy: str
    suppressed: Optional[str] = None  # cooldown|flap_damping|clamp|paused
    dry_run: bool = False
    seq: int = 0
    ts: float = 0.0
    signals: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "Decision":
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})


class LoadPolicy:
    """Threshold + hysteresis on queue depth / occupancy / KV utilization.

    Scale-up sizes the jump to the backlog: each extra replica is assumed to
    absorb one live replica's worth of slots, so a deep queue jumps several
    replicas at once instead of crawling up one per cooldown window.
    """

    name = "load"

    def __init__(self, queue_high: float = 1.0, queue_low: float = 0.0,
                 occupancy_high: float = 0.85, occupancy_low: float = 0.3,
                 kv_high: float = 0.9, kv_low: float = 0.5):
        self.queue_high = queue_high      # backlog per replica to scale up
        self.queue_low = queue_low        # total backlog to allow scale-down
        self.occupancy_high = occupancy_high
        self.occupancy_low = occupancy_low
        self.kv_high = kv_high
        # kv gets its own low-water mark like occupancy: gating scale-down
        # on kv < kv_high would oscillate right at the boundary (shrink
        # pushes utilization over kv_high -> immediate scale back up)
        self.kv_low = kv_low

    def propose(self, s: PoolSignals) -> Tuple[int, str]:
        healthy = max(s.healthy_replicas, 1)
        # rejected demand counts as backlog: shedding keeps the visible
        # queues bounded, so an overloaded-but-shedding fleet would read
        # as idle from queue depth alone (each shed/s ~ one waiting seq)
        backlog = s.queue_depth + s.shed_rate
        per_replica_q = backlog / healthy
        hot = []
        if per_replica_q > self.queue_high:
            hot.append(f"queue {s.queue_depth:.0f} + shed {s.shed_rate:.1f}/s "
                       f"(> {self.queue_high}/replica)")
        if s.occupancy > self.occupancy_high:
            hot.append(f"occupancy {s.occupancy:.2f} "
                       f"(> {self.occupancy_high})")
        if s.kv_utilization > self.kv_high:
            hot.append(f"kv {s.kv_utilization:.2f} (> {self.kv_high})")
        if s.unserved > 0:
            # scale-from-zero: requests arrived for a model nobody
            # serves — ANY unserved demand wakes the pool (there is no
            # queue to deepen and no occupancy to breach at 0 replicas)
            hot.append(f"{s.unserved:.0f} unserved request(s) "
                       f"(scale from zero)")
        if hot:
            slots_per_replica = (s.total_slots / s.replicas
                                 if s.replicas and s.total_slots else 1.0)
            backlog_steps = math.ceil(backlog / slots_per_replica) \
                if backlog else 0
            step = max(1, backlog_steps, s.breaker_open)
            return s.replicas + step, "; ".join(hot)
        cold = (s.queue_depth <= self.queue_low
                and s.occupancy < self.occupancy_low
                and s.kv_utilization < self.kv_low
                and s.breaker_open == 0
                and s.shed_rate <= 0.0)
        if cold:
            return s.replicas - 1, (
                f"idle: queue {s.queue_depth:.0f}, "
                f"occupancy {s.occupancy:.2f} (< {self.occupancy_low})")
        return s.replicas, "within band"


class SlaPolicy:
    """Target TTFT/ITL; required replicas interpolated from a profile table.

    ``capacity`` — the max concurrent sequences one replica sustains inside
    both targets — comes from the table once at construction; demand is the
    live concurrency (active slots + backlog). A measured p90 above target
    forces at least one step up even when the table says the demand fits
    (the table is a model; the histograms are the truth).
    """

    name = "sla"

    def __init__(self, table, ttft_target: float, itl_target: float,
                 headroom: float = 0.85):
        self.table = table
        self.ttft_target = ttft_target
        self.itl_target = itl_target
        cap = table.capacity_per_replica(ttft_target, itl_target)
        # headroom: plan for (cap * headroom) so the fleet is not
        # knife-edged at exactly the SLA boundary
        self.capacity = max(cap * headroom, 1e-9)

    def propose(self, s: PoolSignals) -> Tuple[int, str]:
        # shed_rate is REJECTED demand (req/s the fleet refused): without
        # it the SLA maths would size the fleet to only the traffic that
        # survived admission — overload would read as fitting capacity
        demand = s.active_slots + s.queue_depth + s.shed_rate + s.unserved
        need = max(1, math.ceil(demand / self.capacity))
        # breaker-open instances serve nothing: replace them
        need += s.breaker_open
        reason = (f"demand {demand:.0f} seqs (incl. shed "
                  f"{s.shed_rate:.1f}/s) / capacity "
                  f"{self.capacity:.1f} per replica -> {need}")
        if s.ttft_p90 is not None and s.ttft_p90 > self.ttft_target:
            need = max(need, s.replicas + 1)
            reason += (f"; ttft p90 {s.ttft_p90:.3f}s > "
                       f"{self.ttft_target:.3f}s")
        if s.itl_p90 is not None and s.itl_p90 > self.itl_target:
            need = max(need, s.replicas + 1)
            reason += (f"; itl p90 {s.itl_p90:.4f}s > "
                       f"{self.itl_target:.4f}s")
        return need, reason


class _PoolState:
    __slots__ = ("last_scale", "down_streak")

    def __init__(self) -> None:
        self.last_scale = float("-inf")  # ts of the last non-hold decision
        self.down_streak = 0             # consecutive below-current proposals


class PlannerCore:
    """The deterministic decision engine: policy proposal -> override ->
    clamps -> cooldown/flap damping -> :class:`Decision`.

    Bookkeeping (cooldowns, streaks, seq) advances identically in dry-run —
    "emits but does not actuate" means the decision STREAM is the same; only
    the connector call is skipped by the loop above.
    """

    def __init__(self, policy, min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_up: float = 30.0, cooldown_down: float = 120.0,
                 down_consensus: int = 3, dry_run: bool = False):
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError(f"bad clamp range [{min_replicas}, "
                             f"{max_replicas}]")
        self.policy = policy
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.cooldown_up = cooldown_up
        self.cooldown_down = cooldown_down
        self.down_consensus = max(down_consensus, 1)
        self.dry_run = dry_run
        self.paused = False
        self.overrides: Dict[str, int] = {}
        # per-pool clamp overrides (the fleet plane's per-model
        # min/max_replicas); a pool absent here uses the global clamps.
        # min 0 is legal per-pool: scale-to-zero is a fleet policy.
        self.pool_clamps: Dict[str, Tuple[int, int]] = {}
        self._pools: Dict[str, _PoolState] = {}
        self._seq = 0

    # ------------------------------------------------------------------
    def set_override(self, overrides: Dict[str, int], paused: bool) -> None:
        """Operator state from ``plannerctl`` (store-watched by the loop)."""
        self.overrides = dict(overrides)
        self.paused = paused

    def set_pool_clamps(self, clamps: Dict[str, Tuple[int, int]]) -> None:
        """Per-pool replica bounds (fleet registry records)."""
        for pool, (lo, hi) in clamps.items():
            if lo < 0 or hi < max(lo, 1):
                raise ValueError(f"bad clamp range [{lo}, {hi}] for "
                                 f"pool {pool!r}")
        self.pool_clamps = {p: (int(lo), int(hi))
                            for p, (lo, hi) in clamps.items()}

    def forget_pool(self, pool: str) -> None:
        """Drop a removed pool's damping state (fleet model removal)."""
        self._pools.pop(pool, None)
        self.pool_clamps.pop(pool, None)

    def _clamp(self, n: int, pool: Optional[str] = None) -> int:
        lo, hi = self.pool_clamps.get(pool,
                                      (self.min_replicas, self.max_replicas))
        return max(lo, min(hi, n))

    # ------------------------------------------------------------------
    def evaluate(self, signals: Dict[str, PoolSignals],
                 now: float) -> List[Decision]:
        decisions = []
        for pool, s in sorted(signals.items()):
            decisions.append(self._evaluate_pool(pool, s, now))
        return decisions

    def _evaluate_pool(self, pool: str, s: PoolSignals,
                       now: float) -> Decision:
        st = self._pools.setdefault(pool, _PoolState())
        self._seq += 1
        d = Decision(pool=pool, current=s.replicas, proposed=s.replicas,
                     target=s.replicas, action=HOLD, reason="",
                     policy=self.policy.name, dry_run=self.dry_run,
                     seq=self._seq, ts=now, signals=s.to_dict())
        if self.paused:
            d.reason = "planner paused by operator"
            d.suppressed = "paused"
            return d
        if pool in self.overrides:
            # operator override: authoritative, bypasses policy AND damping
            d.proposed = int(self.overrides[pool])
            d.target = self._clamp(d.proposed, pool)
            d.reason = f"operator override -> {d.proposed}"
            d.policy = "override"
            if d.target != d.proposed:
                d.suppressed = "clamp"
            d.action = (SCALE_UP if d.target > s.replicas
                        else SCALE_DOWN if d.target < s.replicas else HOLD)
            if d.action != HOLD:
                st.last_scale = now
                st.down_streak = 0
            return d

        proposed, reason = self.policy.propose(s)
        d.proposed = proposed
        d.reason = reason
        bounded = self._clamp(proposed, pool)
        clamped = bounded != proposed
        if bounded == s.replicas:
            d.target = bounded
            if clamped:
                d.suppressed = "clamp"
            st.down_streak = 0
            return d

        if bounded > s.replicas:
            st.down_streak = 0
            if now - st.last_scale < self.cooldown_up:
                d.suppressed = "cooldown"
                return d
            d.target = bounded
            d.action = SCALE_UP
            if clamped:
                d.suppressed = "clamp"
            st.last_scale = now
            return d

        # bounded < current: flap damping — scale-down only after
        # ``down_consensus`` consecutive agreeing evaluations AND the
        # (longer) down cooldown. Surrendering capacity is the risky
        # direction; one idle tick must never shrink the fleet.
        st.down_streak += 1
        if st.down_streak < self.down_consensus:
            d.suppressed = "flap_damping"
            return d
        if now - st.last_scale < self.cooldown_down:
            d.suppressed = "cooldown"
            return d
        d.target = bounded
        d.action = SCALE_DOWN
        if clamped:
            d.suppressed = "clamp"
        st.last_scale = now
        st.down_streak = 0
        return d
