"""The planner control loop: observe -> decide -> publish -> actuate.

Production shape:

- every evaluation's decisions (held ones included) are published under
  ``planner/{namespace}/decisions/{seq:010d}`` with a span, and the loop's
  rolling state under ``planner/{namespace}/state`` (lease-bound — the key
  doubles as the planner's liveness beacon);
- ``dyn_planner_*`` counters/gauges ride the same ``metrics_stage/``
  publish path workers use, so the aggregator and ``/metrics`` merge them
  cluster-wide with zero new plumbing;
- operator state (``plannerctl override/pause``) is watched live from
  ``planner/{namespace}/override``;
- dry-run evaluates, damps and publishes identically but never calls the
  connector;
- actuation failures are counted and re-tried naturally on the next tick
  (the decision engine's cooldown keeps that from thrashing).

Store layout::

    planner/{ns}/state              rolling state (lease-bound, JSON)
    planner/{ns}/decisions/{seq}    decision records (bounded ring)
    planner/{ns}/override           {"paused": bool, "pools": {pool: n}}
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..llm.metrics_aggregator import stage_key
from ..runtime.store_client import StoreError
from ..utils import tracing
from ..utils.prometheus import Registry
from .policy import HOLD, SCALE_DOWN, SCALE_UP, Decision, PlannerCore
from .signals import PoolSignals, SignalCollector

log = logging.getLogger("dynamo_tpu.planner")

PLANNER_COMPONENT = "planner"


def planner_prefix(namespace: str) -> str:
    return f"planner/{namespace}/"


def state_key(namespace: str) -> str:
    return planner_prefix(namespace) + "state"


def override_key(namespace: str) -> str:
    return planner_prefix(namespace) + "override"


def decisions_prefix(namespace: str) -> str:
    return planner_prefix(namespace) + "decisions/"


class PlannerMetrics:
    """``dyn_planner_*`` series on their own registry (published to the
    stage-metrics plane under component="planner")."""

    def __init__(self) -> None:
        r = Registry()
        self.registry = r
        self.evaluations = r.counter(
            "dyn_planner_evaluations_total",
            "Planner observe/decide cycles completed", ())
        self.decisions = r.counter(
            "dyn_planner_decisions_total",
            "Decisions by pool and action", ("pool", "action"))
        self.suppressed = r.counter(
            "dyn_planner_suppressed_total",
            "Proposals held back, by reason "
            "(cooldown/flap_damping/clamp/paused)", ("pool", "reason"))
        self.actuations = r.counter(
            "dyn_planner_actuations_total",
            "Connector applications by result", ("pool", "result"))
        self.target_replicas = r.gauge(
            "dyn_planner_target_replicas",
            "Planner's current desired replicas", ("pool",))
        self.observed_replicas = r.gauge(
            "dyn_planner_observed_replicas",
            "Live registered replicas at last observation", ("pool",))
        self.queue_depth = r.gauge(
            "dyn_planner_queue_depth",
            "Observed backlog at last observation", ("pool",))
        self.occupancy = r.gauge(
            "dyn_planner_occupancy",
            "Observed batch occupancy at last observation", ("pool",))
        self.dry_run = r.gauge(
            "dyn_planner_dry_run", "1 when decisions do not actuate", ())


@dataclass
class PlannerConfig:
    """Loop knobs. Every field maps to a ``DYN_PLANNER_*`` env var through
    the CLI's EnvDefaultsParser (see cli/planner.py and docs/planner.md)."""

    interval: float = 2.0               # seconds between evaluations
    min_replicas: int = 1
    max_replicas: int = 8
    cooldown_up: float = 30.0
    cooldown_down: float = 120.0
    down_consensus: int = 3             # agreeing ticks before scale-down
    dry_run: bool = False
    keep_decisions: int = 200           # decision-ring length in the store
    # run the SLO-burn brownout controller (utils/overload.py) on this
    # loop's already-collected signals, publishing level changes to the
    # store for every frontend/router to apply fleet-wide
    brownout: bool = False


class Planner:
    """The standing control loop. ``pools`` maps pool name -> component
    (e.g. ``{"decode": "backend", "prefill": "prefill"}``)."""

    def __init__(self, drt, namespace: str, pools: Dict[str, str],
                 policy, connector, config: Optional[PlannerConfig] = None,
                 fleet=None):
        self.drt = drt
        self.namespace = namespace
        self.pools = dict(pools)
        self.config = config or PlannerConfig()
        self.connector = connector
        # fleet mode (dynamo_tpu/fleet): the pool set follows the model
        # registry live, targets pass through the chip arbiter, and a
        # lease-bound status record is published per model each tick
        self.fleet = fleet
        self.core = PlannerCore(
            policy,
            min_replicas=self.config.min_replicas,
            max_replicas=self.config.max_replicas,
            cooldown_up=self.config.cooldown_up,
            cooldown_down=self.config.cooldown_down,
            down_consensus=self.config.down_consensus,
            dry_run=self.config.dry_run)
        self.collector = SignalCollector(drt.store, namespace, self.pools)
        self.brownout: Optional[object] = None
        if self.config.brownout:
            from ..utils.overload import BrownoutMonitor

            # the monitor's own SloMonitor goes unused — the planner feeds
            # the burn its signal collector already computed into apply()
            self.brownout = BrownoutMonitor(drt.store, namespace,
                                            lease=drt.lease)
        self.metrics = PlannerMetrics()
        self.metrics.dry_run.set(value=1.0 if self.config.dry_run else 0.0)
        self.decisions_log: List[Decision] = []   # in-process tail
        self._task: Optional[asyncio.Task] = None
        self._last_signals: Dict[str, PoolSignals] = {}

    # ------------------------------------------------------------------
    async def start(self) -> "Planner":
        if self.fleet is not None:
            await self.fleet.start()
        await self._watch_override()
        await self._resume_seq()
        self._task = asyncio.create_task(self._run_loop())
        return self

    async def _resume_seq(self) -> None:
        """Continue the decision sequence where the previous planner run
        left it: a seq restart at 0 would interleave with the surviving
        ring entries and `plannerctl decisions` would show the dead run's
        tail as the newest."""
        try:
            items = await self.drt.store.get_prefix(
                decisions_prefix(self.namespace))
            if items:
                seqs = sorted(int(k.rsplit("/", 1)[1]) for k, _ in items)
                self.core._seq = seqs[-1]
                # ring entries whose paired delete was lost (e.g. to a
                # store outage) would otherwise leak forever
                keep = self.config.keep_decisions
                for s in (seqs[:-keep] if keep else seqs):
                    await self.drt.store.delete(
                        f"{decisions_prefix(self.namespace)}{s:010d}")
        except (StoreError, ValueError):
            log.warning("could not resume decision seq; starting fresh",
                        exc_info=True)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            # dynalint: ok(swallowed-exception) reaping our own cancelled
            # loop task; _run_loop logs its own failures with exc_info
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        close = getattr(self.connector, "close", None)
        if close is not None:
            await close()   # LocalConnector default: drain owned workers

    async def _watch_override(self) -> None:
        key = override_key(self.namespace)

        def apply_raw(value: Optional[bytes]) -> None:
            if not value:
                self.core.set_override({}, False)
                return
            try:
                d = json.loads(value.decode())
                pools = {str(k): int(v)
                         for k, v in (d.get("pools") or {}).items()}
                self.core.set_override(pools, bool(d.get("paused")))
                log.info("planner override applied: %s", d)
            except (ValueError, json.JSONDecodeError):
                log.warning("ignoring malformed planner override: %r", value)

        async def on_change(k: str, value: Optional[bytes], deleted: bool):
            if k == key:
                apply_raw(None if deleted else value)

        snapshot = await self.drt.store.watch_prefix(key, on_change)
        for k, value in snapshot:
            if k == key:
                apply_raw(value)

    # ------------------------------------------------------------------
    async def run_once(self, now: Optional[float] = None) -> List[Decision]:
        """One observe->decide->publish->actuate cycle (the loop's body;
        also the unit tests and chaos harness drive it directly)."""
        now = time.time() if now is None else now
        tracer = tracing.get_tracer()
        async with tracer.span("planner.evaluate"):
            if self.fleet is not None:
                await self.fleet.sync(self)
            signals = await self.collector.collect()
            self._last_signals = signals
            await self._brownout_tick(signals)
            decisions = self.core.evaluate(signals, now)
            if self.fleet is not None:
                decisions = self.fleet.arbitrate(decisions, signals)
                if not self.config.dry_run:
                    # same-swap-group chip handoffs become in-place
                    # weight swaps (decision pairs annotated so the
                    # connector's spawn/drain arithmetic skips them)
                    await self.fleet.actuate_swaps(decisions,
                                                   self.connector)
            # scale-ups actuate BEFORE scale-downs: a booting worker's
            # weight load overlaps the donor pool's drain, so a chip
            # handoff between models costs one boot, not boot + drain in
            # series (and scale-to-zero cold boots hide behind drains)
            order = {SCALE_UP: 0, HOLD: 1, SCALE_DOWN: 2}
            for d in sorted(decisions,
                            key=lambda d: order.get(d.action, 1)):
                await self._publish_decision(d)
                self._export(d, signals.get(d.pool))
                if d.action != HOLD and not d.dry_run:
                    await self._actuate(d)
            if self.fleet is not None and not self.config.dry_run:
                await self.fleet.publish_status(self.drt, decisions,
                                                signals)
        self.metrics.evaluations.inc()
        await self._publish_state(now)
        return decisions

    async def _brownout_tick(self, signals: Dict[str, PoolSignals]) -> None:
        """Step the brownout controller on the worst SLO burn the signal
        collector just observed; BrownoutMonitor.apply owns the gauge +
        store publication (lease-bound: a dead planner's brownout expires
        with its lease)."""
        if self.brownout is None:
            return
        burn = max((s.slo_pressure for s in signals.values()), default=0.0)
        await self.brownout.apply(burn)

    async def _actuate(self, d: Decision) -> None:
        tracer = tracing.get_tracer()
        try:
            async with tracer.span(f"planner.actuate:{d.action}",
                                   pool=d.pool, target=d.target):
                await self.connector.apply(d.pool, d.target, d)
            self.metrics.actuations.inc(d.pool, "ok")
            log.info("planner %s: %s %d -> %d (%s)", d.pool, d.action,
                     d.current, d.target, d.reason)
        except Exception:
            self.metrics.actuations.inc(d.pool, "error")
            log.exception("planner actuation failed (%s -> %d); will "
                          "re-evaluate next tick", d.pool, d.target)

    def _export(self, d: Decision, s: Optional[PoolSignals]) -> None:
        m = self.metrics
        m.decisions.inc(d.pool, d.action)
        if d.suppressed:
            m.suppressed.inc(d.pool, d.suppressed)
        m.target_replicas.set(d.pool, value=d.target)
        if s is not None:
            m.observed_replicas.set(d.pool, value=s.replicas)
            m.queue_depth.set(d.pool, value=s.queue_depth)
            m.occupancy.set(d.pool, value=s.occupancy)
        self.decisions_log.append(d)
        del self.decisions_log[:-self.config.keep_decisions]

    async def _publish_decision(self, d: Decision) -> None:
        key = f"{decisions_prefix(self.namespace)}{d.seq:010d}"
        try:
            await self.drt.store.put(
                key, json.dumps(d.to_dict()).encode())
            stale = d.seq - self.config.keep_decisions
            if stale > 0:
                await self.drt.store.delete(
                    f"{decisions_prefix(self.namespace)}{stale:010d}")
            if d.seq % (2 * self.config.keep_decisions) == 0:
                # occasional full sweep: per-publish deletes skipped during
                # store outages leave orphans behind the rolling window
                for k, _ in await self.drt.store.get_prefix(
                        decisions_prefix(self.namespace)):
                    try:
                        if int(k.rsplit("/", 1)[1]) <= stale:
                            await self.drt.store.delete(k)
                    except ValueError:
                        pass
        except StoreError:
            log.debug("decision publish skipped (store disconnected)")

    async def _publish_state(self, now: float) -> None:
        state = {
            "ts": now,
            "namespace": self.namespace,
            "policy": self.core.policy.name,
            "connector": getattr(self.connector, "name", "?"),
            "dry_run": self.config.dry_run,
            "paused": self.core.paused,
            "overrides": self.core.overrides,
            "clamps": [self.config.min_replicas, self.config.max_replicas],
            "fleet": self.fleet is not None,
            # which observer path fed the last signals: "region" (the
            # hierarchical aggregator tree's pre-merged records) or
            # "flat" (the per-worker prefix scan fallback)
            "signal_source": self.collector.last_source,
            "pools": {
                pool: {
                    "component": comp,
                    "replicas": s.replicas if s else None,
                    "occupancy": round(s.occupancy, 3) if s else None,
                    "queue_depth": s.queue_depth if s else None,
                    "kv_utilization":
                        round(s.kv_utilization, 3) if s else None,
                    "breaker_open": s.breaker_open if s else None,
                    "slo_burn": round(s.slo_pressure, 3) if s else None,
                }
                for pool, comp in self.pools.items()
                for s in (self._last_signals.get(pool),)
            },
        }
        try:
            await self.drt.store.put(
                state_key(self.namespace), json.dumps(state).encode(),
                lease=self.drt.lease)
            await self.drt.store.put(
                stage_key(self.namespace, PLANNER_COMPONENT,
                          self.drt.worker_id),
                json.dumps({"component": PLANNER_COMPONENT,
                            "metrics":
                                self.metrics.registry.state_dump()}).encode(),
                lease=self.drt.lease)
        except StoreError:
            log.debug("planner state publish skipped (store disconnected)")

    async def _run_loop(self) -> None:
        while True:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            except StoreError:
                log.warning("planner tick skipped: store disconnected")
            except Exception:
                log.exception("planner evaluation failed")
            await asyncio.sleep(self.config.interval)
