"""SLA-driven planner: the closed-loop autoscaler for prefill/decode fleets.

The planner is the standing control loop between the observability plane
(PR 1: cluster metrics aggregation — TTFT/ITL histograms, queue wait, batch
occupancy) and the safe-actuation plane (PR 2: graceful drain, lease-based
deregistration, circuit breaker). It observes per-pool signals, decides
replica counts under a pluggable policy, and actuates through a connector:

- :mod:`signals`     — what the planner sees (PoolSignals + collectors)
- :mod:`policy`      — how it decides (LoadPolicy, SlaPolicy)
- :mod:`profile`     — the SLA policy's profile table + the sweep that
  produces it (real engine or synthetic mock)
- :mod:`connectors`  — how decisions become replicas (local process spawn /
  graceful drain, Kubernetes CRD patch)
- :mod:`loop`        — the control loop itself (cooldown, flap damping,
  clamps, dry-run, store publishing, dyn_planner_* metrics)

Reference capability: the architecture's "Planner" box ("watches load and
adds/removes prefill and decode workers at runtime") — envisioned in the
reference docs, implemented here.
"""

from .connectors import KubeConnector, LocalConnector, NullConnector
from .loop import Planner, PlannerConfig, decisions_prefix, planner_prefix
from .policy import Decision, LoadPolicy, PlannerCore, SlaPolicy
from .profile import ProfileTable, SyntheticCore, run_profile
from .signals import PoolSignals, SignalCollector

__all__ = [
    "Decision", "KubeConnector", "LoadPolicy", "LocalConnector",
    "NullConnector", "Planner", "PlannerConfig", "PlannerCore",
    "PoolSignals", "ProfileTable", "SignalCollector", "SlaPolicy",
    "SyntheticCore", "decisions_prefix", "planner_prefix", "run_profile",
]
