"""Profile sweep: measure (batch, seq-len) -> TTFT/ITL for the SLA policy.

The SLA policy answers "how many replicas does this demand need?" with a
profile table: per (batch, seq_len) point, the measured time-to-first-token
and inter-token latency of ONE replica. The sweep drives anything with the
EngineCore submit/step surface — the real JAX engine on an accelerator, or
:class:`SyntheticCore` (a deterministic CPU mock with a virtual clock) so
the table format, interpolation and policy wiring are testable everywhere.

Table format (JSON, ``--out profile.json``)::

    {"engine": "synthetic", "platform": "cpu", "version": 1,
     "points": [{"batch": 1, "seq_len": 128,
                 "ttft_s": 0.11, "itl_s": 0.009, "tok_s": 111.0}, ...]}

``capacity_per_replica(ttft_target, itl_target)`` inverts the table: the
largest concurrency (batch) at which BOTH measured latencies stay inside
the targets, linearly interpolated between measured batch points and taken
conservatively (min) across seq-len rows.

    python -m dynamo_tpu.planner.profile --engine synthetic \
        --batches 1,2,4,8 --seq-lens 128,512 --out profile.json
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class ProfilePoint:
    batch: int
    seq_len: int
    ttft_s: float
    itl_s: float
    tok_s: float

    def to_dict(self) -> Dict[str, Any]:
        return {"batch": self.batch, "seq_len": self.seq_len,
                "ttft_s": round(self.ttft_s, 6),
                "itl_s": round(self.itl_s, 6),
                "tok_s": round(self.tok_s, 2)}


class ProfileTable:
    """Measured points + the interpolations the SLA policy needs."""

    def __init__(self, points: Sequence[ProfilePoint],
                 meta: Optional[Dict[str, Any]] = None):
        if not points:
            raise ValueError("profile table needs at least one point")
        self.points = sorted(points, key=lambda p: (p.seq_len, p.batch))
        self.meta = dict(meta or {})

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {**self.meta, "version": 1,
                "points": [p.to_dict() for p in self.points]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProfileTable":
        pts = [ProfilePoint(batch=int(p["batch"]),
                            seq_len=int(p["seq_len"]),
                            ttft_s=float(p["ttft_s"]),
                            itl_s=float(p["itl_s"]),
                            tok_s=float(p.get("tok_s", 0.0)))
               for p in d.get("points", [])]
        meta = {k: v for k, v in d.items() if k != "points"}
        return cls(pts, meta)

    @classmethod
    def load(cls, path: str) -> "ProfileTable":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1)

    # ------------------------------------------------------------------
    def seq_lens(self) -> List[int]:
        return sorted({p.seq_len for p in self.points})

    def _row(self, seq_len: int) -> List[ProfilePoint]:
        return [p for p in self.points if p.seq_len == seq_len]

    @staticmethod
    def _max_batch_within(row: List[ProfilePoint], ttft_target: float,
                          itl_target: float) -> float:
        """Largest (fractional) batch in this row with ttft AND itl inside
        the targets, linearly interpolated between measured batch points.
        0 when even batch=min violates; the last measured batch when even
        it fits (the table can't see beyond its own sweep)."""
        if not row:
            return 0.0
        row = sorted(row, key=lambda p: p.batch)

        def viol(p: ProfilePoint) -> float:
            # worst relative overshoot across both targets (<= 1 fits)
            return max(p.ttft_s / ttft_target if ttft_target else 0.0,
                       p.itl_s / itl_target if itl_target else 0.0)

        prev = None
        for p in row:
            v = viol(p)
            if v > 1.0:
                if prev is None:
                    return 0.0
                pv = viol(prev)
                if v <= pv:          # non-monotonic noise: stop at prev
                    return float(prev.batch)
                # linear crossing between prev.batch and p.batch
                frac = (1.0 - pv) / (v - pv)
                return prev.batch + frac * (p.batch - prev.batch)
            prev = p
        return float(row[-1].batch)

    def capacity_per_replica(self, ttft_target: float, itl_target: float,
                             seq_len: Optional[int] = None) -> float:
        """Concurrent sequences one replica sustains inside both targets.
        Conservative: the minimum across seq-len rows (or the one row
        asked for). Never below 1 — a replica that can't make SLA at
        batch=1 still serves one sequence at a time."""
        lens = [seq_len] if seq_len is not None else self.seq_lens()
        caps = [self._max_batch_within(self._row(sl), ttft_target,
                                       itl_target) for sl in lens]
        return max(min(caps), 1.0)


# ---------------------------------------------------------------------------
# sweep harness
# ---------------------------------------------------------------------------
class SyntheticCore:
    """Deterministic EngineCore stand-in with a virtual clock: prefill costs
    ``ttft0 + a*seq_len + b*batch*seq_len`` seconds, each decode step costs
    ``itl0 + c*batch``. CPU-only, instant wall-clock — the profile sweep,
    table math and SLA policy are fully testable without an accelerator."""

    def __init__(self, max_batch: int, ttft0: float = 0.05,
                 a: float = 2e-4, b: float = 5e-5,
                 itl0: float = 0.008, c: float = 0.002):
        self.max_batch = max_batch
        self.ttft0, self.a, self.b = ttft0, a, b
        self.itl0, self.c = itl0, c
        self.now = 0.0                       # virtual seconds
        self._seqs: Dict[str, Dict[str, int]] = {}
        self._prefill_done = 0.0

    def clock(self) -> float:
        return self.now

    def submit(self, seq_id: str, request: Any) -> None:
        tokens = request["token_ids"] if isinstance(request, dict) \
            else request.token_ids
        stop = request["max_tokens"] if isinstance(request, dict) \
            else request.stop.max_tokens
        self._seqs[seq_id] = {"remaining": int(stop), "emitted": 0}
        seq_len = len(tokens)
        b = len(self._seqs)
        self._prefill_done = self.now + (
            self.ttft0 + self.a * seq_len + self.b * b * seq_len)

    def step(self) -> List[Any]:
        """One decode dispatch over the whole batch (first call finishes the
        prefill and emits the first tokens)."""
        if not self._seqs:
            return []
        if self._prefill_done > self.now:
            self.now = self._prefill_done
        else:
            self.now += self.itl0 + self.c * len(self._seqs)
        outs = []
        for sid, st in list(self._seqs.items()):
            st["remaining"] -= 1
            st["emitted"] += 1
            finished = st["remaining"] <= 0
            outs.append(_SynthOut(sid, "stop" if finished else None))
            if finished:
                del self._seqs[sid]
        return outs


class _SynthOut:
    __slots__ = ("seq_id", "finish")

    def __init__(self, seq_id: str, finish: Optional[str]):
        self.seq_id = seq_id
        self.finish = finish


def profile_core(core, batch: int, seq_len: int,
                 make_request: Callable[[int, int], Any],
                 clock: Callable[[], float],
                 tag: str = "prof") -> ProfilePoint:
    """Drive one (batch, seq_len) point through a submit/step core and
    measure TTFT (submit -> last first-token) and steady-state ITL."""
    t0 = clock()
    for i in range(batch):
        core.submit(f"{tag}{batch}x{seq_len}_{i}",
                    make_request(i, seq_len))
    done = 0
    first: Dict[str, float] = {}
    t_first = None
    post_tokens = 0
    total_tokens = 0
    while done < batch:
        outs = core.step()
        now = clock()
        counted = t_first is not None
        for so in outs:
            total_tokens += 1
            first.setdefault(so.seq_id, now - t0)
            if so.finish is not None:
                done += 1
        if counted:
            post_tokens += len(outs)
        elif len(first) == batch:
            t_first = now - t0
    wall = clock() - t0
    decode_wall = wall - t_first if t_first else 0.0
    itl = (decode_wall / (post_tokens / batch)
           if post_tokens and decode_wall > 0 else 0.0)
    ttfts = sorted(first.values())
    return ProfilePoint(
        batch=batch, seq_len=seq_len,
        ttft_s=ttfts[len(ttfts) // 2],
        itl_s=itl,
        tok_s=(total_tokens / wall if wall > 0 else 0.0))


def run_profile(engine: str, batches: Sequence[int],
                seq_lens: Sequence[int], gen_tokens: int = 32,
                model: Optional[str] = None,
                synthetic_kw: Optional[Dict[str, float]] = None
                ) -> ProfileTable:
    """The sweep: one fresh core per (batch, seq_len) point (decode always
    dispatches at full engine width — a max-sized engine would measure
    padding, not batch-b latency; same reasoning as bench.py)."""
    points: List[ProfilePoint] = []
    meta: Dict[str, Any] = {"engine": engine}
    for seq_len in seq_lens:
        for b in batches:
            if engine == "synthetic":
                core = SyntheticCore(max_batch=b, **(synthetic_kw or {}))
                clock = core.clock

                def make_request(i: int, sl: int):
                    return {"token_ids": list(range(1, sl + 1)),
                            "max_tokens": gen_tokens}
            else:
                import time

                from ..engine.engine import EngineCore, JaxEngineConfig
                from ..llm.protocols.common import (BackendInput,
                                                    StopConditions)
                from ..models import llama

                mcfg = llama.preset(model or "tiny-byte",
                                    max_position=max(2 * seq_len, 256))
                core = EngineCore(JaxEngineConfig(
                    model=mcfg, tp=1, page_size=64, max_batch=b,
                    max_context=max(2 * seq_len, 256),
                    prefill_chunk=min(512, seq_len)))
                clock = time.monotonic
                mod = mcfg.vocab_size - 1

                def make_request(i: int, sl: int):
                    return BackendInput(
                        token_ids=[(p * 31 + i * 7) % mod + 1
                                   for p in range(sl)],
                        stop=StopConditions(max_tokens=gen_tokens,
                                            ignore_eos=True))
                meta["platform"] = "jax"
                meta["model"] = model or "tiny-byte"
                # warm round: compile outside the measurement
                profile_core(core, b, seq_len, make_request, clock,
                             tag="warm")
            points.append(profile_core(core, b, seq_len, make_request,
                                       clock))
            log.info("profiled %s", points[-1].to_dict())
    return ProfileTable(points, meta)


def main(argv=None) -> int:
    from ..utils.dynconfig import EnvDefaultsParser

    ap = EnvDefaultsParser(prog="dynamo-planner-profile")
    ap.add_argument("--engine", choices=("synthetic", "jax"),
                    default="synthetic")
    ap.add_argument("--model", default=None,
                    help="models.llama preset name (jax engine)")
    ap.add_argument("--batches", default="1,2,4,8")
    ap.add_argument("--seq-lens", default="128,512")
    ap.add_argument("--gen-tokens", type=int, default=32)
    ap.add_argument("--out", default="profile.json")
    args = ap.parse_args(argv)
    table = run_profile(
        args.engine,
        [int(x) for x in args.batches.split(",") if x],
        [int(x) for x in args.seq_lens.split(",") if x],
        gen_tokens=args.gen_tokens, model=args.model)
    table.save(args.out)
    print(f"profile: {len(table.points)} points -> {args.out}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
