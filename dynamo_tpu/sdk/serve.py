"""Local multi-process orchestrator for @service graphs.

    serve = LocalServe("examples.hello_world:Frontend",
                       config={"Backend": {...}}, platform="cpu")
    serve.start()      # store + one process per service worker, TPU chips
    ...                # allocated per service `resources={"tpu": n}`
    serve.stop()

The orchestrator: (1) starts a dynstore coordination server unless given an
existing one, (2) walks the graph (links + depends) from the entry service,
(3) allocates accelerator chips per worker, (4) spawns each worker as
``python -m dynamo_tpu.sdk.serve_child`` with the per-service YAML config
injected through the DYN_SERVICE_CONFIG env JSON, and (5) waits for every
worker's READY line.

Reference capability: deploy/dynamo/sdk/cli/serving.py:120-251 (circus
watchers per service + GPU allocator + env-injected config).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Type

from .allocator import TpuAllocator
from .service import SERVICE_CONFIG_ENV, collect_graph
from .serve_child import READY_MARKER, load_class


class LocalServe:
    def __init__(self, entry: str, config: Optional[Dict[str, Any]] = None,
                 store: Optional[str] = None, platform: str = "auto",
                 total_chips: int = 4, cwd: Optional[str] = None):
        self.entry_spec = entry
        self.entry: Type = load_class(entry) if isinstance(entry, str) else entry
        self.config = dict(config or {})
        self.store = store
        self.platform = platform
        self.total_chips = total_chips
        self.cwd = cwd or os.getcwd()
        self.procs: List[subprocess.Popen] = []
        self._store_proc: Optional[subprocess.Popen] = None

    # ------------------------------------------------------------------
    def _ensure_store(self) -> str:
        if self.store:
            return self.store
        # free port
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        self._store_proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.runtime.store_server",
             "--port", str(port)],
            cwd=self.cwd, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        self.store = f"127.0.0.1:{port}"
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                probe = socket.create_connection(("127.0.0.1", port), 0.5)
                probe.close()
                return self.store
            except OSError:
                time.sleep(0.1)
        raise RuntimeError("dynstore failed to start")

    # ------------------------------------------------------------------
    def start(self, timeout: float = 120.0) -> "LocalServe":
        store = self._ensure_store()
        platform = self.platform
        if platform == "auto":
            platform = "tpu" if os.environ.get("TPU_NAME") else "cpu"
        alloc = TpuAllocator(self.total_chips, platform)
        services = collect_graph(self.entry)

        waiters = []
        try:
            self._spawn_all(services, alloc, store, waiters)
        except BaseException:
            self.stop()
            raise
        return self._await_ready(waiters, timeout)

    def _spawn_all(self, services, alloc, store, waiters) -> None:
        for cls in services:
            spec = cls._dynamo_spec
            if not (spec.endpoints or spec.on_start or spec.dependencies):
                continue   # pure grouping node (a graph entry like AggGraph)
            mod = cls.__module__
            section = self.config.get(cls.__name__, {})
            workers = int(section.get("workers", spec.workers))
            chips = int(section.get("resources", {}).get(
                "tpu", spec.resources.get("tpu", 0)))
            for w in range(workers):
                env = dict(os.environ)
                env[SERVICE_CONFIG_ENV] = json.dumps(self.config)
                env.update(alloc.allocate(chips, service=spec.name))
                p = subprocess.Popen(
                    [sys.executable, "-m", "dynamo_tpu.sdk.serve_child",
                     f"{mod}:{cls.__name__}", "--store", store],
                    cwd=self.cwd, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True)
                self.procs.append(p)
                waiters.append((spec.name, p))

    def _await_ready(self, waiters, timeout: float) -> "LocalServe":
        # wait for every worker's READY marker (reader threads keep pipes
        # drained afterwards so children never block on stdout)
        ready = {}
        lock = threading.Lock()

        def pump(name, p):
            for line in p.stdout:
                if READY_MARKER in line:
                    with lock:
                        ready[p] = True
                sys.stderr.write(f"[{name}] {line}")

        threads = [threading.Thread(target=pump, args=(n, p), daemon=True)
                   for n, p in waiters]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with lock:
                if len(ready) == len(waiters):
                    return self
            dead = [p for _, p in waiters if p.poll() is not None]
            if dead:
                self.stop()
                raise RuntimeError(
                    f"{len(dead)} service worker(s) exited during bring-up")
            time.sleep(0.1)
        self.stop()
        raise RuntimeError("serve bring-up timed out")

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()
        if self._store_proc is not None:
            self._store_proc.terminate()
            self._store_proc = None
