"""TPU slice allocator for the local `serve` orchestrator.

Assigns each service worker a disjoint, CONTIGUOUS set of TPU chips (the
reference's GPU allocator assigns CUDA_VISIBLE_DEVICES ranges,
deploy/dynamo/sdk/cli/allocator.py:35-101). Contiguity matters on TPU:
neighboring chips share ICI links, so a slice split across the board pays
DCN-class latency for what should be ICI collectives. On TPU VMs chip
visibility is controlled with ``TPU_VISIBLE_DEVICES``; for hermetic CPU
runs the same request becomes a virtual device count
(``--xla_force_host_platform_device_count``).

Beyond the round-4 bump allocator: per-allocation release (a restarted
worker's chips return to the pool instead of leaking until ``release_all``),
best-fit placement over free runs (limits fragmentation under churn), and
per-service placement tracking (``placements()`` — the disjointness
invariant is inspectable, not implicit).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


class AllocationError(RuntimeError):
    pass


@dataclass
class Allocation:
    """One worker's chip grant. ``env`` is what the worker process gets."""

    service: str
    chips: List[int]
    env: Dict[str, str] = field(default_factory=dict)


class TpuAllocator:
    """Hands out contiguous chip ranges; ``platform='cpu'`` hands out
    virtual device counts instead (no exclusivity needed)."""

    def __init__(self, total_chips: int = 4, platform: str = "tpu"):
        self.total = total_chips
        self.platform = platform
        self._free = set(range(total_chips))
        self._allocs: List[Allocation] = []

    # ------------------------------------------------------------------
    def _free_runs(self) -> List[List[int]]:
        """Maximal runs of contiguous free chips, ascending."""
        runs: List[List[int]] = []
        cur: List[int] = []
        for c in sorted(self._free):
            if cur and c == cur[-1] + 1:
                cur.append(c)
            else:
                if cur:
                    runs.append(cur)
                cur = [c]
        if cur:
            runs.append(cur)
        return runs

    def allocate(self, n_chips: int, service: str = "") -> Dict[str, str]:
        """Env for a worker needing ``n_chips`` accelerator chips (0 => a
        pure-CPU service; it must not initialize the TPU)."""
        return self.allocate_handle(n_chips, service=service).env

    def allocate_handle(self, n_chips: int, service: str = "") -> Allocation:
        """Like :meth:`allocate` but returns the :class:`Allocation` so the
        caller can :meth:`release` it individually (worker restart)."""
        if n_chips <= 0:
            return Allocation(service, [], {"JAX_PLATFORMS": "cpu"})
        if self.platform == "cpu":
            return Allocation(service, [], {
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": ("--xla_force_host_platform_device_count="
                              f"{n_chips}"),
            })
        # best-fit: the smallest contiguous run that fits, so large future
        # requests keep a chance at the big runs
        candidates = [r for r in self._free_runs() if len(r) >= n_chips]
        if not candidates:
            raise AllocationError(
                f"need {n_chips} contiguous chips for {service or 'worker'}; "
                f"free runs: {[len(r) for r in self._free_runs()]} "
                f"of {self.total} total")
        run = min(candidates, key=len)
        chips = run[:n_chips]
        self._free.difference_update(chips)
        alloc = Allocation(service, chips, {
            "TPU_VISIBLE_DEVICES": ",".join(map(str, chips))})
        self._allocs.append(alloc)
        return alloc

    def release(self, alloc: Allocation) -> None:
        """Return one worker's chips to the pool (restart path). Identity
        match, not equality: a re-grant of the same chips produces an
        EQUAL dataclass, and releasing a stale handle twice must not free
        the new owner's live grant."""
        for i, a in enumerate(self._allocs):
            if a is alloc:
                del self._allocs[i]
                self._free.update(alloc.chips)
                return

    def release_all(self) -> None:
        self._free = set(range(self.total))
        self._allocs.clear()

    def placements(self) -> Dict[str, List[List[int]]]:
        """service -> list of chip sets currently granted (disjointness and
        contiguity are directly checkable by callers/tests)."""
        out: Dict[str, List[List[int]]] = {}
        for a in self._allocs:
            out.setdefault(a.service or "worker", []).append(list(a.chips))
        return out
