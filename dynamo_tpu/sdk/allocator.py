"""TPU slice allocator for the local `serve` orchestrator.

Assigns each service worker a disjoint set of TPU chips (the reference's GPU
allocator assigns CUDA_VISIBLE_DEVICES ranges, deploy/dynamo/sdk/cli/
allocator.py:35-101). On TPU VMs chip visibility is controlled with
``TPU_VISIBLE_DEVICES``; for hermetic CPU runs the same request becomes a
virtual device count (``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

from typing import Dict, List, Optional


class AllocationError(RuntimeError):
    pass


class TpuAllocator:
    """Hands out chip index ranges; ``platform='cpu'`` hands out virtual
    device counts instead (no exclusivity needed)."""

    def __init__(self, total_chips: int = 4, platform: str = "tpu"):
        self.total = total_chips
        self.platform = platform
        self._next = 0

    def allocate(self, n_chips: int) -> Dict[str, str]:
        """Env for a worker needing ``n_chips`` accelerator chips (0 => a
        pure-CPU service; it must not initialize the TPU)."""
        if n_chips <= 0:
            return {"JAX_PLATFORMS": "cpu"}
        if self.platform == "cpu":
            return {
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": ("--xla_force_host_platform_device_count="
                              f"{n_chips}"),
            }
        if self._next + n_chips > self.total:
            raise AllocationError(
                f"need {n_chips} chips, only "
                f"{self.total - self._next}/{self.total} left")
        chips = list(range(self._next, self._next + n_chips))
        self._next += n_chips
        return {"TPU_VISIBLE_DEVICES": ",".join(map(str, chips))}

    def release_all(self) -> None:
        self._next = 0
