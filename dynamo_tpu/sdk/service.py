"""Service model: decorators, dependency descriptors, graph links.

A ``@service``-decorated class carries a :class:`ServiceSpec` describing its
namespace, component name, resource needs and endpoints. ``depends()``
attributes resolve to live :class:`~dynamo_tpu.runtime.component.Client`
wrappers at bring-up. ``.link()`` records graph edges so the orchestrator
can discover every service reachable from the entry point.

Reference capability: deploy/dynamo/sdk/src/dynamo/sdk/lib/service.py:32-120
(@service -> DynamoService), decorators.py:26-101 (@dynamo_endpoint),
dependency.py (depends/DynamoClient), LinkedServices (.link()).
"""

from __future__ import annotations

import inspect
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type

SERVICE_CONFIG_ENV = "DYN_SERVICE_CONFIG"


@dataclass
class ServiceSpec:
    """Deployment metadata attached to a @service class."""

    namespace: str = "dynamo"
    name: str = ""                       # component name (class name default)
    resources: Dict[str, Any] = field(default_factory=dict)  # {"tpu": n}
    workers: int = 1
    links: List[Type] = field(default_factory=list)
    endpoints: Dict[str, str] = field(default_factory=dict)  # name -> attr
    on_start: List[str] = field(default_factory=list)        # hook attrs
    dependencies: Dict[str, "Dependency"] = field(default_factory=dict)


@dataclass
class Dependency:
    """Declared edge to another service: resolves to a client at runtime."""

    target: Type
    endpoint: str = "generate"

    def __set_name__(self, owner, name):
        self._attr = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        resolved = getattr(obj, "_dyn_clients", {}).get(self._attr)
        if resolved is None:
            raise RuntimeError(
                f"dependency {self._attr!r} not wired — the service is not "
                f"running under `serve` (or bring-up has not finished)")
        return resolved


class BoundClient:
    """What a ``depends()`` attribute resolves to: endpoint-call sugar over
    the runtime Client (``self.backend.generate(req)`` streams results)."""

    def __init__(self, client, endpoint: str):
        self.client = client
        self.endpoint = endpoint

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(request, context=None, **kw):
            return self.client.generate(request, context=context, **kw)

        # any attribute name is the endpoint method (the client was built
        # for spec.endpoint already); name kept for call-site readability
        return call


def depends(target: Type, endpoint: str = "generate") -> Dependency:
    return Dependency(target, endpoint)


def dynamo_endpoint(name: Optional[str] = None) -> Callable:
    """Mark an ``async def (self, request, ctx)`` generator as a served
    endpoint."""

    def wrap(fn):
        fn._dynamo_endpoint = name or fn.__name__
        return fn

    return wrap


def async_on_start(fn):
    """Mark an ``async def (self)`` to run after the runtime is connected
    and dependencies are wired, before endpoints serve."""
    fn._dynamo_on_start = True
    return fn


def service(namespace: str = "dynamo", name: Optional[str] = None,
            resources: Optional[Dict[str, Any]] = None,
            workers: int = 1) -> Callable[[Type], Type]:
    """Class decorator: attach a ServiceSpec and a .link() graph builder."""

    def wrap(cls: Type) -> Type:
        spec = ServiceSpec(namespace=namespace,
                           name=(name or cls.__name__.lower()),
                           resources=dict(resources or {}),
                           workers=workers)
        for attr, val in list(vars(cls).items()):
            if callable(val) and hasattr(val, "_dynamo_endpoint"):
                spec.endpoints[val._dynamo_endpoint] = attr
            if callable(val) and getattr(val, "_dynamo_on_start", False):
                spec.on_start.append(attr)
            if isinstance(val, Dependency):
                spec.dependencies[attr] = val
        cls._dynamo_spec = spec

        @classmethod
        def link(kls, other: Type) -> Type:
            kls._dynamo_spec.links.append(other)
            return kls

        cls.link = link
        return cls

    return wrap


def collect_graph(entry: Type) -> List[Type]:
    """Every service reachable from ``entry`` via links + dependencies, in
    dependency-first order (leaves start before the services calling them)."""
    seen: Dict[Type, None] = {}
    visiting: set = set()

    def visit(cls: Type):
        if cls in seen or cls in visiting:
            return   # visiting-guard: cyclic links must not recurse forever
        visiting.add(cls)
        spec: ServiceSpec = cls._dynamo_spec
        for dep in spec.dependencies.values():
            visit(dep.target)
        for other in spec.links:
            visit(other)
        visiting.discard(cls)
        seen[cls] = None

    visit(entry)
    return list(seen)


class ServiceConfig:
    """Per-service config injected by `serve` (YAML section -> env JSON),
    readable inside the service process:

        cfg = ServiceConfig.load()          # whole process config
        port = cfg.get("Frontend", {}).get("port", 8080)

    Reference capability: sdk/lib/config.py (DYNAMO_SERVICE_CONFIG env).
    """

    def __init__(self, data: Dict[str, Any]):
        self.data = data

    @classmethod
    def load(cls) -> "ServiceConfig":
        raw = os.environ.get(SERVICE_CONFIG_ENV, "")
        if raw:
            return cls(json.loads(raw))
        # k8s path: config mounted as a file (deploy/manifests.py ConfigMap)
        path = os.environ.get(SERVICE_CONFIG_ENV + "_FILE", "")
        if path and os.path.exists(path):
            with open(path) as f:
                return cls(json.load(f))
        return cls({})

    def get(self, section: str, default: Any = None) -> Any:
        return self.data.get(section, default if default is not None else {})

    def for_service(self, cls_or_name) -> Dict[str, Any]:
        name = (cls_or_name if isinstance(cls_or_name, str)
                else cls_or_name.__name__)
        return dict(self.data.get(name, {}))
