"""Deployment SDK: declare serving graphs as decorated Python classes.

    from dynamo_tpu.sdk import service, dynamo_endpoint, depends

    @service(namespace="dynamo")
    class Backend:
        @dynamo_endpoint()
        async def generate(self, request, ctx):
            yield ...

    @service(namespace="dynamo", resources={"tpu": 0})
    class Frontend:
        backend = depends(Backend)

        @dynamo_endpoint()
        async def generate(self, request, ctx):
            async for x in self.backend.generate(request):
                yield x

    Frontend.link(Backend)   # deployable graph

Run locally with ``python -m dynamo_tpu.cli.serve module:Frontend``.

Reference capability: deploy/dynamo/sdk (service.py:32-120, decorators.py:
26-101, dependency.py) re-expressed without the BentoML dependency.
"""

from .service import (ServiceConfig, depends, dynamo_endpoint, async_on_start,
                      service)

__all__ = ["service", "dynamo_endpoint", "depends", "async_on_start",
           "ServiceConfig"]
