"""Per-worker child entry for the `serve` orchestrator.

    python -m dynamo_tpu.sdk.serve_child pkg.module:ServiceClass \
        --store host:port

Instantiates the @service class, connects the distributed runtime, wires
``depends()`` clients, runs @async_on_start hooks, then serves every
@dynamo_endpoint on the service's component. Prints a READY line on stdout
once all endpoints are registered (the orchestrator gates on it).

Reference capability: deploy/dynamo/sdk/cli/serve_dynamo.py:96-190.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import logging
import sys
from typing import Type

from ..runtime.component import DistributedRuntime
from ..utils.logging_ext import init_logging
from .service import BoundClient, ServiceConfig, ServiceSpec

log = logging.getLogger("dynamo_tpu.sdk.child")

READY_MARKER = "DYNAMO_SERVICE_READY"


def load_class(spec: str) -> Type:
    mod_name, _, cls_name = spec.partition(":")
    mod = importlib.import_module(mod_name)
    cls = getattr(mod, cls_name)
    if not hasattr(cls, "_dynamo_spec"):
        raise SystemExit(f"{spec} is not a @service class")
    return cls


async def run_service(cls: Type, store: str,
                      ready_event=None) -> None:
    spec: ServiceSpec = cls._dynamo_spec
    host, port = store.split(":")
    drt = await DistributedRuntime(store_host=host,
                                   store_port=int(port)).connect()
    obj = cls()
    obj.runtime = drt
    obj.config = ServiceConfig.load().for_service(cls)
    obj._dyn_clients = {}
    for attr, dep in spec.dependencies.items():
        tspec: ServiceSpec = dep.target._dynamo_spec
        client = await drt.namespace(tspec.namespace) \
            .component(tspec.name).endpoint(dep.endpoint).client().start()
        obj._dyn_clients[attr] = BoundClient(client, dep.endpoint)
    for hook in spec.on_start:
        await getattr(obj, hook)()
    component = drt.namespace(spec.namespace).component(spec.name)
    for ep_name, attr in spec.endpoints.items():
        await component.endpoint(ep_name).serve(getattr(obj, attr))
    print(f"{READY_MARKER} {spec.name} worker={drt.worker_id:x}", flush=True)
    if ready_event is not None:
        ready_event.set()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await drt.close()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser("dynamo-serve-child")
    ap.add_argument("service", help="pkg.module:ServiceClass")
    ap.add_argument("--store", default="127.0.0.1:4222")
    args = ap.parse_args(argv)
    from ..utils.hostmesh import honor_jax_platforms_env

    init_logging()
    honor_jax_platforms_env()
    sys.path.insert(0, ".")
    # artifact-deployed graphs: the operator extracts the bundle and hands
    # its path down (deploy/artifacts.py)
    import os

    apath = os.environ.get("DYNAMO_ARTIFACT_PATH")
    if apath:
        # appended, matching load_entry: bundles must not shadow framework
        # or stdlib imports (and the worker must resolve the same code the
        # operator resolved)
        sys.path.append(apath)
    asyncio.run(run_service(load_class(args.service), args.store))


if __name__ == "__main__":
    main()
