"""Always-on flight recorder: per-process black-box rings.

Every process keeps bounded, high-resolution rings of what it saw over
the last seconds: every finished span (including ones head sampling
dropped from the store export), engine dispatch/step timings, queue
depths and slot-gate waits, transfer-bandwidth EWMA snapshots,
store-client health transitions, and a tail of recent log records.
Recording is a deque append — cheap enough to leave on in production.
The rings exist so a watchdog stall, a torn stream, or a breaker trip
can dump exactly what this process saw around the event into a
coordinated incident bundle (obs/incidents.py) instead of hoping the
interesting trace survived head sampling.

The recorder also keeps **heartbeats**: named liveness records the hang
watchdog (obs/watchdog.py) polls. A heartbeat tracks in-flight depth,
last-activity time, and an EWMA of completed-unit durations, so "a
decode dispatch exceeding N× its EWMA step time" and "a transfer stream
with no layer progress" are one uniform check.

``DYN_FLIGHTREC=0`` disables recording (the API stays a cheap no-op).
Ring capacities: ``DYN_FLIGHTREC_SPANS`` / ``DYN_FLIGHTREC_EVENTS`` /
``DYN_FLIGHTREC_LOGTAIL``. Evictions are counted per ring
(``dyn_flightrec_evicted_total{ring}``) so a bundle consumer can tell a
quiet window from a ring too small to cover it.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils.prometheus import stage_metrics

log = logging.getLogger("dynamo_tpu.obs.flightrec")

#: heartbeat table bound — transient heartbeats (per-stream) whose owner
#: forgot ``hb_end`` must not grow the table forever
MAX_HEARTBEATS = 256

#: EWMA weight of a new completed-unit duration observation
EWMA_ALPHA = 0.2


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r", name, raw)
        return default


class Ring:
    """Bounded drop-oldest ring with eviction accounting. Appends may
    come from the engine thread: ``deque.append`` is atomic and the
    eviction counter tolerates a rare racy undercount."""

    __slots__ = ("name", "capacity", "_items", "evicted")

    def __init__(self, name: str, capacity: int):
        self.name = name
        self.capacity = max(1, capacity)
        self._items: deque = deque(maxlen=self.capacity)
        self.evicted = 0

    def append(self, item: Any) -> None:
        if len(self._items) >= self.capacity:
            self.evicted += 1
            stage_metrics().flightrec_evicted.inc(self.name)
        self._items.append(item)

    def snapshot(self) -> List[Any]:
        return list(self._items)

    def __len__(self) -> int:
        return len(self._items)


class Heartbeat:
    """Liveness record for one wedgeable activity. ``depth`` counts
    in-flight units (overlapping decode dispatches pipeline); any unit
    completing or progressing resets ``last_activity`` — a stall is
    "work in flight, nothing moved for too long", judged against an
    explicit ``budget`` (drain grace, transfer no-progress bound) or
    the watchdog's multiple of the completed-unit EWMA."""

    __slots__ = ("name", "stall", "budget", "trace_id", "depth", "ewma",
                 "progress", "fired", "last_activity", "last_wall")

    def __init__(self, name: str, stall: Optional[str] = None,
                 budget: Optional[float] = None,
                 trace_id: Optional[str] = None):
        self.name = name
        self.stall = stall or name
        self.budget = budget
        self.trace_id = trace_id
        self.depth = 0
        self.ewma = 0.0
        self.progress = 0
        self.fired = False
        self.last_activity = time.monotonic()
        self.last_wall = time.time()

    def _touch(self) -> None:
        self.last_activity = time.monotonic()
        self.last_wall = time.time()
        self.fired = False

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "stall": self.stall,
                "depth": self.depth, "ewma": self.ewma,
                "progress": self.progress, "budget": self.budget,
                "fired": self.fired,
                "idle_s": time.monotonic() - self.last_activity}


class FlightRecorder:
    """The per-process black box: three rings + the heartbeat table."""

    def __init__(self, component: str = "proc",
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("DYN_FLIGHTREC", "1") \
                not in ("0", "false")
        self.component = component
        self.enabled = enabled
        self.spans = Ring("spans", _env_int("DYN_FLIGHTREC_SPANS", 2048))
        self.events = Ring("events", _env_int("DYN_FLIGHTREC_EVENTS", 4096))
        self.logtail = Ring("logtail",
                            _env_int("DYN_FLIGHTREC_LOGTAIL", 256))
        self.heartbeats: Dict[str, Heartbeat] = {}
        self._hb_lock = threading.Lock()
        self._log_handler: Optional[logging.Handler] = None
        self._attached_tracers: List[Any] = []

    # -- rings --------------------------------------------------------------
    def on_span(self, span) -> None:
        """Tracer sink: EVERY finished span lands here, including the
        ones trace-id head sampling keeps out of the store export."""
        if self.enabled:
            self.spans.append(span)

    def note(self, kind: str, **fields: Any) -> None:
        """Append one structured event (engine step, gate wait, transfer
        EWMA snapshot, store health transition, ...)."""
        if self.enabled:
            fields["t"] = time.time()
            fields["kind"] = kind
            self.events.append(fields)

    def attach(self, tracer) -> None:
        """Mirror a tracer's finished spans into the span ring."""
        if tracer in self._attached_tracers:
            return
        tracer.add_sink(self.on_span)
        self._attached_tracers.append(tracer)

    def attach_logging(self, level: int = logging.INFO) -> None:
        if self._log_handler is not None:
            return
        self._log_handler = _LogTailHandler(self.logtail)
        self._log_handler.setLevel(level)
        logging.getLogger().addHandler(self._log_handler)

    def detach(self) -> None:
        for tracer in self._attached_tracers:
            tracer.remove_sink(self.on_span)
        self._attached_tracers.clear()
        if self._log_handler is not None:
            logging.getLogger().removeHandler(self._log_handler)
            self._log_handler = None

    # -- heartbeats ---------------------------------------------------------
    def hb(self, name: str, stall: Optional[str] = None,
           budget: Optional[float] = None,
           trace_id: Optional[str] = None) -> Heartbeat:
        with self._hb_lock:
            h = self.heartbeats.get(name)
            if h is None:
                if len(self.heartbeats) >= MAX_HEARTBEATS:
                    # shed an idle transient first; a busy one only if
                    # the table is saturated with busy entries
                    for key, old in self.heartbeats.items():
                        if old.depth <= 0:
                            del self.heartbeats[key]
                            break
                    else:
                        self.heartbeats.pop(next(iter(self.heartbeats)))
                h = Heartbeat(name, stall=stall, budget=budget,
                              trace_id=trace_id)
                self.heartbeats[name] = h
            return h

    def hb_begin(self, name: str, stall: Optional[str] = None,
                 budget: Optional[float] = None,
                 trace_id: Optional[str] = None) -> None:
        if not self.enabled:
            return
        h = self.hb(name, stall=stall, budget=budget, trace_id=trace_id)
        h.depth += 1
        if budget is not None:
            h.budget = budget
        h._touch()

    def hb_done(self, name: str, elapsed: Optional[float] = None) -> None:
        if not self.enabled:
            return
        h = self.heartbeats.get(name)
        if h is None:
            return
        h.depth = max(0, h.depth - 1)
        if elapsed is not None and elapsed >= 0:
            h.ewma = elapsed if h.ewma == 0.0 else \
                (1 - EWMA_ALPHA) * h.ewma + EWMA_ALPHA * elapsed
        h._touch()

    def hb_progress(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        h = self.heartbeats.get(name)
        if h is None:
            return
        h.progress += n
        h._touch()

    def hb_end(self, name: str) -> None:
        with self._hb_lock:
            self.heartbeats.pop(name, None)

    # -- snapshots ----------------------------------------------------------
    def snapshot(self, window: Optional[Tuple[float, float]] = None,
                 trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Serializable dump of the rings, optionally sliced to a
        ``(t0, t1)`` epoch window. Spans of ``trace_id`` are always
        included, window or not — the incident's trace is the point."""
        t0, t1 = window if window is not None else (None, None)

        def in_window(t: float) -> bool:
            return t0 is None or (t0 <= t <= t1)

        spans = [s for s in self.spans.snapshot()
                 if (trace_id is not None and s.trace_id == trace_id)
                 or in_window(s.end or s.start)]
        events = [e for e in self.events.snapshot() if in_window(e["t"])]
        logs = [r for r in self.logtail.snapshot() if in_window(r["t"])]
        with self._hb_lock:
            beats = {n: h.to_dict() for n, h in self.heartbeats.items()}
        return {
            "component": self.component,
            "pid": os.getpid(),
            "captured_at": time.time(),
            "window": [t0, t1],
            "rings": {
                "spans": {"n": len(spans), "capacity": self.spans.capacity,
                          "evicted": self.spans.evicted,
                          "items": [s.to_dict() for s in spans]},
                "events": {"n": len(events),
                           "capacity": self.events.capacity,
                           "evicted": self.events.evicted,
                           "items": events},
                "logtail": {"n": len(logs),
                            "capacity": self.logtail.capacity,
                            "evicted": self.logtail.evicted,
                            "items": logs},
            },
            "heartbeats": beats,
        }


class _LogTailHandler(logging.Handler):
    """Root-logger handler feeding the structured-log tail ring."""

    def __init__(self, ring: Ring):
        super().__init__()
        self.ring = ring

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.ring.append({"t": record.created,
                              "level": record.levelname,
                              "logger": record.name,
                              "msg": record.getMessage()})
        # dynalint: ok(swallowed-exception) a log-formatting error inside
        # the black box must never recurse into logging or break callers
        except Exception:
            pass


# ---------------------------------------------------------------------------
# process-global recorder + module-level conveniences for hook sites
# ---------------------------------------------------------------------------
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def install(component: Optional[str] = None, tracer=None) -> FlightRecorder:
    """Arm the process-global recorder: name it, mirror the (process)
    tracer's spans into the span ring, start the log tail. Idempotent —
    hook sites call the module-level note/hb functions regardless."""
    rec = flight_recorder()
    if component is not None:
        rec.component = component
    if rec.enabled:
        if tracer is None:
            from ..utils.tracing import get_tracer
            tracer = get_tracer()
        rec.attach(tracer)
        rec.attach_logging()
    return rec


def note_event(kind: str, **fields: Any) -> None:
    flight_recorder().note(kind, **fields)


def hb_begin(name: str, stall: Optional[str] = None,
             budget: Optional[float] = None,
             trace_id: Optional[str] = None) -> None:
    flight_recorder().hb_begin(name, stall=stall, budget=budget,
                               trace_id=trace_id)


def hb_done(name: str, elapsed: Optional[float] = None) -> None:
    flight_recorder().hb_done(name, elapsed=elapsed)


def hb_progress(name: str, n: int = 1) -> None:
    flight_recorder().hb_progress(name, n=n)


def hb_end(name: str) -> None:
    flight_recorder().hb_end(name)
