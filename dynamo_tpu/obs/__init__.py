"""Flight-recorder observability plane: black-box rings, hang watchdog,
coordinated incident bundles.

One call wires a process in::

    handle = await obs.start_process("decode_worker", store=drt.store,
                                     namespace=ns, span_sink=span_sink)
    ...
    await handle.stop()

which arms the always-on flight recorder (obs/flightrec.py), starts the
hang watchdog (obs/watchdog.py), and — when a store is given — joins the
cluster's incident coordination (obs/incidents.py): the process dumps
its rings whenever any process publishes a capture beacon, and local
triggers (breaker trips, torn streams, watchdog stalls, SLO burn,
SIGUSR2) raise beacons of their own.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import incidents
from .flightrec import (FlightRecorder, flight_recorder, hb_begin, hb_done,
                        hb_end, hb_progress, install, note_event)
from .incidents import IncidentManager
from .watchdog import Watchdog

__all__ = ["FlightRecorder", "IncidentManager", "ObsHandle", "Watchdog",
           "flight_recorder", "hb_begin", "hb_done", "hb_end",
           "hb_progress", "incidents", "install", "note_event",
           "start_process"]


@dataclass
class ObsHandle:
    recorder: FlightRecorder
    watchdog: Watchdog
    manager: Optional[IncidentManager]

    async def stop(self) -> None:
        await self.watchdog.stop()
        if self.manager is not None:
            await self.manager.stop()
            if incidents.manager() is self.manager:
                incidents.install_manager(None)


async def start_process(component: str, *, store=None,
                        namespace: str = "dynamo",
                        proc_label: Optional[str] = None,
                        span_sink=None, tracer=None,
                        install_signal: bool = False) -> ObsHandle:
    """Arm the whole plane for this process. ``proc_label`` names this
    process's dump inside incident bundles (default ``component:pid``);
    pass the worker id when several components share a pid (tests)."""
    rec = install(component=component, tracer=tracer)
    wd = await Watchdog(recorder=rec, tracer=tracer).start()
    mgr = None
    if store is not None:
        mgr = IncidentManager(store, namespace, component, recorder=rec,
                              span_sink=span_sink, proc_label=proc_label)
        await mgr.start(install_signal=install_signal)
        incidents.install_manager(mgr)
    return ObsHandle(rec, wd, mgr)
