"""Cluster byte-flow ledger: the one per-process accounting chokepoint
for every byte the cluster moves.

Every byte-moving site — disagg KV push/receive, cluster ``kv_fetch``
donor/receiver, paged-lane page-in/page-out, admission h2d prefetch,
write-through d2h spill, mobility weight prefetch and hot-swap slab
uploads — records ``(src, dst, kind, bytes, seconds)`` through
:func:`record_flow`. Link identity is unified across the two transport
families the fleet actually has:

- **network pairs**: worker endpoints, hex worker ids (the anonymous
  prefill pool is ``"q"``, matching ``kv_transfer.ANON_SRC``);
- **host↔device / disk edges**: ``host:<id>`` / ``dev:<id>`` /
  ``disk`` per process, where ``<id>`` is the worker hex id when known,
  else the pid — so a worker's PCIe/DMA lanes are links with the same
  telemetry shape as its NICs.

Per link the ledger keeps lifetime byte totals per kind
(``dyn_link_bytes_total{src,dst,kind}``), a windowed transfer rate over
the trailing ``DYN_LINK_WINDOW`` seconds (``dyn_link_bw_bytes_per_s``)
and a utilization estimate against calibrated capacity
(``dyn_link_saturation{link}``): capacity comes from the per-class
``DYN_LINK_CAPACITY_{NET,H2D,D2H,DISK}`` overrides when set, else from
the link's own measured peak instantaneous rate — under that fallback a
throttled pair that stays busy all window saturates toward 1.0 while a
fast bursty pair idles near 0. A rising edge through
``DYN_LINK_SAT_THRESHOLD`` emits a flight-recorder ``link.congested``
event, bumps ``dyn_link_congested_total{link}`` and (when an incident
manager is installed) triggers a ``link_congested`` incident capture.

All series ride the normal :class:`StageMetrics` registry, so they
publish through the existing StagePublisher path and merge cluster-wide
via ``fetch_stage_states`` — :func:`flows_from_states` folds that merged
view back into one link table (the shared backend of ``dyntop links:``,
``GET /v1/flows`` and ``ctl flows``).

Every flow with measured seconds also feeds the per-(src,dst) bandwidth
EWMA behind the router's :class:`~..llm.kv_cluster.registry.
TransferCostModel` (``observe_pair_bw``), so transfer-cost scoring sees
total observed traffic — paged page-in, cluster fetch, weight slabs —
not just disagg stream receives. Sites that used to call
``observe_pair_bw`` directly now go through the ledger so each flow
feeds the EWMA exactly once.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..utils.knobs import env_float
from ..utils.prometheus import stage_metrics
from . import flightrec as _flightrec
from . import incidents as _incidents

#: every flow kind the ledger accepts, mapped to its link class — the
#: class picks which ``DYN_LINK_CAPACITY_*`` override calibrates it
KIND_CLASS: Dict[str, str] = {
    "disagg_push": "net",
    "disagg_stream_rx": "net",
    "kv_fetch_tx": "net",
    "kv_fetch_rx": "net",
    "kvpage_pagein": "h2d",
    "h2d_prefetch": "h2d",
    "swap_slab": "h2d",
    "kvpage_pageout": "d2h",
    "d2h_writethrough": "d2h",
    "weight_prefetch": "disk",
}

#: label-key separator in metric state dumps (StageMetrics convention)
_SEP = "\x1f"


def _class_capacity(klass: str) -> float:
    """Calibrated capacity override for a link class, bytes/s; 0 = unset
    (fall back to the link's measured peak)."""
    if klass == "net":
        return env_float("DYN_LINK_CAPACITY_NET", 0.0, minimum=0.0)
    if klass == "h2d":
        return env_float("DYN_LINK_CAPACITY_H2D", 0.0, minimum=0.0)
    if klass == "d2h":
        return env_float("DYN_LINK_CAPACITY_D2H", 0.0, minimum=0.0)
    if klass == "disk":
        return env_float("DYN_LINK_CAPACITY_DISK", 0.0, minimum=0.0)
    return 0.0


def link_name(src: str, dst: str) -> str:
    """The single-label link identity (`dyn_link_saturation{link}`)."""
    return f"{src}>{dst}"


def split_link(link: str) -> Tuple[str, str]:
    src, _, dst = link.partition(">")
    return src, dst


class _LinkState:
    """Per-(src,dst) accounting: lifetime bytes by kind, a bounded
    trailing window of (end_time, bytes, seconds) samples, the measured
    peak instantaneous rate, and the last published saturation (for
    rising-edge congestion detection)."""

    __slots__ = ("bytes_by_kind", "window", "peak_bw", "last_sat",
                 "congested")

    def __init__(self) -> None:
        self.bytes_by_kind: Dict[str, int] = {}
        self.window: Deque[Tuple[float, int, float]] = deque(maxlen=512)
        self.peak_bw = 0.0
        self.last_sat = 0.0
        self.congested = 0


class FlowLedger:
    """Process-local byte-flow accounting. One instance per process
    (module singleton via :func:`flow_ledger`); ``enabled`` is the
    overhead A/B switch (``DYN_FLOWS``, default on)."""

    def __init__(self, local: Optional[str] = None,
                 now: Optional[Any] = None) -> None:
        self.enabled = os.environ.get("DYN_FLOWS", "1").lower() in (
            "1", "true", "yes", "on")
        #: endpoint id for this process's host/device edges: worker hex
        #: id once known (see :meth:`set_local`), else the pid
        self.local = local or str(os.getpid())
        self._now = now or time.monotonic
        self._lock = threading.Lock()
        self._links: Dict[Tuple[str, str], _LinkState] = {}

    # -- identity -----------------------------------------------------------
    def set_local(self, worker_id: Optional[int]) -> None:
        """Adopt the worker's hex id for host/device link endpoints, the
        same convention the network pairs use — called when the worker
        learns its lease id."""
        if worker_id:
            self.local = f"{worker_id:x}"

    def _default_link(self, kind: str) -> Tuple[str, str]:
        klass = KIND_CLASS.get(kind)
        host, dev = f"host:{self.local}", f"dev:{self.local}"
        if klass == "h2d":
            return host, dev
        if klass == "d2h":
            return dev, host
        if klass == "disk":
            return "disk", host
        # network kinds have no meaningful default; the anonymous pool
        # id keeps an unlabelled site visible rather than dropped
        return "q", self.local

    # -- recording ----------------------------------------------------------
    def record(self, kind: str, nbytes: int, seconds: float = 0.0,
               src: Optional[str] = None, dst: Optional[str] = None,
               trace_id: Optional[str] = None) -> None:
        """Account one movement of ``nbytes`` over the (src,dst) link.

        ``seconds`` is the measured wall time of the movement (0 =
        unknown: bytes still count, rates/EWMA skip the sample). ``src``
        / ``dst`` default from the kind's link class for host↔device and
        disk edges; network kinds should always pass worker endpoints.
        ``trace_id`` additionally drops a ``flow.<kind>`` span into the
        trace so waterfalls show the bytes each stage moved.
        """
        if not self.enabled or nbytes <= 0:
            return
        d_src, d_dst = self._default_link(kind)
        src = src or d_src
        dst = dst or d_dst
        now = self._now()
        window = env_float("DYN_LINK_WINDOW", 10.0, minimum=0.1)
        with self._lock:
            st = self._links.setdefault((src, dst), _LinkState())
            st.bytes_by_kind[kind] = st.bytes_by_kind.get(kind, 0) \
                + int(nbytes)
            st.window.append((now, int(nbytes), float(seconds)))
            if seconds > 0:
                st.peak_bw = max(st.peak_bw, nbytes / seconds)
            cutoff = now - window
            while st.window and st.window[0][0] < cutoff:
                st.window.popleft()
            win_bytes = sum(n for _, n, _ in st.window)
            bw = win_bytes / window
            cap = _class_capacity(KIND_CLASS.get(kind, "net")) \
                or st.peak_bw
            sat = min(bw / cap, 1.0) if cap > 0 else 0.0
            prev_sat = st.last_sat
            st.last_sat = sat
            edge = False
            thr = env_float("DYN_LINK_SAT_THRESHOLD", 0.9, minimum=0.0)
            if sat >= thr > prev_sat:
                st.congested += 1
                edge = True
        stage = stage_metrics()
        stage.link_bytes.inc(src, dst, kind, amount=int(nbytes))
        stage.link_bw.set(src, dst, value=bw)
        link = link_name(src, dst)
        stage.link_saturation.set(link, value=sat)
        if edge:
            stage.link_congested.inc(link)
            _flightrec.note_event("link.congested", link=link,
                                  sat=round(sat, 3), bw=round(bw),
                                  cap=round(cap))
            _incidents.trigger("link_congested", link=link,
                               sat=round(sat, 3), kind=kind)
        if seconds > 0:
            # ALL kinds feed the router's per-pair bandwidth EWMA — the
            # TransferCostModel prices total observed traffic, not just
            # disagg receives (lazy import: kv_transfer imports obs)
            from ..llm.kv_transfer import observe_pair_bw

            observe_pair_bw(src, dst, int(nbytes), float(seconds))
        if trace_id is not None and seconds > 0:
            from ..utils.tracing import get_tracer

            get_tracer().record(f"flow.{kind}", now - seconds, now,
                                trace_id=trace_id, bytes=int(nbytes),
                                src=src, dst=dst)

    # -- views --------------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        """Per-link view of this process's ledger, hottest first."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (src, dst), st in self._links.items():
                out.append({
                    "src": src, "dst": dst,
                    "bytes": sum(st.bytes_by_kind.values()),
                    "kinds": dict(st.bytes_by_kind),
                    "peak_bw": st.peak_bw,
                    "saturation": st.last_sat,
                    "congested": st.congested,
                })
        out.sort(key=lambda e: -e["bytes"])
        return out

    def total_bytes(self, kind: Optional[str] = None) -> int:
        with self._lock:
            return sum(n for st in self._links.values()
                       for k, n in st.bytes_by_kind.items()
                       if kind is None or k == kind)

    def reset(self) -> None:
        with self._lock:
            self._links.clear()


# ---------------------------------------------------------------------------
# process singleton + convenience chokepoint
# ---------------------------------------------------------------------------

_ledger: Optional[FlowLedger] = None
_ledger_lock = threading.Lock()


def flow_ledger() -> FlowLedger:
    global _ledger
    if _ledger is None:
        with _ledger_lock:
            if _ledger is None:
                _ledger = FlowLedger()
    return _ledger


def record_flow(kind: str, nbytes: int, seconds: float = 0.0,
                src: Optional[str] = None, dst: Optional[str] = None,
                trace_id: Optional[str] = None) -> None:
    """Module-level chokepoint every byte-moving site calls — the
    dynalint ``flow-accounting`` rule inventories exactly this."""
    flow_ledger().record(kind, nbytes, seconds, src=src, dst=dst,
                         trace_id=trace_id)


def set_local_worker(worker_id: Optional[int]) -> None:
    flow_ledger().set_local(worker_id)


# ---------------------------------------------------------------------------
# cluster-wide fold (pure: dyntop / HTTP / CLI share it)
# ---------------------------------------------------------------------------

def flows_from_states(states) -> List[Dict[str, Any]]:
    """Fold a ``fetch_stage_states`` result into one per-link table,
    hottest link first. Tolerates absent series (a fleet that never
    moved a byte returns ``[]`` — surfaces degrade by omission, never
    crash). Both ends of a network transfer may publish the same pair
    (``disagg_push`` at the sender, ``disagg_stream_rx`` at the
    receiver): bytes accumulate per kind so each view stays intact,
    while rate/saturation take the max across publishers (same wire)."""
    links: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def entry(src: str, dst: str) -> Dict[str, Any]:
        return links.setdefault((src, dst), {
            "src": src, "dst": dst, "bytes": 0, "kinds": {},
            "bw": 0.0, "saturation": 0.0, "congested": 0})

    for _component, dump in states or []:
        series = (dump.get("dyn_link_bytes_total") or {}).get(
            "series") or {}
        for skey, val in series.items():
            parts = skey.split(_SEP)
            if len(parts) != 3:
                continue
            e = entry(parts[0], parts[1])
            e["bytes"] += int(val)
            e["kinds"][parts[2]] = e["kinds"].get(parts[2], 0) + int(val)
        series = (dump.get("dyn_link_bw_bytes_per_s") or {}).get(
            "series") or {}
        for skey, val in series.items():
            parts = skey.split(_SEP)
            if len(parts) != 2:
                continue
            e = entry(parts[0], parts[1])
            e["bw"] = max(e["bw"], float(val))
        series = (dump.get("dyn_link_saturation") or {}).get(
            "series") or {}
        for skey, val in series.items():
            src, dst = split_link(skey)
            e = entry(src, dst)
            e["saturation"] = max(e["saturation"], float(val))
        series = (dump.get("dyn_link_congested_total") or {}).get(
            "series") or {}
        for skey, val in series.items():
            src, dst = split_link(skey)
            e = entry(src, dst)
            e["congested"] += int(val)
    out = list(links.values())
    out.sort(key=lambda e: -e["bytes"])
    return out


def fmt_bytes(n: float) -> str:
    """Human-scale byte count for the CLI surfaces (dyntop / ctl)."""
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TB"  # pragma: no cover - loop always returns
