"""Coordinated incident bundles over the dynstore keyspace.

Any trigger — a watchdog stall, a circuit-breaker trip, a torn disagg
stream, an SLO burn crossing, ``ctl incident capture`` or SIGUSR2 —
publishes a **capture beacon** under ``incidents/{ns}/beacon/{id}``.
Every process runs an :class:`IncidentManager` watching that prefix;
on a new beacon each one freezes a windowed slice of its flight-recorder
rings (obs/flightrec.py) and writes it under
``incidents/{ns}/bundle/{id}/{proc}`` on a TTL lease. The result is ONE
coordinated bundle per incident: the beacon doubles as the manifest,
per-process ring dumps sit under the bundle prefix, and the trace named
by the trigger is retro-assembled (the local span sink force-exports it,
so the store holds the complete trace even at ``DYN_TRACE_SAMPLE=0.01``).

Triggers raised while a beacon younger than ``DYN_INCIDENT_COOLDOWN``
exists *attach* to that incident instead of opening a new one — a torn
stream and the breaker trip it causes are one incident, not a beacon
storm. Bundles expire with their ``DYN_INCIDENT_TTL`` lease; the ring
slice spans ``DYN_INCIDENT_WINDOW`` seconds before the trigger.

Inspect with ``dynctl incident ls/show/export`` and
``tracectl --bundle <file> --chrome <out>``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.knobs import env_float
from ..utils.prometheus import stage_metrics
from .flightrec import FlightRecorder, flight_recorder

log = logging.getLogger("dynamo_tpu.obs.incidents")

INCIDENT_PREFIX = "incidents/"


def incident_beacon_key(ns: str, incident_id: str) -> str:
    return f"{INCIDENT_PREFIX}{ns}/beacon/{incident_id}"


def incident_beacon_prefix(ns: str) -> str:
    return f"{INCIDENT_PREFIX}{ns}/beacon/"


def incident_dump_key(ns: str, incident_id: str, proc: str) -> str:
    return f"{INCIDENT_PREFIX}{ns}/bundle/{incident_id}/{proc}"


def incident_dump_prefix(ns: str, incident_id: str) -> str:
    return f"{INCIDENT_PREFIX}{ns}/bundle/{incident_id}/"


async def publish_beacon(store, ns: str, reason: str, *,
                         window_s: float = 30.0,
                         trace_id: Optional[str] = None,
                         by: str = "ctl", ttl: float = 3600.0,
                         detail: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
    """Create + publish one capture beacon; returns the beacon record.
    Shared by :meth:`IncidentManager.trigger` and ``ctl incident
    capture`` (which has no rings of its own to dump)."""
    now = time.time()
    iid = f"{int(now)}-{reason}-{uuid.uuid4().hex[:6]}"
    beacon = {"id": iid, "reason": reason, "at": now,
              "window": [now - window_s, now],
              "trace_id": trace_id, "detail": detail or {}, "by": by}
    # unbound: the beacon must outlive the (often short-lived) publisher —
    # ctl exits right after capture, a stalled worker may be about to die
    lease = await store.lease_grant(ttl=ttl, auto_keepalive=False,
                                    bind=False)
    await store.put(incident_beacon_key(ns, iid),
                    json.dumps(beacon).encode(), lease=lease)
    stage_metrics().incidents_captured.inc(reason)
    return beacon


class IncidentManager:
    """Per-process incident coordinator: watches the beacon prefix,
    dumps this process's rings into the bundle, and raises beacons for
    locally observed triggers."""

    def __init__(self, store, namespace: str = "dynamo",
                 component: str = "proc",
                 recorder: Optional[FlightRecorder] = None,
                 span_sink=None, proc_label: Optional[str] = None,
                 ttl: Optional[float] = None,
                 cooldown: Optional[float] = None,
                 window: Optional[float] = None):
        self.store = store
        self.namespace = namespace
        self.component = component
        self.recorder = recorder if recorder is not None \
            else flight_recorder()
        self.span_sink = span_sink
        self.proc_label = proc_label or f"{component}:{os.getpid()}"
        self.ttl = env_float("DYN_INCIDENT_TTL", 3600.0, minimum=10.0) \
            if ttl is None else ttl
        self.cooldown = env_float("DYN_INCIDENT_COOLDOWN", 30.0,
                                  minimum=0.0) \
            if cooldown is None else cooldown
        self.window = env_float("DYN_INCIDENT_WINDOW", 30.0, minimum=1.0) \
            if window is None else window
        #: extra bundle sections: name -> () -> JSON-serializable (sync
        #: or async); e.g. the router's decision-ring slice
        self.sources: Dict[str, Callable[[], Any]] = {}
        self._dumped: deque = deque(maxlen=256)       # incident ids done
        self._recent: deque = deque(maxlen=64)        # (mono, beacon)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._closed = False
        self._signal_installed = False

    # -- lifecycle ----------------------------------------------------------
    async def start(self, install_signal: bool = False
                    ) -> "IncidentManager":
        self._loop = asyncio.get_running_loop()
        snapshot = await self.store.watch_prefix(
            incident_beacon_prefix(self.namespace), self._on_beacon)
        for key, value in snapshot:
            await self._on_beacon(key, value, False)
        if install_signal:
            try:
                import signal

                self._loop.add_signal_handler(
                    signal.SIGUSR2, self.trigger_nowait, "sigusr2")
                self._signal_installed = True
            except (NotImplementedError, ValueError, OSError, RuntimeError):
                log.debug("SIGUSR2 capture handler unavailable",
                          exc_info=True)
        return self

    async def stop(self) -> None:
        self._closed = True
        if self._signal_installed and self._loop is not None:
            try:
                import signal

                self._loop.remove_signal_handler(signal.SIGUSR2)
            except (NotImplementedError, ValueError, OSError, RuntimeError):
                pass
            self._signal_installed = False

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        self.sources[name] = fn

    # -- triggers -----------------------------------------------------------
    def _fresh_beacon(self) -> Optional[Dict[str, Any]]:
        now = time.monotonic()
        for seen_at, beacon in reversed(self._recent):
            if now - seen_at <= self.cooldown:
                return beacon
            break
        return None

    async def trigger(self, reason: str, trace_id: Optional[str] = None,
                      **detail: Any) -> Optional[str]:
        """Open (or attach to) an incident. Returns the incident id, or
        None when the manager is closed."""
        if self._closed:
            return None
        existing = self._fresh_beacon()
        if existing is not None:
            # coordinated, not chatty: a trigger inside the cooldown of
            # a live incident joins it — the attach is visible in the
            # events ring, and the re-dump refreshes our slice
            self.recorder.note("incident.attach", incident=existing["id"],
                               reason=reason, trace_id=trace_id, **detail)
            if trace_id and not existing.get("trace_id"):
                existing["trace_id"] = trace_id
            await self._dump(existing, force=True)
            return existing["id"]
        try:
            beacon = await publish_beacon(
                self.store, self.namespace, reason, window_s=self.window,
                trace_id=trace_id, by=self.proc_label, ttl=self.ttl,
                detail=detail)
        except Exception:
            log.warning("incident beacon publish failed", exc_info=True)
            return None
        self._recent.append((time.monotonic(), beacon))
        await self._dump(beacon, force=True)
        return beacon["id"]

    def trigger_nowait(self, reason: str, trace_id: Optional[str] = None,
                       **detail: Any) -> None:
        """Fire-and-forget trigger from sync code (breaker callbacks,
        signal handlers, the SLO monitor tick)."""
        if self._closed or self._loop is None:
            return
        from ..utils.aiotasks import spawn

        def _go() -> None:
            spawn(self.trigger(reason, trace_id=trace_id, **detail),
                  name=f"incident-{reason}")

        self._loop.call_soon_threadsafe(_go)

    # -- beacon fan-in ------------------------------------------------------
    async def _on_beacon(self, key: str, value: Optional[bytes],
                         deleted: bool) -> None:
        if deleted or self._closed or value is None:
            return
        try:
            beacon = json.loads(value.decode())
        except (ValueError, UnicodeDecodeError):
            log.warning("undecodable incident beacon %s", key)
            return
        self._recent.append((time.monotonic(), beacon))
        if beacon["id"] in self._dumped:
            return
        # dump from a task, NOT the watch callback: the dump itself does
        # store I/O and must not re-enter the client's receive path
        from ..utils.aiotasks import spawn
        spawn(self._dump(beacon), name=f"incident-dump-{beacon['id']}")

    # -- the dump -----------------------------------------------------------
    async def _dump(self, beacon: Dict[str, Any],
                    force: bool = False) -> None:
        iid = beacon["id"]
        try:
            t0 = float(beacon.get("window", [time.time() - self.window])[0])
            snap = self.recorder.snapshot(window=(t0, time.time()),
                                          trace_id=beacon.get("trace_id"))
            rings = snap["rings"]
            touched = any(rings[r]["n"] for r in rings)
            if not (touched or force or beacon.get("by") == self.proc_label):
                return      # nothing of ours in the window: stay out
            snap["incident"] = {k: beacon.get(k) for k in
                                ("id", "reason", "at", "trace_id", "by")}
            if self.sources:
                out: Dict[str, Any] = {}
                for name, fn in self.sources.items():
                    try:
                        val = fn()
                        if asyncio.iscoroutine(val):
                            val = await asyncio.wait_for(val, timeout=2.0)
                        out[name] = val
                    except Exception as e:  # noqa: BLE001 - best-effort
                        out[name] = {"error": f"{type(e).__name__}: {e}"}
                snap["sources"] = out
            tid = beacon.get("trace_id")
            if tid and self.span_sink is not None:
                # retro-assemble: force the whole trace into the store
                # export, sampled-out spans included
                self.span_sink.force_trace(tid)
            # unbound: the black box must survive the crash that made it
            # interesting — a dump vanishing with its process is useless
            lease = await self.store.lease_grant(ttl=self.ttl,
                                                 auto_keepalive=False,
                                                 bind=False)
            await self.store.put(
                incident_dump_key(self.namespace, iid, self.proc_label),
                json.dumps(snap).encode(), lease=lease)
            if iid not in self._dumped:
                self._dumped.append(iid)
            stage_metrics().incident_dumps.inc()
        except Exception:
            log.warning("incident ring dump failed for %s", iid,
                        exc_info=True)


# ---------------------------------------------------------------------------
# process-global manager + the trigger hook other subsystems call
# ---------------------------------------------------------------------------
_manager: Optional[IncidentManager] = None


def install_manager(m: Optional[IncidentManager]) -> None:
    global _manager
    _manager = m


def manager() -> Optional[IncidentManager]:
    return _manager


def trigger(reason: str, trace_id: Optional[str] = None,
            **detail: Any) -> None:
    """Raise an incident from anywhere (breaker trip, torn stream, SLO
    burn, watchdog stall). A no-op in processes without a manager — hook
    sites call unconditionally."""
    m = _manager
    if m is not None:
        m.trigger_nowait(reason, trace_id=trace_id, **detail)


# ---------------------------------------------------------------------------
# bundle read side (ctl incident ls/show/export, tracectl, http_service)
# ---------------------------------------------------------------------------
async def list_incidents(store, ns: str) -> List[Dict[str, Any]]:
    """Live (unexpired) incident beacons, newest first."""
    out: List[Dict[str, Any]] = []
    for _key, value in await store.get_prefix(incident_beacon_prefix(ns)):
        try:
            out.append(json.loads(value.decode()))
        except (ValueError, UnicodeDecodeError):
            continue
    out.sort(key=lambda b: b.get("at", 0.0), reverse=True)
    return out


async def fetch_bundle(store, ns: str, incident_id: str
                       ) -> Optional[Dict[str, Any]]:
    """Assemble one incident bundle: manifest (the beacon) + every
    process's ring dump + the trigger's trace retro-assembled from the
    store export merged with the spans the rings preserved."""
    from ..utils.tracing import Span, fetch_trace_spans, merge_spans

    raw = await store.get(incident_beacon_key(ns, incident_id))
    if raw is None:
        return None
    manifest = json.loads(raw.decode())
    processes: Dict[str, Any] = {}
    for key, value in await store.get_prefix(
            incident_dump_prefix(ns, incident_id)):
        proc = key.rsplit("/", 1)[-1]
        try:
            processes[proc] = json.loads(value.decode())
        except (ValueError, UnicodeDecodeError):
            log.warning("undecodable incident dump %s", key)
    trace: List[Dict[str, Any]] = []
    tid = manifest.get("trace_id")
    if tid:
        groups = [await fetch_trace_spans(store, tid)]
        for snap in processes.values():
            ring = snap.get("rings", {}).get("spans", {}).get("items", [])
            groups.append([Span.from_dict(d) for d in ring
                           if d.get("trace_id") == tid])
        trace = [s.to_dict() for s in merge_spans(*groups)]
    return {"manifest": manifest, "processes": processes, "trace": trace}


def bundle_summary(bundle: Dict[str, Any]) -> List[str]:
    """Human-readable summary lines for ``ctl incident show`` — includes
    per-ring eviction loss so "quiet window" and "ring too small" read
    differently."""
    m = bundle["manifest"]
    lines = [f"incident {m['id']}",
             f"  reason   {m['reason']}  (by {m.get('by', '?')})",
             f"  at       {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(m['at']))}"
             f"  window {m['window'][1] - m['window'][0]:.0f}s"]
    if m.get("trace_id"):
        lines.append(f"  trace    {m['trace_id']} "
                     f"({len(bundle['trace'])} spans retro-assembled)")
    if m.get("detail"):
        lines.append(f"  detail   {json.dumps(m['detail'], sort_keys=True)}")
    lines.append(f"  processes ({len(bundle['processes'])}):")
    for proc in sorted(bundle["processes"]):
        snap = bundle["processes"][proc]
        rings = snap.get("rings", {})
        cells = []
        for name in ("spans", "events", "logtail"):
            r = rings.get(name, {})
            cell = f"{name} {r.get('n', 0)}"
            if r.get("evicted"):
                cell += f" (LOSS: {r['evicted']} evicted, ring too small?)"
            cells.append(cell)
        lines.append(f"    {proc:32s} {'  '.join(cells)}")
        stalls = [e for e in rings.get("events", {}).get("items", [])
                  if e.get("kind") == "watchdog.stall"]
        for st in stalls:
            lines.append(f"      stall: {st.get('name')} wedged "
                         f"{st.get('waited', 0):.2f}s")
    return lines
