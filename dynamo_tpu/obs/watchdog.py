"""Hang watchdog: turns wedged state into never-sampled ``stall:*`` spans.

Polls the flight recorder's heartbeat table every
``DYN_WATCHDOG_INTERVAL`` seconds and fires when work is in flight but
nothing has moved for too long:

- a decode dispatch exceeding ``DYN_WATCHDOG_MULT`` × its EWMA step time
  (with a ``DYN_WATCHDOG_FLOOR`` absolute floor so a noisy EWMA cannot
  produce sub-second false positives);
- a transfer stream with no layer progress inside its explicit budget
  (``DYN_WATCHDOG_TRANSFER`` armed by the KV receiver);
- a drain that outlives its grace budget (armed by the worker shell);
- an event-loop stall: the watchdog's own tick waking more than
  ``DYN_WATCHDOG_LOOP_STALL`` seconds late means something held the loop.

Each detection emits ONE ``stall:<kind>`` span per wedged period
(re-armed the moment the activity moves again) carrying
``force_trace=True`` so head sampling can never drop it, counts
``dyn_watchdog_stalls_total{kind}``, and raises an incident trigger so
every involved process dumps its rings (obs/incidents.py).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.knobs import env_float
from ..utils.prometheus import stage_metrics
from .flightrec import FlightRecorder, flight_recorder

log = logging.getLogger("dynamo_tpu.obs.watchdog")


class Watchdog:
    def __init__(self, recorder: Optional[FlightRecorder] = None,
                 tracer=None, interval: Optional[float] = None,
                 mult: Optional[float] = None,
                 floor: Optional[float] = None,
                 loop_stall: Optional[float] = None,
                 enabled: Optional[bool] = None):
        self.recorder = recorder if recorder is not None \
            else flight_recorder()
        self._tracer = tracer
        self.interval = env_float("DYN_WATCHDOG_INTERVAL", 0.25,
                                  minimum=0.01) \
            if interval is None else interval
        self.mult = env_float("DYN_WATCHDOG_MULT", 8.0, minimum=1.0) \
            if mult is None else mult
        self.floor = env_float("DYN_WATCHDOG_FLOOR", 1.0, minimum=0.0) \
            if floor is None else floor
        self.loop_stall = env_float("DYN_WATCHDOG_LOOP_STALL", 1.0,
                                    minimum=0.05) \
            if loop_stall is None else loop_stall
        if enabled is None:
            enabled = os.environ.get("DYN_WATCHDOG", "1") \
                not in ("0", "false")
        self.enabled = enabled
        self.stalls = 0
        self._task: Optional[asyncio.Task] = None

    @property
    def tracer(self):
        if self._tracer is None:
            from ..utils.tracing import get_tracer
            self._tracer = get_tracer()
        return self._tracer

    async def start(self) -> "Watchdog":
        if self.enabled and self._task is None:
            from ..utils.aiotasks import spawn
            self._task = spawn(self._loop(), name="obs-watchdog")
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- detection (pure against the heartbeat table; unit-testable) --------
    def check(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One poll over the heartbeat table; returns the stalls fired
        this tick (each wedged period fires once, re-armed on the next
        activity)."""
        if now is None:
            now = time.monotonic()
        fired: List[Dict[str, Any]] = []
        for name, hb in list(self.recorder.heartbeats.items()):
            if hb.depth <= 0 or hb.fired:
                continue
            if hb.budget is not None:
                deadline = hb.budget
            elif hb.ewma > 0.0:
                deadline = max(self.mult * hb.ewma, self.floor)
            else:
                # nothing to judge against yet (first unit may include
                # compilation); the budgetless EWMA path stays silent
                continue
            waited = now - hb.last_activity
            if waited <= deadline:
                continue
            hb.fired = True
            fired.append({"kind": hb.stall, "name": name,
                          "waited": waited, "deadline": deadline,
                          "ewma": hb.ewma, "depth": hb.depth,
                          "progress": hb.progress,
                          "trace_id": hb.trace_id})
        return fired

    def _emit(self, st: Dict[str, Any]) -> None:
        self.stalls += 1
        end = time.time()
        kind = st["kind"]
        # "name" would collide with record()'s span-name parameter: the
        # wedged heartbeat rides as the ``hb`` attribute instead
        attrs = {k: v for k, v in st.items()
                 if k not in ("kind", "trace_id", "name") and v is not None}
        self.tracer.record(f"stall:{kind}", start=end - st["waited"],
                           end=end, trace_id=st.get("trace_id"),
                           status="error", force_trace=True,
                           hb=st["name"], **attrs)
        stage_metrics().watchdog_stalls.inc(kind)
        # the event's own ``kind`` is "watchdog.stall"; the stall kind
        # rides as ``stall_kind``
        self.recorder.note("watchdog.stall", stall_kind=kind,
                           **{k: v for k, v in st.items() if k != "kind"})
        log.warning("watchdog: %s wedged for %.2fs (deadline %.2fs, "
                    "ewma %.3fs, depth %d)", st["name"], st["waited"],
                    st["deadline"], st["ewma"], st["depth"])
        from . import incidents
        incidents.trigger(f"stall_{kind}", trace_id=st.get("trace_id"),
                          name=st["name"], waited=round(st["waited"], 3))

    async def _loop(self) -> None:
        last = time.monotonic()
        while True:
            await asyncio.sleep(self.interval)
            now = time.monotonic()
            lag = now - last - self.interval
            last = now
            if lag > self.loop_stall:
                # the watchdog itself woke late: something held the
                # event loop for the whole lag — report it retroactively
                self._emit({"kind": "event_loop", "name": "event_loop",
                            "waited": lag, "deadline": self.loop_stall,
                            "ewma": 0.0, "depth": 1, "progress": 0,
                            "trace_id": None})
            for st in self.check(now):
                self._emit(st)
