"""Multi-host worker model: one process per TPU host, one logical worker.

JAX is SPMD multi-controller: every process must execute the same program
over the global mesh. The serving engine is request-driven on ONE process,
so the leader (node rank 0) broadcasts a descriptor of every device dispatch
(program kind + bucket shapes + host input arrays) to the followers over a
TCP dispatch channel, and each follower replays it through
``EngineCore.mirror_dispatch`` — identical jitted programs, identical
inputs, lockstep device state. Only the leader serves the endpoint,
registers in the store and streams tokens; followers join the mesh silently
and die with the leader.

Failure detection is two-layered: a dispatch-channel socket error kills the
worker immediately (see DispatchPublisher.hook), and silent member death is
caught by jax.distributed's own coordination-service heartbeat, which
terminates every surviving process of the slice within its timeout
(~1 minute) — after which the leader's lease expires and clients shrink
their live set. The slice fails as one unit, like the reference's Ray
cluster does.

Reference capability: the multi-node engine bootstrap the reference
delegates to Ray/torch-distributed (lib/llm/src/engines.rs:40-58
MultiNodeConfig, engines/vllm/src/ray.rs:66-229 leader/follower), rebuilt on
jax.distributed.initialize + an explicit dispatch-replay plane (SURVEY §7
"Multi-host process model").
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

from ..runtime.wire import MAX_FRAME, pack as wire_pack

log = logging.getLogger("dynamo_tpu.multihost")

_HDR = struct.Struct(">I")


def init_distributed(coordinator: str, num_nodes: int,
                     node_rank: int) -> None:
    """``jax.distributed.initialize`` wrapper: call BEFORE any jax backend
    use. After it, ``jax.devices()`` is the global device list."""
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_nodes,
                               process_id=node_rank)


def _pack_arrays(arrs: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out = {}
    for k, a in arrs.items():
        a = np.ascontiguousarray(a)
        out[k] = [str(a.dtype), list(a.shape), a.tobytes()]
    return out


def _unpack_arrays(d: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out = {}
    for k, (dtype, shape, raw) in d.items():
        out[k] = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)
    return out


def _send_frame(sock: socket.socket, obj: Any) -> None:
    sock.sendall(wire_pack(obj))   # the one wire framing (runtime/wire.py)


def _recv_frame(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _HDR.size)
    (n,) = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ConnectionError(f"dispatch frame of {n} bytes exceeds "
                              f"MAX_FRAME — corrupt channel")
    return msgpack.unpackb(_recv_exact(sock, n), raw=False)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("dispatch channel closed")
        buf += chunk
    return buf


class DispatchPublisher:
    """Leader side: accepts follower connections, then broadcasts every
    engine dispatch in order.

    ``hook`` plugs into EngineCore.dispatch_hook (called from the engine
    thread). Broadcast is PIPELINED: the hook packs the frame and enqueues
    it on a bounded queue; a sender thread drains the queue, coalescing
    every queued dispatch into ONE socket write per follower — the engine
    never blocks on follower sockets at steady state, while the bounded
    depth keeps lockstep backpressure (a stalled follower stalls the
    leader within ``queue_depth`` dispatches rather than diverging)."""

    def __init__(self, port: int, expected_followers: int,
                 queue_depth: int = 8):
        import queue as _queue

        self.expected = expected_followers
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(expected_followers)
        self.port = self._srv.getsockname()[1]
        self._socks: List[socket.socket] = []
        self._lock = threading.Lock()
        self._q: "_queue.Queue[bytes]" = _queue.Queue(maxsize=queue_depth)
        self._sender = threading.Thread(target=self._drain, daemon=True,
                                        name="dispatch-publisher")
        self._sender.start()
        # Follower death must be detected even when the engine is WEDGED
        # inside a collective waiting for the dead peer (no further sends
        # ever happen). Followers never write on the dispatch channel, so
        # a readable socket means EOF/RST: poll for it.
        self._monitor = threading.Thread(target=self._watch_followers,
                                         daemon=True,
                                         name="dispatch-monitor")
        self._monitor.start()

    def wait_for_followers(self, timeout: float = 300.0) -> None:
        self._srv.settimeout(timeout)
        while len(self._socks) < self.expected:
            sock, addr = self._srv.accept()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._socks.append(sock)
            log.info("follower %s connected (%d/%d)", addr,
                     len(self._socks), self.expected)

    def hook(self, kind: str, meta: Dict[str, Any],
             arrs: Dict[str, np.ndarray]) -> None:
        # pack on the engine thread (deterministic dispatch order), send on
        # the sender thread (overlaps the next device dispatch)
        self._q.put(wire_pack([kind, meta, _pack_arrays(arrs)]))

    def _drain(self) -> None:
        import queue as _queue

        while True:
            buf = [self._q.get()]
            while True:
                try:
                    buf.append(self._q.get_nowait())
                except _queue.Empty:
                    break
            data = b"".join(buf)     # coalesced: one write per follower
            with self._lock:
                socks = list(self._socks)
            for sock in socks:
                try:
                    sock.sendall(data)
                except OSError:
                    # SPMD divergence is unrecoverable: a follower that
                    # missed a dispatch can never rejoin the lockstep, and
                    # surviving followers may already be blocked inside a
                    # collective the leader would never run again. Die hard:
                    # the lease expires, the endpoint deregisters, clients
                    # shrink their live set — clean slice failure.
                    log.critical("dispatch channel to a follower failed; "
                                 "terminating the multi-host worker")
                    import os as _os

                    _os._exit(13)

    def _watch_followers(self) -> None:
        import select
        import time as _time

        while True:
            if self._closing:
                return     # orderly teardown: never escalate to exit(13)
            with self._lock:
                socks = list(self._socks)
            if not socks:
                _time.sleep(0.2)
                continue
            try:
                readable, _, errored = select.select(socks, [], socks, 0.5)
            except (OSError, ValueError):
                _time.sleep(0.2)   # close() raced us; clean shutdown path
                continue
            if self._closing:
                return
            if readable or errored:
                # EOF/reset — or a protocol violation (followers are
                # silent): the slice can no longer stay in lockstep
                if self._closing:
                    return   # a follower closing first during teardown is
                             # not a failure — re-check right at the brink
                log.critical("dispatch channel lost (follower died); "
                             "terminating the multi-host worker")
                import os as _os

                _os._exit(13)

    _closing = False

    def close(self) -> None:
        self._closing = True
        for s in self._socks:
            s.close()
        self._srv.close()


class FollowerLoop:
    """Follower side: connect to the leader's dispatch channel and replay
    every dispatch through the local EngineCore mirror. Blocks forever
    (until the channel closes — leader death ends the follower)."""

    def __init__(self, core, leader_host: str, dispatch_port: int,
                 connect_timeout: float = 300.0):
        self.core = core
        deadline = connect_timeout
        import time

        t0 = time.monotonic()
        while True:
            try:
                self._sock = socket.create_connection(
                    (leader_host, dispatch_port), timeout=5)
                break
            except OSError:
                if time.monotonic() - t0 > deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def run(self) -> None:
        n = 0
        try:
            while True:
                kind, meta, packed = _recv_frame(self._sock)
                self.core.mirror_dispatch(kind, meta, _unpack_arrays(packed))
                n += 1
        except ConnectionError as e:
            # leader loss is a SLICE failure, not a clean exit: re-raise so
            # the process exits nonzero and a restart policy brings the
            # whole slice back together
            log.error("dispatch channel lost after %d dispatches: %s", n, e)
            raise
