"""Pipeline parallelism over the ``pp`` mesh axis.

GPipe-style staggered execution for SPMD: the stacked per-layer params are
sharded on the layer dimension over ``pp`` (each device materializes only
its contiguous stage of layers — the memory win that makes 70B-class models
fit small slices), microbatches enter stage 0 one per step, activations hop
stage-to-stage with ``lax.ppermute`` (neighbor ICI links), and after
``M + pp - 1`` steps every microbatch has traversed every stage. Steady-
state utilization is M/(M+pp-1); the bubble shrinks as microbatches grow.

pp is engine-served: ``JaxEngineConfig.pp`` builds the pp(×tp) mesh and
the serving prefill/decode programs run the staged path with params AND
paged KV pools sharded on the layer dim (``models/llama.py`` forward_pp /
forward_decode_pp; docs/pipeline_parallel.md). This module holds the
standalone staged-matmul pipeline primitive and its schedule tests.

Reference capability: pipeline parallelism the reference delegates to vLLM
multinode (SURVEY §2.5: pipeline_parallel_size = num_nodes, vllm_inc.py:38),
expressed TPU-natively as an SPMD collective-permute pipeline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AXIS_PP


def pipeline_apply(stage_fn: Callable, stage_params, xs: jax.Array,
                   mesh: Mesh, axis: str = AXIS_PP) -> jax.Array:
    """Run every microbatch through all pipeline stages.

    stage_fn(params_stage, x) -> y applies ONE stage (its slice of layers).
    stage_params: pytree whose leaves have a leading layer/stage-shardable
    dim divisible by pp (sharded over ``axis``); inside the pipeline each
    device sees only its local slice.
    xs: [M, ...] microbatches (replicated).

    Returns [M, ...] outputs after all stages, replicated.
    """
    pp = mesh.shape[axis]
    M = xs.shape[0]
    if pp == 1:
        return jnp.stack([stage_fn(stage_params, xs[m]) for m in range(M)])

    perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def local(params_local, xs):
        idx = jax.lax.axis_index(axis)
        cur = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)
        # steps: microbatch m enters stage 0 at step m, exits the last
        # stage at step m + pp - 1
        for t in range(M + pp - 1):
            if t < M:
                cur = jnp.where(idx == 0, xs[t], cur)
            y = stage_fn(params_local, cur)
            if t >= pp - 1:
                m_out = t - (pp - 1)
                outs = outs.at[m_out].set(
                    jnp.where(idx == pp - 1, y, outs[m_out]))
            cur = jax.lax.ppermute(y, axis, perm_fwd)
        # replicate the collected outputs (only the last stage held them)
        return jax.lax.psum(
            jnp.where(jax.lax.axis_index(axis) == pp - 1, outs, 0.0), axis)

    # params sharded on their leading dim over pp; xs replicated
    pspec = jax.tree.map(lambda _: P(axis), stage_params)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
    )(stage_params, xs)
