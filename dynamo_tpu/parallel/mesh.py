"""Device mesh and sharding conventions.

One place defines the axis names used everywhere:

- ``dp``  — data parallel (independent decode batches / replicas in one proc)
- ``tp``  — tensor parallel: attention heads / ffn dim over ICI
- ``sp``  — sequence/context parallel for long prefill (ring attention axis)
- ``ep``  — expert parallel (MoE layers)
- ``pp``  — pipeline stages (inter-slice over DCN, optional)

The serving engine usually runs a 1-D ``tp`` mesh per replica; the runtime
scales replicas (the reference's data parallelism is worker replication, not
an in-engine axis). ``dryrun`` builds the full 4-D mesh to validate shardings.

TPU-native stance: shardings are declared with NamedSharding/PartitionSpec and
XLA inserts the collectives (scaling-book recipe) — no hand-written NCCL-style
calls anywhere.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if cfg.size > len(devices):
        raise ValueError(f"mesh needs {cfg.size} devices, have {len(devices)}")
    devs = np.array(devices[: cfg.size]).reshape(cfg.pp, cfg.dp, cfg.ep, cfg.sp, cfg.tp)
    return Mesh(devs, (AXIS_PP, AXIS_DP, AXIS_EP, AXIS_SP, AXIS_TP))


def tp_mesh(tp: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The common serving mesh: 1-D tensor parallel."""
    devices = list(devices if devices is not None else jax.devices())
    devs = np.array(devices[:tp]).reshape(tp)
    return Mesh(devs, (AXIS_TP,))


def sp_tp_mesh(sp: int, tp: int,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Long-context serving mesh: ring-attention sequence axis x tensor
    parallel. sp is the OUTER axis so each ring hop crosses between tp
    groups (neighboring ICI links), while tp collectives stay innermost."""
    devices = list(devices if devices is not None else jax.devices())
    devs = np.array(devices[: sp * tp]).reshape(sp, tp)
    return Mesh(devs, (AXIS_SP, AXIS_TP))


def serving_mesh(tp: int = 1, sp: int = 1, ep: int = 1, pp: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Engine mesh with exactly the axes in use: pp (pipeline stages,
    outermost — stage hops tolerate DCN), sp (ring prefill), ep (experts),
    tp (innermost, so tp collectives ride neighbor ICI links). Axes of
    size 1 other than tp are omitted."""
    devices = list(devices if devices is not None else jax.devices())
    axes = [(AXIS_PP, pp), (AXIS_SP, sp), (AXIS_EP, ep), (AXIS_TP, tp)]
    axes = [(n, s) for n, s in axes if s > 1 or n == AXIS_TP]
    total = math.prod(s for _, s in axes)
    if total > len(devices):
        raise ValueError(
            f"serving mesh tp={tp} sp={sp} ep={ep} pp={pp} needs {total} "
            f"devices, have {len(devices)}")
    devs = np.array(devices[:total]).reshape([s for _, s in axes])
    return Mesh(devs, tuple(n for n, _ in axes))


def filter_spec(mesh: Mesh, spec: P) -> P:
    """Drop axis names the mesh doesn't carry from a PartitionSpec (lets
    one spec serve 1-D and 4-D meshes)."""
    names = set(mesh.axis_names)

    def keep(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            kept = tuple(x for x in s if x in names)
            return kept if kept else None
        return s if s in names else None

    return P(*(keep(s) for s in spec))


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, filter_spec(mesh, P(*spec)))


def shard_divisible(n: int, mesh: Mesh, axis: str) -> bool:
    """Can dimension ``n`` be sharded over mesh axis ``axis``?"""
    if axis not in mesh.axis_names:
        return False
    return n % mesh.shape[axis] == 0
