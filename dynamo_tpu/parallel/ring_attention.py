"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context prefill splits both the query chunk and the KV context across
the ``sp`` mesh axis; each device computes blockwise attention between its
local queries and the KV shard it currently holds, then rotates the KV shard
to its ring neighbor with ``lax.ppermute``, carrying flash-style online
softmax statistics (m, l, acc) across the sp steps. After sp rotations every
query has seen every context position, with peak memory O(S/sp) per device
and the rotation riding ICI neighbor links.

Positions and validity travel with the KV shard, so causal masking is
position-exact regardless of which device currently holds which shard — the
same explicit (k_pos <= q_pos) & valid contract as the Pallas flash kernel,
which makes the two composable (the per-shard inner update can later be
swapped for the kernel).

Reference capability: the reference has NO sequence/context parallelism
(SURVEY §5.7 — verified absent); this is the TPU-native long-context answer
the survey assigns to the in-tree engine, not a port.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AXIS_SP

NEG_INF = -1e30


def _online_update(qg, k, v, qpos, kpos, kval, scale, m, l, acc):
    """One flash-style partial-attention update.

    qg: [B, Hkv, G, Tl, Dh] ; k, v: [B, Sl, Hkv, Dh]
    qpos: [B, Tl] ; kpos, kval: [B, Sl]
    m, l: [B, Hkv, G, Tl, 1] ; acc: [B, Hkv, G, Tl, Dh]
    """
    s = jnp.einsum("bhgtd,bshd->bhgts", qg, k,
                   preferred_element_type=jnp.float32) * scale
    mask = (kval[:, None, None, None, :]
            & (kpos[:, None, None, None, :]
               <= qpos[:, None, None, :, None]))
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # explicit mask on p: with the finite NEG_INF sentinel, a fully-masked
    # row would otherwise contribute exp(0) = 1 per position
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m - m_new)
    l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum("bhgts,bshd->bhgtd",
                                   p.astype(v.dtype), v)
    return m_new, l, acc


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   q_pos: jax.Array, k_pos: jax.Array, k_valid: jax.Array,
                   mesh: Mesh, axis: str = AXIS_SP,
                   head_axis: Optional[str] = None,
                   scale: Optional[float] = None) -> jax.Array:
    """Sequence-parallel attention with explicit positions.

    q: [B, T, Hq, Dh] ; k, v: [B, S, Hkv, Dh] ; q_pos: [B, T] int32 ;
    k_pos: [B, S] int32 ; k_valid: [B, S] bool. T and S must divide by the
    ``axis`` size. Returns [B, T, Hq, Dh] in q.dtype.

    Call under jit with global arrays; shard_map internally splits T and S
    over ``axis`` and rotates KV shards around the ring. With ``head_axis``
    (tp) set, heads additionally stay sharded — both Hq and Hkv must divide
    by that axis so GQA groups stay aligned per shard.
    """
    sp = mesh.shape[axis]
    if scale is None:
        scale = 1.0 / (math.sqrt(q.shape[-1]))
    if head_axis is not None:
        hp = mesh.shape[head_axis]
        if q.shape[2] % hp or k.shape[2] % hp:
            raise ValueError(
                f"head_axis={head_axis} ({hp}) must divide Hq={q.shape[2]} "
                f"and Hkv={k.shape[2]}")

    def local(q, k, v, qpos, kpos, kval):
        # shapes here are PER-SHARD: T/sp, and heads/tp when head-sharded
        B, Tl, Hq_l, Dh = q.shape
        Hkv_l = k.shape[2]
        G = Hq_l // Hkv_l
        qg = q.reshape(B, Tl, Hkv_l, G, Dh).transpose(0, 2, 3, 1, 4)
        m = jnp.full((B, Hkv_l, G, Tl, 1), NEG_INF, jnp.float32)
        l = jnp.zeros((B, Hkv_l, G, Tl, 1), jnp.float32)
        acc = jnp.zeros((B, Hkv_l, G, Tl, Dh), jnp.float32)
        perm = [(j, (j + 1) % sp) for j in range(sp)]

        # python loop: sp is static and small; lets XLA overlap the
        # ppermute of step i+1's shard with step i's compute
        carry = (k, v, kpos, kval, m, l, acc)
        for i in range(sp):
            k, v, kpos, kval, m, l, acc = carry
            m, l, acc = _online_update(qg, k, v, qpos, kpos, kval,
                                       scale, m, l, acc)
            if sp > 1 and i < sp - 1:
                k, v, kpos, kval = (
                    jax.lax.ppermute(x, axis, perm)
                    for x in (k, v, kpos, kval))
            carry = (k, v, kpos, kval, m, l, acc)
        out = acc / jnp.where(l == 0.0, 1.0, l)
        return out.transpose(0, 3, 1, 2, 4).reshape(B, Tl, Hq_l, Dh) \
                  .astype(q.dtype)

    if sp == 1 and head_axis is None:
        return local(q, k, v, q_pos, k_pos, k_valid)

    seq = P(None, axis)
    seq4 = P(None, axis, head_axis, None)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(seq4, seq4, seq4, seq, seq, seq),
        out_specs=seq4,
    )(q, k, v, q_pos, k_pos, k_valid)
