"""FleetPlane: the binding that turns the planner into an N-model-pool
reconciler.

The planner loop stays the planner loop — observe, decide, publish,
actuate. The plane hooks it at three points:

- :meth:`sync` (start of every tick): the pool set, per-pool clamps,
  model-scoped signal wiring and connector pool specs all follow the
  live :class:`~.registry.FleetRegistry`. ``ctl fleet add`` mid-traffic
  means the NEXT tick already reconciles the new model; ``remove`` means
  the next tick drains its owned workers and forgets its damping state.
- :meth:`arbitrate` (between decide and actuate): every pool's clamped
  target passes through the :class:`~.arbiter.ChipArbiter` so the joint
  plan fits the global chip budget; reductions land on the Decision
  record (``suppressed="chip_budget"``, reason naming who outbid whom)
  exactly like cooldowns and clamps do.
- :meth:`publish_status` (end of tick): one lease-bound
  ``fleet_status/{ns}/{model}`` record per model (replicas, target,
  ready/booting/draining/off, chips, burn) — what ``GET /v1/models``,
  ``dyntop`` and ``plannerctl`` render. Dying with the planner's lease
  is deliberate: a stale status is worse than an absent one.

Cold-boot ordering is the loop's half of the contract: scale-ups actuate
before scale-downs in the same tick, so a preempted-into-existence
model's worker is already loading weights while the donor pool drains —
PRESERVE's overlap argument applied to scale-to-zero.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

from ..planner.policy import HOLD, SCALE_DOWN, SCALE_UP, Decision
from ..planner.signals import PoolSignals
from .arbiter import SUPPRESSED_CHIP_BUDGET, ChipArbiter, PoolClaim
from .mobility.keys import mobility_prefetch_key, mobility_wake_prefix
from .registry import (
    STATE_BOOTING,
    STATE_DRAINING,
    STATE_OFF,
    STATE_READY,
    FleetModelSpec,
    FleetRegistry,
    publish_fleet_status,
)

log = logging.getLogger("dynamo_tpu.fleet")


class FleetPlane:
    """See module docstring. ``worker_env`` is merged into every spawned
    model worker's environment (the soak harnesses pass store knobs)."""

    def __init__(self, store, namespace: str, total_chips: int = 4,
                 arbiter: Optional[ChipArbiter] = None,
                 worker_env: Optional[Dict[str, str]] = None):
        self.store = store
        self.namespace = namespace
        self.registry = FleetRegistry(store, namespace)
        self.arbiter = arbiter or ChipArbiter(total_chips)
        self.worker_env = dict(worker_env or {})
        self._last_targets: Dict[str, int] = {}
        # per-component prefetch hints last written (change-gated so the
        # store sees one write per hint change, not one per tick)
        self._last_hints: Dict[str, str] = {}

    async def start(self) -> "FleetPlane":
        await self.registry.start()
        return self

    # ------------------------------------------------------------------
    def pool_spec(self, spec: FleetModelSpec):
        """LocalConnector PoolSpec for one model: its own component, its
        model identity registered so discovery serves it the moment the
        worker is up."""
        from ..planner.connectors import PoolSpec

        extra = ["--model-name", spec.name, "--register-model"]
        if spec.model_path:
            extra += ["--model-path", spec.model_path]
        extra += list(spec.extra_args)
        return PoolSpec(component=spec.component,
                        chips=spec.chips_per_replica,
                        engine=spec.engine, extra_args=extra,
                        env=dict(self.worker_env))

    async def sync(self, planner) -> None:
        """Reconcile the planner's pool set with the registry (start of
        every tick). ``planner`` is the :class:`~..planner.loop.Planner`."""
        specs = self.registry.snapshot()
        new_pools = {name: s.component for name, s in specs.items()}
        if new_pools != planner.pools:
            removed = set(planner.pools) - set(new_pools)
            # a model re-added under a DIFFERENT component is a remove +
            # add: the old component's workers must drain (they'd
            # otherwise run — and hold chips — forever, invisible to
            # both the collector and the arbiter) and the pool's
            # damping/signal state belongs to the old pool
            moved = {p for p in new_pools
                     if p in planner.pools
                     and planner.pools[p] != new_pools[p]}
            for pool in removed | moved:
                log.info("fleet: draining %s model pool %s",
                         "moved" if pool in moved else "removed", pool)
                planner.core.forget_pool(pool)
                planner.collector.forget_pool(pool)
                self._last_targets.pop(pool, None)
                remove = getattr(planner.connector, "remove_pool", None)
                if remove is not None:
                    await remove(pool)
            planner.pools = dict(new_pools)
            planner.collector.pools = dict(new_pools)
        planner.collector.pool_models = {name: name for name in new_pools}
        planner.core.set_pool_clamps(
            {name: (s.min_replicas, s.max_replicas)
             for name, s in specs.items()})
        set_pool = getattr(planner.connector, "set_pool", None)
        if set_pool is not None:
            for name, s in specs.items():
                set_pool(name, self.pool_spec(s))
        await self.publish_prefetch_hints()

    async def publish_prefetch_hints(self) -> None:
        """Per-component weight-prefetch hints under ``mobility/``: each
        model's workers stage (a) every sibling sharing its non-empty
        ``swap_group`` — the models a preemption could swap in — and
        (b) every ``prewarm`` model (``ctl fleet add --prewarm``).
        Change-gated: the hint key is written only when its content
        moves, and deleted when a component's hint set empties or the
        model leaves the registry."""
        specs = self.registry.snapshot()
        live: Dict[str, str] = {}
        for name, s in specs.items():
            hints = []
            for other, o in sorted(specs.items()):
                if other == name or not o.model_path:
                    continue
                if ((s.swap_group and o.swap_group == s.swap_group)
                        or o.prewarm):
                    hints.append({"model": other,
                                  "model_path": o.model_path})
            key = mobility_prefetch_key(self.namespace, s.component)
            live[key] = json.dumps({"models": hints}) if hints else ""
        stale = [k for k in self._last_hints if k not in live]
        for key, blob in live.items():
            if self._last_hints.get(key) == blob:
                continue
            if blob:
                await self.store.put(key, blob.encode())
            else:
                await self.store.delete(key)
            self._last_hints[key] = blob
        for key in stale:
            if self._last_hints.pop(key, ""):
                await self.store.delete(key)

    # ------------------------------------------------------------------
    def arbitrate(self, decisions: List[Decision],
                  signals: Dict[str, PoolSignals]) -> List[Decision]:
        """Clamp the joint plan to the chip budget; annotate reductions.
        Non-fleet pools (not in the registry) pass through untouched."""
        specs = self.registry.snapshot()
        claims = []
        for d in decisions:
            s = specs.get(d.pool)
            if s is None:
                continue
            sig = signals.get(d.pool)
            claims.append(PoolClaim(
                model=d.pool, want=d.target, current=d.current,
                chips_per_replica=s.chips_per_replica,
                min_replicas=s.min_replicas, priority=s.priority,
                burn=sig.slo_pressure if sig is not None else 0.0,
                swap_group=s.swap_group))
        if not claims:
            return decisions
        grants = self.arbiter.grant(claims)
        for d in decisions:
            granted = grants.get(d.pool)
            if granted is None:
                continue
            target, reason = granted
            if target == d.target:
                continue
            # the budget overrode the damped policy target — including,
            # deliberately, cooldown/hold decisions: preemption exists to
            # move chips NOW, when another model's burn demands them
            d.target = target
            d.suppressed = SUPPRESSED_CHIP_BUDGET
            if reason:
                d.reason = f"{d.reason}; {reason}" if d.reason else reason
            d.action = (SCALE_UP if d.target > d.current
                        else SCALE_DOWN if d.target < d.current else HOLD)
        return decisions

    # ------------------------------------------------------------------
    async def actuate_swaps(self, decisions: List[Decision],
                            connector) -> None:
        """Convert same-``swap_group`` chip handoffs into in-place weight
        swaps. Runs between :meth:`arbitrate` and actuation: when the
        arbitrated plan scales model B up while scaling its hot-swap
        sibling A down, one of A's workers is told (via the connector's
        ``swap_pool``) to overwrite its weights in place and re-register
        as B — seconds instead of drain + cold spawn. The paired
        decisions are annotated (``swap_in``/``swap_out``) so the
        connector neither spawns a worker the swap already provides nor
        SIGTERMs the worker that is leaving by swap. At most one swap per
        donor component per tick (the command key holds a single
        claim-by-delete record); the planner reconverges across ticks.
        Connectors without ``swap_pool`` (Kube, Null) fall back to the
        plain spawn/drain path untouched."""
        swap_pool = getattr(connector, "swap_pool", None)
        if swap_pool is None:
            return
        specs = self.registry.snapshot()

        def _swappable(d, action):
            s = specs.get(d.pool)
            return (s is not None and s.swap_group
                    and d.action == action and not d.dry_run)

        ups = [d for d in decisions if _swappable(d, SCALE_UP)]
        downs = [d for d in decisions if _swappable(d, SCALE_DOWN)]
        for up in ups:
            s_up = specs[up.pool]
            if not s_up.model_path:
                continue        # nothing to stage — echo/test pools
            for down in downs:
                s_down = specs[down.pool]
                if s_down.swap_group != s_up.swap_group:
                    continue
                need = (up.target - up.current
                        - getattr(up, "swap_in", 0))
                give = (down.current - down.target
                        - getattr(down, "swap_out", 0))
                if need <= 0 or give <= 0:
                    continue
                payload = {
                    "model": s_up.name,
                    "component": s_up.component,
                    "model_path": s_up.model_path,
                    "from": down.pool,
                    "issued_at": time.time(),
                }
                issued = await swap_pool(
                    self.store, self.namespace, down.pool,
                    s_down.component, payload)
                if not issued:
                    continue
                up.swap_in = getattr(up, "swap_in", 0) + issued
                down.swap_out = getattr(down, "swap_out", 0) + issued
                note = (f"swap {down.pool}->{up.pool} "
                        f"(group {s_up.swap_group})")
                up.reason = f"{up.reason}; {note}" if up.reason else note
                log.info("fleet mobility: %s", note)

    # ------------------------------------------------------------------
    @staticmethod
    def model_state(replicas: int, target: int) -> str:
        if target > replicas:
            return STATE_BOOTING
        if target < replicas:
            return STATE_DRAINING
        return STATE_READY if replicas > 0 else STATE_OFF

    async def publish_status(self, drt, decisions: List[Decision],
                             signals: Dict[str, PoolSignals]) -> None:
        """One lease-bound status record per registered model."""
        for d in decisions:
            if d.pool in self.registry.models:
                self._last_targets[d.pool] = d.target
        wakes: Dict[str, Dict] = {}
        for key, value in await drt.store.get_prefix(
                mobility_wake_prefix(self.namespace)):
            try:
                wakes[key.rsplit("/", 1)[1]] = json.loads(value.decode())
            except (ValueError, json.JSONDecodeError):
                log.warning("skipping malformed wake record %s", key)
        for name, spec in self.registry.snapshot().items():
            sig = signals.get(name)
            replicas = sig.replicas if sig is not None else 0
            target = self._last_targets.get(name, replicas)
            status = {
                "component": spec.component,
                "state": self.model_state(replicas, target),
                "replicas": replicas,
                "target": target,
                "chips": replicas * spec.chips_per_replica,
                "chips_per_replica": spec.chips_per_replica,
                "priority": spec.priority,
                "burn": round(sig.slo_pressure, 3) if sig else 0.0,
                "unserved": sig.unserved if sig else 0.0,
            }
            wake = wakes.get(name)
            if wake:
                # last observed wake (published by the worker's mobility
                # agent): how this model most recently came up —
                # "swap" (in-place weight swap) or "cold" (full boot)
                status["wake_path"] = wake.get("path")
                status["wake_seconds"] = wake.get("seconds")
            await publish_fleet_status(drt.store, self.namespace, name,
                                       status, lease=drt.lease)
