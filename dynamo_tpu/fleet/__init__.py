"""Fleet plane: one cluster, many models, per-tenant SLOs.

The reference serves many models from one deployment — ``llmctl http
add/remove`` makes model membership a *runtime* operation backed by a
``ModelDeploymentCard``. This package is that capability generalized into
a desired-state control plane:

- :mod:`registry` — the store-backed model registry (``fleet_models/``
  desired state written by ``ctl fleet add``, ``fleet_status/`` observed
  state published lease-bound by the planner) plus the live
  :class:`~registry.FleetRegistry` watcher every consumer arms;
- :mod:`arbiter` — the pure chip-arbitration core: N model pools, one
  global chip budget, allocation ordered by (priority, SLO burn) with a
  preemption-hysteresis margin so a borderline burn difference does not
  thrash replicas between models;
- :mod:`plane` — :class:`~plane.FleetPlane`, the binding that turns the
  existing single-pool-pair planner into an N-model-pool reconciler:
  pools/clamps/connector specs follow the registry live, decisions pass
  through the arbiter, scale-ups actuate before scale-downs so a cold
  boot's weight load overlaps the donor pool's drain (PRESERVE's
  overlap argument applied to scale-to-zero).
"""

from .arbiter import ChipArbiter
from .registry import (
    FLEET_MODELS_PREFIX,
    FLEET_STATUS_PREFIX,
    FleetModelSpec,
    FleetRegistry,
    fleet_model_key,
    fleet_models_prefix,
    fleet_status_key,
    fleet_status_prefix,
    get_fleet_model,
    list_fleet_models,
    put_fleet_model,
    remove_fleet_model,
)
from .plane import FleetPlane

__all__ = [
    "ChipArbiter",
    "FLEET_MODELS_PREFIX",
    "FLEET_STATUS_PREFIX",
    "FleetModelSpec",
    "FleetPlane",
    "FleetRegistry",
    "fleet_model_key",
    "fleet_models_prefix",
    "fleet_status_key",
    "fleet_status_prefix",
    "get_fleet_model",
    "list_fleet_models",
    "put_fleet_model",
    "remove_fleet_model",
]
