"""Cross-model chip arbitration: N pools, one budget, burn decides.

The per-pool policies (``planner/policy.py``) each propose replicas as if
their model owned the cluster; the arbiter makes those proposals
*jointly* feasible under the global chip budget. Allocation runs in two
deterministic passes:

**Tiered grant** — chips are handed out one replica at a time, tier by
tier, entitlement-ordered (priority desc, burn desc, name) inside each
tier:

1. *floors*: every model's ``min_replicas`` (the operator's availability
   promise — never arbitrated away while the budget physically allows);
2. *retention*: up to each model's live replica count (what a model is
   already using is granted before anyone grows);
3. *growth*: the rest of the budget, round-robin in entitlement order so
   equal claimants split leftover chips instead of the first starving
   its peers.

**Preemption** — after the tiered grant, a growth-starved model whose
SLO burn exceeds a retention-holding model's burn by
``DYN_FLEET_PREEMPT_MARGIN`` (or whose priority class is strictly
higher) takes chips from it: the victim's grant shrinks toward its
floor, the beneficiary boots. The margin is hysteresis — a preemption
costs a drain plus a cold boot, so a borderline burn difference must not
thrash replicas between models every tick. This is the "scale model A
down to boot model B when B's burn is worse" rule.

Chip-exempt pools (``chips_per_replica == 0``: CPU echo fleets, test
fixtures) pass through untouched — the budget constrains accelerators,
not processes.

The arbiter is pure and clock-free: claims in, grants out, every
reduction annotated so ``plannerctl decisions`` shows the arbitration
the same way it shows cooldowns and clamps.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.knobs import env_float

log = logging.getLogger("dynamo_tpu.fleet")

#: Decision.suppressed tag for budget-reduced targets
SUPPRESSED_CHIP_BUDGET = "chip_budget"


@dataclass
class PoolClaim:
    """One model pool's input to the arbitration round."""

    model: str
    want: int                 # clamped policy target (replicas)
    current: int              # live replicas
    chips_per_replica: int
    min_replicas: int
    priority: int = 0
    burn: float = 0.0         # worst SLO burn (0 = within budget)
    #: model-mobility swap class ("" = not swap-capable): a victim whose
    #: swap_group matches the beneficiary's hands its chips over by
    #: in-place weight swap — seconds instead of drain + cold spawn
    swap_group: str = ""

    @property
    def rank(self) -> Tuple:
        """Entitlement sort key (ascending = most entitled first)."""
        return (-self.priority, -self.burn, self.model)


class ChipArbiter:
    """Grant replicas under ``budget`` chips; see module docstring."""

    def __init__(self, budget: int, preempt_margin: Optional[float] = None):
        self.budget = max(int(budget), 0)
        self.preempt_margin = (
            env_float("DYN_FLEET_PREEMPT_MARGIN", 0.5, minimum=0.0)
            if preempt_margin is None else float(preempt_margin))

    def _outranks(self, hot: PoolClaim, victim: PoolClaim) -> bool:
        """May ``hot`` preempt ``victim``? Strictly higher priority
        always wins; within a class the burn gap must clear the margin."""
        if hot.priority != victim.priority:
            return hot.priority > victim.priority
        return hot.burn > victim.burn + self.preempt_margin

    # ------------------------------------------------------------------
    def grant(self, claims: List[PoolClaim]
              ) -> Dict[str, Tuple[int, Optional[str]]]:
        """{model: (granted_replicas, reason_or_None)} — reason is set
        only when the grant came out below the claim's ``want``."""
        out: Dict[str, Tuple[int, Optional[str]]] = {
            c.model: (c.want, None) for c in claims
            if c.chips_per_replica <= 0}          # budget-exempt
        paying = sorted((c for c in claims if c.chips_per_replica > 0),
                        key=lambda c: c.rank)
        if not paying:
            return out

        granted = {c.model: 0 for c in paying}
        left = self.budget

        def tier(target) -> None:
            nonlocal left
            hungry = list(paying)
            while hungry:
                progressed = False
                for c in list(hungry):
                    if (granted[c.model] >= min(target(c), c.want)
                            or c.chips_per_replica > left):
                        hungry.remove(c)
                        continue
                    granted[c.model] += 1
                    left -= c.chips_per_replica
                    progressed = True
                if not progressed:
                    break

        tier(lambda c: c.min_replicas)
        tier(lambda c: c.current)
        tier(lambda c: c.want)

        for c in paying:
            if granted[c.model] < min(c.min_replicas, c.want):
                log.warning(
                    "fleet arbiter: budget %d cannot cover %s's "
                    "min_replicas floor (%d x %d chips)", self.budget,
                    c.model, c.min_replicas, c.chips_per_replica)

        # ---- preemption: hot growth-starved models take retention
        # chips from colder models (toward their floors), margin-gated.
        # Each attempt is transactional: victims are drained only if the
        # freed chips actually complete a whole replica for the
        # beneficiary — a partial drain would cost the victim capacity
        # while the chips sit stranded (nobody they'd fit).
        preempted: Dict[str, str] = {}    # victim -> beneficiary
        blocked: set = set()              # hot models preemption can't help
        for _ in range(sum(c.want for c in paying) + len(paying)):
            hot = next((c for c in paying            # entitlement order
                        if granted[c.model] < c.want
                        and c.model not in blocked), None)
            if hot is None:
                break
            snapshot, left0 = dict(granted), left
            drained: List[PoolClaim] = []
            while left < hot.chips_per_replica:
                # eligible victims, coldest first; among them, a hot-swap
                # sibling (same non-empty swap_group) is preferred — its
                # chips hand over by in-place weight swap, which costs
                # seconds, while any other victim costs drain + cold
                # spawn. Entitlement order is preserved WITHIN each class
                # so the preference never drains a hotter sibling when an
                # equally-preemptible colder one exists.
                eligible = [v for v in reversed(paying)
                            if v.model != hot.model
                            and granted[v.model] > v.min_replicas
                            and self._outranks(hot, v)]
                victim = next(
                    (v for v in eligible
                     if v.swap_group and v.swap_group == hot.swap_group),
                    eligible[0] if eligible else None)
                if victim is None:
                    break
                granted[victim.model] -= 1
                left += victim.chips_per_replica
                drained.append(victim)
            if left < hot.chips_per_replica:
                # couldn't complete a replica: roll the drain back and
                # stop considering this claim (a smaller-chip hungry
                # model may still preempt successfully)
                granted, left = snapshot, left0
                blocked.add(hot.model)
                continue
            granted[hot.model] += 1
            left -= hot.chips_per_replica
            for v in drained:
                preempted[v.model] = hot.model

        for c in paying:
            got = granted[c.model]
            if got >= c.want:
                out[c.model] = (got, None)
            elif c.model in preempted:
                b = next(x for x in paying if x.model == preempted[c.model])
                why = (f"priority {b.priority} vs {c.priority}"
                       if b.priority != c.priority
                       else f"burn {b.burn:.2f} vs {c.burn:.2f}")
                out[c.model] = (got, (
                    f"chip budget {self.budget}: yielded to "
                    f"{b.model} ({why})"))
            else:
                out[c.model] = (got, (
                    f"chip budget {self.budget}: {c.want} replicas x "
                    f"{c.chips_per_replica} chip(s) does not fit"))
        return out
