"""Desired-state model registry: the store is the source of truth for
*which models this namespace serves* and *on what terms*.

Two keyspace families (see ``runtime/keyspace.py``):

``fleet_models/{ns}/{model}`` — **persistent** desired state, mutated by
``ctl fleet add/remove`` (and any operator tooling). One
:class:`FleetModelSpec` per model: the ModelDeploymentCard reference,
worker component + engine + chip shape, min/max replicas, priority, and
the per-tenant quota table. Persistence is deliberate: the registry must
survive every process — a planner restart re-reads the desired fleet, it
does not forget it.

``fleet_status/{ns}/{model}`` — **lease-bound** observed state written by
whichever planner currently reconciles the fleet (replicas, target,
ready/booting/draining/off, chips, worst burn). Dying with the planner's
lease is the point: a stale status is worse than an absent one, and the
frontends rendering ``GET /v1/models`` fall back to "registered but
unobserved" cleanly.

:class:`FleetRegistry` is the live watcher every consumer arms (planner,
fleet router, frontend): a prefix watch + snapshot into a plain dict,
with an ``on_change`` hook for consumers that need to react (the fleet
router adds/removes per-model routing state).
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..utils.overload import TenantQuota

log = logging.getLogger("dynamo_tpu.fleet")

FLEET_MODELS_PREFIX = "fleet_models/"
FLEET_STATUS_PREFIX = "fleet_status/"

_NAME_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")

#: observed lifecycle states published to fleet_status/
STATE_OFF = "off"              # scaled to zero by design
STATE_BOOTING = "booting"      # target > live replicas (weights loading)
STATE_READY = "ready"          # live replicas match the target
STATE_DRAINING = "draining"    # target < live replicas


def fleet_models_prefix(namespace: str) -> str:
    return f"{FLEET_MODELS_PREFIX}{namespace}/"


def fleet_model_key(namespace: str, model: str) -> str:
    return f"{FLEET_MODELS_PREFIX}{namespace}/{model}"


def fleet_status_prefix(namespace: str) -> str:
    return f"{FLEET_STATUS_PREFIX}{namespace}/"


def fleet_status_key(namespace: str, model: str) -> str:
    return f"{FLEET_STATUS_PREFIX}{namespace}/{model}"


@dataclass
class FleetModelSpec:
    """One model's desired state — everything the planner, router and
    frontend need to serve it, in one record."""

    name: str
    #: store component the model's worker pool registers as; every model
    #: gets its own pool so routing/metrics/KV events stay model-scoped
    component: str = ""
    engine: str = "echo"
    model_path: Optional[str] = None
    #: chips one replica occupies — the arbiter's unit of account.
    #: 0 = exempt from the chip budget (CPU echo pools, test fixtures)
    chips_per_replica: int = 1
    min_replicas: int = 0           # 0 = scale-to-zero allowed
    max_replicas: int = 4
    #: arbitration rank: higher-priority models take chips first when the
    #: budget is short, burn breaking ties within a priority class
    priority: int = 0
    #: per-tenant admission quotas enforced at HTTP ingress; merged
    #: across models (max per field) into the frontend's live quota table
    tenants: Dict[str, TenantQuota] = field(default_factory=dict)
    #: serialized ModelDeploymentCard (ctl fleet add --model-path embeds
    #: the resolved card so workers/frontends need no filesystem access)
    card: Optional[Dict[str, Any]] = None
    #: extra worker argv (planner LocalConnector pass-through)
    extra_args: List[str] = field(default_factory=list)
    #: model-mobility swap class: models sharing a non-empty swap_group
    #: are hot-swap siblings — a preemption can hand a victim's chips to
    #: the beneficiary by in-place weight swap instead of spawn + drain,
    #: and workers prefetch siblings' weights into the host cache
    swap_group: str = ""
    #: prewarm hint (``ctl fleet add --prewarm``): every worker in the
    #: namespace stages this model's weights even across swap groups
    prewarm: bool = False

    def __post_init__(self) -> None:
        # the name is a store-key path segment, a metric label, a pool
        # id and a component-name stem — a '/' (HF-style "org/model")
        # would make the registry key's last segment diverge from the
        # spec name and strand the record. Same charset as tenant ids.
        if (not self.name or len(self.name) > 64
                or not set(self.name) <= _NAME_CHARS):
            raise ValueError(
                f"fleet model name {self.name!r}: expected 1-64 chars of "
                f"[A-Za-z0-9._-] (use --model-path for the checkpoint "
                f"location; the name is the serving id)")
        if not self.component:
            self.component = f"backend-{self.name}"
        if self.min_replicas < 0 or self.max_replicas < max(
                self.min_replicas, 1):
            raise ValueError(
                f"model {self.name!r}: bad replica range "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if self.chips_per_replica < 0:
            raise ValueError(f"model {self.name!r}: negative chip shape")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "component": self.component,
            "engine": self.engine,
            "model_path": self.model_path,
            "chips_per_replica": self.chips_per_replica,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "priority": self.priority,
            "tenants": {t: q.to_dict() for t, q in self.tenants.items()},
            "card": self.card,
            "extra_args": list(self.extra_args),
            "swap_group": self.swap_group,
            "prewarm": self.prewarm,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FleetModelSpec":
        kw = {k: v for k, v in d.items() if k in cls.__dataclass_fields__}
        kw["tenants"] = {t: TenantQuota.from_dict(q)
                         for t, q in (d.get("tenants") or {}).items()}
        kw["extra_args"] = list(d.get("extra_args") or [])
        return cls(**kw)


# ---------------------------------------------------------------------------
# one-shot accessors (ctl, tests)
# ---------------------------------------------------------------------------
async def put_fleet_model(store, namespace: str,
                          spec: FleetModelSpec) -> None:
    await store.put(fleet_model_key(namespace, spec.name),
                    json.dumps(spec.to_dict()).encode())


async def get_fleet_model(store, namespace: str,
                          model: str) -> Optional[FleetModelSpec]:
    raw = await store.get(fleet_model_key(namespace, model))
    if not raw:
        return None
    return FleetModelSpec.from_dict(json.loads(raw.decode()))


async def remove_fleet_model(store, namespace: str, model: str) -> None:
    """Drop the desired-state record AND the (possibly stale) status —
    the planner's next tick drains the pool to zero."""
    await store.delete(fleet_model_key(namespace, model))
    await store.delete(fleet_status_key(namespace, model))


async def list_fleet_models(store, namespace: str) -> List[FleetModelSpec]:
    out: List[FleetModelSpec] = []
    for key, value in await store.get_prefix(fleet_models_prefix(namespace)):
        try:
            out.append(FleetModelSpec.from_dict(json.loads(value.decode())))
        except (ValueError, json.JSONDecodeError):
            log.warning("skipping malformed fleet record %s", key)
    return sorted(out, key=lambda s: s.name)


async def publish_fleet_status(store, namespace: str, model: str,
                               status: Dict[str, Any],
                               lease: Optional[int] = None) -> None:
    """Lease-bound observed state (dead planner => status expires)."""
    payload = dict(status)
    payload.setdefault("model", model)
    payload["ts"] = time.time()
    await store.put(fleet_status_key(namespace, model),
                    json.dumps(payload).encode(), lease=lease)


async def fetch_fleet_status(store, namespace: str) -> Dict[str, Dict]:
    """{model: status} — whatever statuses a live planner has published."""
    out: Dict[str, Dict] = {}
    for key, value in await store.get_prefix(fleet_status_prefix(namespace)):
        model = key.rsplit("/", 1)[1]
        try:
            out[model] = json.loads(value.decode())
        except (ValueError, json.JSONDecodeError):
            log.warning("skipping malformed fleet status %s", key)
    return out


class FleetRegistry:
    """Live view of the desired fleet: prefix watch + snapshot into
    ``self.models``. ``on_change(name, spec_or_None)`` fires per record
    mutation (None = removed) AFTER the dict is updated."""

    def __init__(self, store, namespace: str):
        self.store = store
        self.namespace = namespace
        self.models: Dict[str, FleetModelSpec] = {}
        self.on_change: Optional[Callable[[str, Optional[FleetModelSpec]],
                                          None]] = None
        self._started = False

    async def start(self) -> "FleetRegistry":
        prefix = fleet_models_prefix(self.namespace)
        # live events win over the snapshot for keys they already touched
        # (same discipline as KvClusterIndex: a delete racing the watch
        # registration must not resurrect the record)
        touched: set = set()

        async def on_event(key: str, value: Optional[bytes],
                           deleted: bool) -> None:
            touched.add(key)
            self._apply(key, value, deleted)

        snapshot = await self.store.watch_prefix(prefix, on_event)
        for key, value in snapshot:
            if key not in touched:
                self._apply(key, value, False)
        self._started = True
        return self

    def _apply(self, key: str, value: Optional[bytes],
               deleted: bool) -> None:
        name = key.rsplit("/", 1)[1]
        if deleted or not value:
            if self.models.pop(name, None) is not None:
                log.info("fleet: model %s removed from registry", name)
                self._notify(name, None)
            return
        try:
            spec = FleetModelSpec.from_dict(json.loads(value.decode()))
        except (ValueError, json.JSONDecodeError):
            log.warning("fleet: ignoring malformed record %s", key)
            return
        self.models[name] = spec
        self._notify(name, spec)

    def _notify(self, name: str, spec: Optional[FleetModelSpec]) -> None:
        if self.on_change is None:
            return
        try:
            self.on_change(name, spec)
        except Exception:  # noqa: BLE001 - consumer hook must not kill watch
            log.exception("fleet on_change hook failed for %s", name)

    def snapshot(self) -> Dict[str, FleetModelSpec]:
        return dict(self.models)

    def tenant_quotas(self) -> Dict[str, TenantQuota]:
        """The fleet-wide tenant quota table: per-model tables merged by
        taking each field's max across models — a tenant's ingress
        allowance is its most generous grant (admission happens before
        the model is known, so the per-model grant cannot be applied
        until routing; the max is the sound pre-body bound)."""
        merged: Dict[str, TenantQuota] = {}
        for spec in self.models.values():
            for tenant, q in spec.tenants.items():
                cur = merged.get(tenant)
                if cur is None:
                    merged[tenant] = TenantQuota(rps=q.rps, burst=q.burst,
                                                 concurrency=q.concurrency)
                else:
                    merged[tenant] = TenantQuota(
                        rps=max(cur.rps, q.rps),
                        burst=max(cur.burst, q.burst),
                        concurrency=max(cur.concurrency, q.concurrency))
        return merged
