"""In-place engine hot-swap: overwrite donated weight buffers from host.

The compiled bucket programs (prefill/decode/verify) take ``params`` as
an *argument* but close over the engine config, so a sibling checkpoint
whose layer-class shape signature matches can be swapped in by
overwriting the weight buffers — the SAME XLA programs keep serving and
``dyn_compiled_programs`` stays flat. The signature therefore covers
every compute-affecting field: the full model config plus the engine
geometry the programs were bucketed against.

Swap sequencing (engine thread, post-drain):

1. gate — tree structure + shape signature must match, else a typed
   :class:`SwapError` (the agent falls back to a counted full reload);
2. demote — sealed device KV blocks flush to the host tier (the cluster
   plane keeps serving them through the drain window);
3. overwrite — per layer-group h2d uploads feed donated in-place slab
   scatters (``CopyStream.h2d_param_slab``), enqueued async so the
   device streams weights while the host...
4. ...tears down KV state: tiered cache + hash registries clear (block
   hashes carry no model identity — old-model KV must not alias), then
   one barrier on the new params.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any, Dict, Optional

import numpy as np

from ...utils.knobs import env_float

log = logging.getLogger("dynamo_tpu.mobility")

#: layers per h2d group (DYN_SWAP_GROUP_LAYERS overrides)
DEFAULT_GROUP_LAYERS = 4


class SwapError(RuntimeError):
    """Typed swap refusal; ``reason`` is one of ``shape_mismatch`` |
    ``not_drained`` | ``weights_unavailable`` | ``unsupported``."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


@dataclasses.dataclass
class SwapOutcome:
    path: str                 # "swap" (in-place) | "cold" (full reload)
    seconds: float
    model_path: Optional[str]
    groups: int = 0           # layer-group h2d scatters issued
    demoted_blocks: int = 0   # sealed KV blocks demoted to the host tier

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def swap_signature(cfg) -> str:
    """The layer-class shape signature of a :class:`JaxEngineConfig`:
    equal signatures guarantee every compiled bucket program of one
    engine is valid for the other's params. Covers the full model config
    (all fields affect either shapes or the traced compute) and the
    engine geometry the bucket grids were derived from — NOT
    ``params_path``/``preset``/``seed``, which only name the weights."""
    m = dataclasses.asdict(cfg.model)
    geom = {
        "tp": cfg.tp, "sp": cfg.sp, "ep": cfg.ep, "pp": cfg.pp,
        "page_size": cfg.page_size, "max_batch": cfg.max_batch,
        "max_context": cfg.max_context,
        "prefill_chunk": cfg.prefill_chunk,
        "num_pages": cfg.num_pages, "decode_steps": cfg.decode_steps,
        "prefill_lanes": cfg.prefill_lanes, "attn_impl": cfg.attn_impl,
        "spec": cfg.spec or None, "spec_k": cfg.spec_k,
        "spec_draft": cfg.spec_draft,
    }
    return json.dumps({"model": m, "geom": geom}, sort_keys=True,
                      default=str)


def _flat(tree) -> Dict[tuple, Any]:
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {tuple(str(getattr(k, "key", k)) for k in path): leaf
            for path, leaf in leaves}


def hot_swap(core, host_params, new_cfg, group_layers: Optional[int] = None
             ) -> SwapOutcome:
    """Overwrite ``core``'s weight buffers with ``host_params`` (the new
    model's host tree) in place. Engine-thread only, post-drain. Raises
    :class:`SwapError` (typed, never hangs) when the swap cannot reuse
    the compiled programs; the caller then takes the full-reload path."""
    t0 = time.monotonic()
    if core.by_seq or core.waiting or core._inflight \
            or core._stream_injects:
        raise SwapError("not_drained",
                        f"{len(core.by_seq)} active, "
                        f"{len(core.waiting)} waiting")
    if swap_signature(core.cfg) != swap_signature(new_cfg):
        raise SwapError("shape_mismatch",
                        "engine geometry/model config differs")
    if core.cfg.model.vision is not None:
        # the vision tower's params are separate jits keyed off tower
        # weights; swapping only the LM half would serve mismatched
        # encoders — take the full reload path
        raise SwapError("unsupported", "VLM engines reload cold")
    new_flat = _flat(host_params)
    old_flat = _flat(core.params)
    if set(new_flat) != set(old_flat):
        missing = set(old_flat) ^ set(new_flat)
        raise SwapError("shape_mismatch",
                        f"param tree differs at {sorted(missing)[:4]}")
    for path, leaf in old_flat.items():
        if tuple(new_flat[path].shape) != tuple(leaf.shape):
            raise SwapError(
                "shape_mismatch",
                f"{'/'.join(path)}: {new_flat[path].shape} vs "
                f"{leaf.shape}")

    # ---- demote sealed KV to the host tier (drain-window serving) ----
    demoted = core.pool.flush_reusable()
    core._flush_evictions()

    # ---- enqueue the weight overwrite (async device work) ------------
    if group_layers is None:
        group_layers = max(1, int(env_float(
            "DYN_SWAP_GROUP_LAYERS", DEFAULT_GROUP_LAYERS, minimum=1.0)))
    from ...engine.engine import global_put

    L = core.cfg.model.num_layers
    groups = 0
    slab_bytes = 0
    params = core.params
    layered = (core.cfg.pp == 1)

    def rewrite(old_leaf, path):
        nonlocal groups, slab_bytes
        src = np.asarray(new_flat[path])
        if src.dtype != old_leaf.dtype:
            src = src.astype(old_leaf.dtype)
        slab_bytes += src.nbytes
        if layered and path and path[0] == "layers" \
                and old_leaf.shape[0] == L and L > group_layers:
            buf = old_leaf
            for g0 in range(0, L, group_layers):
                chunk = global_put(src[g0:g0 + group_layers],
                                   buf.sharding)
                buf = core.copy_stream.h2d_param_slab(buf, g0, chunk)
                groups += 1
            return buf
        # non-stacked leaves (embed/final_norm/lm_head), small stacks,
        # and pp>1 (layer axis sharded across stages — a host-side slab
        # would not line up with one stage's shard): whole-leaf put
        return global_put(src, old_leaf.sharding)

    import jax

    t_enq = time.monotonic()
    flat_old, treedef = jax.tree_util.tree_flatten_with_path(params)
    new_leaves = [
        rewrite(leaf,
                tuple(str(getattr(k, "key", k)) for k in path))
        for path, leaf in flat_old]
    new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)

    # ---- KV teardown on the host while the device streams weights ----
    if core.tiered is not None:
        core.tiered.clear()
    core.pool.flush_reusable()           # anything demotion re-parked
    core._evict_buf.clear()              # post-clear offloads are moot
    core._writethrough_buf.clear()
    core._writethrough_armed.clear()
    core._writethrough_pending.clear()
    with core._h2d_stage_lock:
        core._h2d_stage.clear()
        core._h2d_requested.clear()
    core._pending_prefix_hit.clear()
    core._spec_states.clear()
    core.prefix_hit_tokens = 0
    core.prefix_query_tokens = 0
    core.last_prefix_hit = 0
    core._last_final_tok = None          # chained off old-model logits

    # ---- barrier: weights resident before the first new-model token --
    # dynalint: ok(host-sync) swap cutover barrier — blocks once per
    # model swap (the wake path's h2d stream), never on a request
    jax.block_until_ready(jax.tree.leaves(new_params))
    # one flow for the whole weight stream: the barrier bounds it, so
    # bytes/seconds is the swap's real h2d rate
    from ...obs.flows import record_flow
    record_flow("swap_slab", slab_bytes, time.monotonic() - t_enq)
    core.params = new_params
    core.cfg = dataclasses.replace(
        core.cfg, params_path=getattr(new_cfg, "params_path", None),
        preset=getattr(new_cfg, "preset", None))
    seconds = time.monotonic() - t0
    from ...utils.prometheus import stage_metrics

    stage_metrics().model_swaps.inc("swap")
    log.info("hot-swap to %s: %.2fs, %d layer-group scatters, %d KV "
             "blocks demoted (0 new compiled programs)",
             core.cfg.params_path, seconds, groups, demoted)
    return SwapOutcome("swap", seconds, core.cfg.params_path,
                       groups=groups, demoted_blocks=demoted)
