"""Model mobility plane: weight prefetch + in-place hot-swap.

PRESERVE-style (arXiv 2501.08192) seconds-scale model wake: candidate
models' weights prefetch into a pinned host-RAM cache while the current
model serves, and a swap command turns a draining worker into a serving
replica of a sibling model without process restart — and, when the
sibling shares the layer-class shape signature, without a single new
XLA compile (``dyn_compiled_programs`` stays flat across the swap).

- :mod:`.weightcache` — pinned host-RAM LRU over ``DYN_WEIGHT_CACHE_BYTES``
- :mod:`.swap`        — shape-signature gate + engine hot-swap
- :mod:`.agent`       — worker-side store watcher executing swap commands
- :mod:`.keys`        — the ``mobility/`` keyspace family's helpers
"""

from .agent import EngineRef, MobilityAgent
from .keys import (mobility_prefetch_key, mobility_prefix,
                   mobility_swap_key, mobility_wake_key,
                   mobility_wake_prefix)
from .swap import SwapError, SwapOutcome, hot_swap, swap_signature
from .weightcache import WeightCache

__all__ = [
    "EngineRef", "MobilityAgent", "WeightCache", "SwapError",
    "SwapOutcome", "hot_swap", "swap_signature",
    "mobility_prefetch_key", "mobility_prefix", "mobility_swap_key",
    "mobility_wake_key", "mobility_wake_prefix",
]
