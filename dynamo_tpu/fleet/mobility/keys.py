"""Key helpers for the ``mobility/`` keyspace family.

Three record kinds, all JSON values (see ``docs/keyspace.md``):

- ``mobility/{ns}/prefetch/{component}`` — arbiter/operator hint: the
  list of candidate sibling models a component's workers should stage
  into their host weight cache while serving. Written by the fleet plane
  each arbitration tick (swap-group siblings) and by
  ``ctl fleet add --prewarm``; read (watched) by the worker's
  :class:`~dynamo_tpu.fleet.mobility.agent.MobilityAgent`.
- ``mobility/{ns}/swap/{component}`` — swap command (the SIGUSR1-style
  control message): tells one worker of ``component`` to drain and
  hot-swap into the named sibling model. The executing worker deletes
  the key as its claim; a rare double-claim only over-swaps by one
  worker, which the next planner tick corrects.
- ``mobility/{ns}/wake/{model}`` — last wake record for a model:
  ``{"path": "swap"|"cold", "seconds": float, "at": ts, "worker": id}``.
  Read by ``GET /v1/models``, dyntop and the soak wake lane.
"""

from __future__ import annotations

PREFIX = "mobility/"


def mobility_prefix(namespace: str) -> str:
    """Every mobility record of one namespace — the agent's single watch
    (filtering live means a post-swap component change needs no re-arm)."""
    return f"{PREFIX}{namespace}/"


def mobility_prefetch_key(namespace: str, component: str) -> str:
    return f"{PREFIX}{namespace}/prefetch/{component}"


def mobility_swap_key(namespace: str, component: str) -> str:
    return f"{PREFIX}{namespace}/swap/{component}"


def mobility_wake_key(namespace: str, model: str) -> str:
    return f"{PREFIX}{namespace}/wake/{model}"


def mobility_wake_prefix(namespace: str) -> str:
    return f"{PREFIX}{namespace}/wake/"
