"""Worker-side mobility actor: watch ``mobility/`` hints, execute swaps.

One :class:`MobilityAgent` per (simple-path) worker process. It arms a
single store watch over the namespace's ``mobility/`` prefix and reacts
to the two record kinds addressed at its CURRENT component:

- **prefetch hints** — stage the listed sibling checkpoints into the
  host :class:`~.weightcache.WeightCache` (background thread; the
  incumbent keeps serving);
- **swap commands** — claim the command by deleting its key, drain
  (``prepare_drain`` + bounded wait for in-flight streams), hand the
  cached host tree to the engine thread
  (:meth:`~dynamo_tpu.engine.engine.JaxEngine.swap_weights`), then
  re-register under the new model's component via the host-supplied
  ``reregister`` callback. A typed :class:`~.swap.SwapError` (shape
  mismatch, drain timeout, missing weights) falls back to a counted
  full reload through the ``cold_reload`` callback — never a hang.

The agent publishes the wake record (``mobility/{ns}/wake/{model}``)
and the ``dyn_model_wake_seconds``/``dyn_model_swaps_total`` series;
wake latency is measured from command receipt to serving registration,
which is the number the arbiter's swap preference is buying down.

Scope gate: hot-swap is only wired for the simple serving path — no
disagg, no cluster KV attach, no multi-host lockstep (the worker CLI
enforces this at construction; those paths keep the plain cold-spawn
wake).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Any, Awaitable, Callable, Optional

from ...utils.knobs import env_float
from .keys import (mobility_prefetch_key, mobility_prefix,
                   mobility_swap_key, mobility_wake_key)
from .swap import SwapError, SwapOutcome
from .weightcache import WeightCache

log = logging.getLogger("dynamo_tpu.mobility")

#: geometry fields copied from the incumbent config when building the
#: swap candidate's config — the compiled bucket programs were derived
#: from these, so the sibling must be evaluated against the SAME grid
#: (a matching model class then signature-matches and reuses programs)
GEOMETRY_FIELDS = (
    "tp", "sp", "ep", "pp", "page_size", "max_batch", "max_context",
    "prefill_chunk", "num_pages", "decode_steps", "prefill_lanes",
    "attn_impl", "spec", "spec_k", "spec_draft",
    "enable_prefix_reuse", "host_cache_blocks", "disk_cache_blocks",
    "disk_cache_path", "cluster_writethrough",
    "kvpage_budget", "kvpage_seg_pages", "kvpage_prefetch",
    "kvpage_max_context",
)


class EngineRef:
    """Serving-path indirection: handlers stream through ``.engine`` so
    a cold-reload fallback can rebind the live engine object without
    re-plumbing every closure that captured it."""

    def __init__(self, engine: Any):
        self.engine = engine

    def generate(self, request, context):
        return self.engine.generate(request, context)

    def __getattr__(self, name):
        return getattr(self.engine, name)


class MobilityAgent:
    """See module docstring. ``reregister(payload)`` re-grants the lease
    and re-serves endpoints under the swapped-in model's component;
    ``cold_reload(new_cfg)`` builds a replacement engine for the typed
    fallback path (both supplied by the worker CLI; tests inject stubs).
    """

    def __init__(self, drt, namespace: str, component: str,
                 engine_ref: EngineRef,
                 reregister: Callable[[dict], Awaitable[None]],
                 cold_reload: Optional[
                     Callable[[Any], Awaitable[Any]]] = None,
                 cache: Optional[WeightCache] = None,
                 model_name: str = "",
                 cfg_builder: Optional[Callable[[str, str], Any]] = None):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.engine_ref = engine_ref
        self.reregister = reregister
        self.cold_reload = cold_reload
        self.cache = cache or WeightCache()
        self.model_name = model_name
        self._cfg_builder = cfg_builder or self._default_cfg
        self._lock = asyncio.Lock()
        self._tasks: list = []
        self.swaps = 0            # completed wakes (either path)

    # ------------------------------------------------------------------
    def _default_cfg(self, model_name: str, model_path: str):
        """Candidate engine config for a sibling checkpoint: its own
        model config on the INCUMBENT's geometry (see GEOMETRY_FIELDS)."""
        from ...engine.engine import JaxEngineConfig
        from ...llm.model_card import ModelDeploymentCard

        old = self.engine_ref.engine.core.cfg
        card = ModelDeploymentCard.resolve(model_path, model_name or None)
        card.kv_block_size = old.page_size
        cand = JaxEngineConfig.from_card(card, tensor_parallel=old.tp)
        return dataclasses.replace(
            cand, **{f: getattr(old, f) for f in GEOMETRY_FIELDS})

    # ------------------------------------------------------------------
    async def start(self) -> "MobilityAgent":
        prefix = mobility_prefix(self.namespace)

        async def on_event(key: str, value: Optional[bytes],
                           deleted: bool) -> None:
            self._dispatch(key, value, deleted)

        snapshot = await self.drt.store.watch_prefix(prefix, on_event)
        for key, value in snapshot:
            self._dispatch(key, value, False)
        return self

    def _dispatch(self, key: str, value: Optional[bytes],
                  deleted: bool) -> None:
        """Route one watch event. The comparison is against the CURRENT
        component, so after a swap the same watch follows the worker's
        new identity with no re-arm."""
        if deleted or not value:
            return
        if key == mobility_prefetch_key(self.namespace, self.component):
            self._apply_prefetch(value)
        elif key == mobility_swap_key(self.namespace, self.component):
            try:
                payload = json.loads(value.decode())
            except (ValueError, json.JSONDecodeError):
                log.warning("ignoring malformed swap command %s", key)
                return
            self._tasks = [t for t in self._tasks if not t.done()]
            self._tasks.append(
                asyncio.create_task(self._execute(payload)))

    def _apply_prefetch(self, value: bytes) -> None:
        try:
            models = json.loads(value.decode()).get("models") or []
        except (ValueError, json.JSONDecodeError):
            log.warning("ignoring malformed prefetch hint for %s",
                        self.component)
            return
        for m in models:
            path = m.get("model_path")
            if not path or path in self.cache:
                continue
            try:
                cfg = self._cfg_builder(m.get("model", ""), path)
            except Exception:  # noqa: BLE001 - a bad hint must not kill
                log.warning("prefetch hint for %s unresolvable", path,
                            exc_info=True)
                continue
            if self.cache.prefetch(path, cfg.model):
                log.info("prefetching sibling weights: %s", path)

    # ------------------------------------------------------------------
    async def _wait_drained(self) -> bool:
        """Bounded post-``prepare_drain`` wait for in-flight streams and
        engine work to finish. DYN_SWAP_DRAIN_TIMEOUT caps it — a wedged
        drain becomes the typed reload fallback, never a hang."""
        timeout = env_float("DYN_SWAP_DRAIN_TIMEOUT", 120.0, minimum=0.0)
        deadline = time.monotonic() + timeout
        core = getattr(self.engine_ref.engine, "core", None)
        while time.monotonic() < deadline:
            busy = bool(self.drt._active) \
                or (core is not None and core.has_work)
            if not busy:
                return True
            await asyncio.sleep(0.05)
        return False

    async def _execute(self, payload: dict) -> None:
        async with self._lock:
            try:
                await self._execute_locked(payload)
            except Exception:  # noqa: BLE001 - agent must survive a swap
                # failure and keep serving whatever identity it holds
                from ...utils.prometheus import stage_metrics

                stage_metrics().model_swaps.inc("error")
                log.exception("swap command failed; worker keeps its "
                              "current model")

    async def _execute_locked(self, payload: dict) -> None:
        from ...utils.prometheus import stage_metrics

        model = payload.get("model") or ""
        model_path = payload.get("model_path")
        command_key = mobility_swap_key(self.namespace, self.component)
        # claim-by-delete: the first worker to erase the command owns it
        # (a rare double-claim over-swaps by one worker; the planner's
        # next tick corrects the counts)
        await self.drt.store.delete(command_key)
        t0 = time.monotonic()
        new_cfg = self._cfg_builder(model, model_path)
        # the source tree must survive the swap window even under cache
        # pressure from a concurrent prefetch
        host = self.cache.get(model_path) if model_path else None
        if host is None and model_path:
            host = await asyncio.to_thread(
                self.cache.load_now, model_path, new_cfg.model)
        if model_path:
            self.cache.pin(model_path)
        try:
            await self.drt.prepare_drain()
            drained = await self._wait_drained()
            outcome: Optional[SwapOutcome] = None
            try:
                if host is None:
                    raise SwapError("weights_unavailable",
                                    model_path or "no model_path")
                if not drained:
                    raise SwapError("not_drained", "drain timeout")
                if not hasattr(self.engine_ref.engine, "swap_weights"):
                    raise SwapError("unsupported",
                                    "engine has no swap path")
                outcome = await self.engine_ref.engine.swap_weights(
                    host, new_cfg)
            except SwapError as e:
                stage_metrics().model_swaps.inc(e.reason)
                if self.cold_reload is None:
                    raise
                log.warning("hot-swap to %s refused (%s); full reload",
                            model, e)
                self.engine_ref.engine = await self.cold_reload(new_cfg)
                stage_metrics().model_swaps.inc("reload")
                outcome = SwapOutcome("cold", time.monotonic() - t0,
                                      model_path)
        finally:
            if model_path:
                self.cache.unpin(model_path)
        # serving registration under the new identity completes the wake
        await self.reregister(payload)
        old = self.component
        self.component = payload.get("component") or f"backend-{model}"
        self.model_name = model
        self.swaps += 1
        seconds = time.monotonic() - t0
        stage_metrics().model_wake_seconds.observe(outcome.path,
                                                   value=seconds)
        await self.drt.store.put(
            mobility_wake_key(self.namespace, model),
            json.dumps({"path": outcome.path,
                        "seconds": round(seconds, 3),
                        "at": time.time(),
                        "worker": f"{self.drt.worker_id:x}"}).encode())
        log.info("model wake %s -> %s via %s in %.2fs", old,
                 self.component, outcome.path, seconds)
