"""Pinned host-RAM weight cache: the prefetch half of model mobility.

One cache per worker process. Candidate sibling checkpoints load off the
serving path (a single daemon thread, safetensors mmap-fed through
:func:`~dynamo_tpu.engine.loader.load_llama_params_host`) into host numpy
trees, LRU-bounded by ``DYN_WEIGHT_CACHE_BYTES``. A hot-swap then pays
only the h2d stream — PRESERVE's observation that prefetching weights
while the incumbent model serves hides nearly all of the load latency.

Pinned entries (the incumbent model, an in-progress swap's source) are
excluded from LRU eviction, mirroring the KV tier's pinning contract.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...utils.knobs import env_float

log = logging.getLogger("dynamo_tpu.mobility")

#: default cache budget: two 7B-class bf16 checkpoints' worth
DEFAULT_CACHE_BYTES = 32 << 30


def tree_nbytes(tree: Any) -> int:
    """Total host bytes of a numpy param tree."""
    import jax

    return int(sum(np.asarray(a).nbytes for a in jax.tree.leaves(tree)))


class WeightCache:
    """LRU host-RAM cache of model-path -> host param trees.

    Thread-safe: the prefetch thread inserts while the engine thread (or
    the asyncio swap agent) reads. ``loader`` is injected for tests; the
    default reads safetensors via :func:`load_llama_params_host`.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 loader: Optional[Callable[[str, Any], Any]] = None):
        if capacity_bytes is None:
            capacity_bytes = int(env_float(
                "DYN_WEIGHT_CACHE_BYTES", float(DEFAULT_CACHE_BYTES),
                minimum=0.0))
        self.capacity_bytes = capacity_bytes
        self._loader = loader or self._default_loader
        # path -> (host tree, nbytes), LRU order (oldest first)
        self._entries: "collections.OrderedDict[str, Tuple[Any, int]]" = \
            collections.OrderedDict()
        self._pinned: set = set()
        self._lock = threading.RLock()
        self._queue: "collections.deque[Tuple[str, Any]]" = \
            collections.deque()
        self._queued: set = set()
        self._wake = threading.Event()
        self._running = True
        self._thread: Optional[threading.Thread] = None
        self.loads = 0
        self.load_errors = 0

    @staticmethod
    def _default_loader(path: str, cfg: Any) -> Any:
        from ...engine.loader import load_llama_params_host

        return load_llama_params_host(path, cfg)

    # ------------------------------------------------------------------
    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(n for _, n in self._entries.values())

    def __contains__(self, path: str) -> bool:
        with self._lock:
            return path in self._entries

    def paths(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def get(self, path: str) -> Optional[Any]:
        """The cached host tree for ``path`` (LRU-touched), or None."""
        with self._lock:
            got = self._entries.get(path)
            if got is None:
                return None
            self._entries.move_to_end(path)
            return got[0]

    def pin(self, path: str) -> None:
        with self._lock:
            self._pinned.add(path)

    def unpin(self, path: str) -> None:
        with self._lock:
            self._pinned.discard(path)

    def drop(self, path: str) -> None:
        with self._lock:
            self._entries.pop(path, None)
            self._pinned.discard(path)
            self._set_gauges_locked()

    # ------------------------------------------------------------------
    def put(self, path: str, tree: Any) -> bool:
        """Insert a loaded tree, evicting unpinned LRU entries to fit.
        Returns False (tree dropped) if it cannot fit even after evicting
        everything unpinned — an over-budget checkpoint must not evict a
        pinned incumbent."""
        nbytes = tree_nbytes(tree)
        with self._lock:
            self._entries.pop(path, None)
            while (self.resident_bytes + nbytes > self.capacity_bytes
                   and self._evict_one_locked()):
                pass
            if self.resident_bytes + nbytes > self.capacity_bytes:
                log.warning("weight cache cannot fit %s (%.1f GiB > "
                            "budget); dropping", path, nbytes / 2**30)
                self._set_gauges_locked()
                return False
            self._entries[path] = (tree, nbytes)
            self._set_gauges_locked()
            return True

    def _evict_one_locked(self) -> bool:
        for path in self._entries:          # LRU -> MRU
            if path not in self._pinned:
                self._entries.pop(path)
                return True
        return False

    def _set_gauges_locked(self) -> None:
        from ...utils.prometheus import stage_metrics

        pinned = sum(n for p, (_, n) in self._entries.items()
                     if p in self._pinned)
        total = sum(n for _, n in self._entries.values())
        g = stage_metrics().weight_cache_bytes
        g.set("pinned", value=float(pinned))
        g.set("unpinned", value=float(total - pinned))

    # ------------------------------------------------------------------
    # background prefetch (the PRESERVE overlap)
    # ------------------------------------------------------------------
    def prefetch(self, path: str, cfg: Any) -> bool:
        """Queue a background load of ``path`` (idempotent while resident
        or already queued). Returns True if a load was queued."""
        with self._lock:
            if path in self._entries or path in self._queued:
                return False
            self._queued.add(path)
            self._queue.append((path, cfg))
        self._ensure_thread()
        self._wake.set()
        return True

    def load_now(self, path: str, cfg: Any) -> Optional[Any]:
        """Synchronous load-through (the swap fallback when the prefetch
        has not landed yet). Returns the host tree, or None on failure."""
        got = self.get(path)
        if got is not None:
            return got
        t0 = time.perf_counter()
        try:
            tree = self._loader(path, cfg)
        except Exception:  # noqa: BLE001 - a bad checkpoint must not raise
            log.exception("weight load failed for %s", path)
            self.load_errors += 1
            return None
        self.loads += 1
        # the checkpoint read is a disk->host flow: weight prefetch shows
        # up on the same link telemetry every other byte movement uses
        from ...obs.flows import record_flow
        record_flow("weight_prefetch", tree_nbytes(tree),
                    time.perf_counter() - t0)
        self.put(path, tree)
        return tree

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._prefetch_loop, name="weight-prefetch",
                daemon=True)
            self._thread.start()

    def _prefetch_loop(self) -> None:
        while self._running:
            try:
                path, cfg = self._queue.popleft()
            except IndexError:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            try:
                if path not in self:
                    self.load_now(path, cfg)
            finally:
                with self._lock:
                    self._queued.discard(path)

    def close(self) -> None:
        self._running = False
        self._wake.set()
        with self._lock:
            self._entries.clear()
            self._pinned.clear()
            self._queue.clear()
            self._queued.clear()
            self._set_gauges_locked()
